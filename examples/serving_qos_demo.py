"""Surge Gate demo — serve a tiny RAG store behind the serving QoS
layer, hammer it, and show the gate's behavior.

Tier-1 runs ``python -m pathway_tpu.analysis examples/serving_qos_demo.py``
over this file (build-only: the graph is declared, the engine never
starts) — the ``qos=`` below is also what keeps the Graph Doctor's
``serving-admission`` rule quiet. Executed directly (JAX_PLATFORMS=cpu
safe), it starts the VectorStoreServer threaded with a deliberately
tiny gate, fires a concurrent burst plus one request with an
already-hopeless deadline budget, prints the resulting status mix and
the gate metrics (batch sizes, queue waits, sheds), and finishes with a
graceful drain. See README "Serving QoS" for the knobs.
"""

import json
import socket
import threading
import time
import urllib.error
import urllib.request
from collections import Counter

import pathway_tpu as pw
from pathway_tpu.serving import QoSConfig, drain_all
from pathway_tpu.xpacks.llm.embedders import SentenceTransformerEmbedder
from pathway_tpu.xpacks.llm.vector_store import VectorStoreServer


class DocSchema(pw.Schema):
    data: str


QOS = QoSConfig(
    max_batch_size=8,
    max_wait_ms=10,
    max_queue=16,
    max_dispatched=8,
    default_deadline_ms=20_000,
)


def build_server() -> VectorStoreServer:
    # toy dims: this demo is about the gate, not embedding quality
    embedder = SentenceTransformerEmbedder(
        dim=32, depth=1, heads=2, max_len=64, batch_size=64
    )
    docs = pw.debug.table_from_rows(
        DocSchema,
        [(f"document {i} about topic {i % 4}",) for i in range(16)],
    )
    return VectorStoreServer(docs, embedder=embedder)


def _post(port: int, payload: dict, deadline_ms: float | None = None):
    headers = {"Content-Type": "application/json"}
    if deadline_ms is not None:
        headers["x-pathway-deadline-ms"] = str(deadline_ms)
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/retrieve",
        data=json.dumps(payload).encode(),
        headers=headers,
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, {"retry_after": e.headers.get("Retry-After")}
    except Exception as e:
        return type(e).__name__, None


def main() -> None:
    import importlib

    # the module, not the re-exported `run` function: the build-only flag
    # lives in the module namespace (same dance as analysis/__main__.py)
    _run = importlib.import_module("pathway_tpu.internals.run")

    server = build_server()
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    server.run_server(host="127.0.0.1", port=port, threaded=True, qos=QOS)
    if _run._build_only:
        return  # analysis gate: graph declared, nothing to serve

    deadline = time.time() + 120
    while time.time() < deadline:
        status, body = _post(port, {"query": "topic 2", "k": 2})
        if status == 200 and body:
            break
        time.sleep(0.5)  # server up but store not yet indexed
    else:
        print("server did not come up in time")
        return
    print(f"warm: top hit for 'topic 2' -> {body[0]['text']!r}")

    # concurrent burst: the micro-batcher coalesces these into a few
    # bucketed releases instead of one engine tick per request
    statuses: Counter = Counter()

    def worker(i: int) -> None:
        status, _ = _post(port, {"query": f"topic {i % 4}", "k": 2})
        statuses[status] += 1

    threads = [
        threading.Thread(target=worker, args=(i,)) for i in range(24)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # one request whose deadline budget is already spent: dropped
    # server-side with 504, never dispatched into the engine
    status, _ = _post(port, {"query": "too late", "k": 2}, deadline_ms=0)
    statuses[status] += 1
    print(f"burst of 24 + 1 hopeless deadline -> {dict(statuses)}")

    from pathway_tpu.observability import REGISTRY

    lines = [
        ln
        for ln in REGISTRY.render().splitlines()
        if ln.startswith("pathway_serving_")
        and ("_count" in ln or "_total" in ln or "depth" in ln)
    ]
    print("gate metrics:")
    for ln in lines:
        print(f"  {ln}")

    print("draining (stop admitting, flush, answer, close) ...")
    idle = drain_all(grace_s=15)
    print(f"drain complete, all gates idle: {idle}")
    try:
        pw.internals.parse_graph.G.runtime.stop()
    except Exception:
        pass


if __name__ == "__main__":
    main()
