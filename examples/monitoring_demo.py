"""Flight Recorder demo — a monitored streaming run, scraped headlessly.

Tier-1 runs ``python -m pathway_tpu.analysis examples/monitoring_demo.py``
over this file (build-only, no execution). Executed directly, it runs a
small windowed aggregation with the monitoring HTTP server on, scrapes
``/metrics`` and ``/debug/graph`` from inside the process, and prints
the serving-path numbers a Prometheus dashboard would chart — including
a p50/p95 estimated from the per-operator tick-time histogram. See
README "Observability" for the full metric inventory.
"""

import pathway_tpu as pw


class EventSubject(pw.io.python.ConnectorSubject):
    def run(self) -> None:
        for t in range(200):
            self.next(route="/v1/retrieve" if t % 3 else "/v1/stats",
                      event_time=t)
        self.close()


class EventSchema(pw.Schema):
    route: str
    event_time: int


events = pw.io.python.read(EventSubject(), schema=EventSchema)

per_route = events.windowby(
    pw.this.event_time,
    window=pw.temporal.tumbling(duration=50),
    instance=pw.this.route,
    behavior=pw.temporal.common_behavior(cutoff=100),
).reduce(
    route=pw.this._pw_instance,
    hits=pw.reducers.count(),
)

pw.io.null.write(per_route)


def _scrape_and_report() -> None:
    import json
    import urllib.request

    from pathway_tpu.internals.parse_graph import G
    from pathway_tpu.observability import REGISTRY, validate_exposition

    if G.last_runtime is None:
        return  # build-only mode (the analysis gate): nothing ran
    server = G.last_runtime.http_server
    if server is None:
        print("monitoring server did not start")
        return
    host, port = server.server_address[:2]
    base = f"http://{'127.0.0.1' if host == '0.0.0.0' else host}:{port}"
    body = urllib.request.urlopen(f"{base}/metrics", timeout=5).read()
    violations = validate_exposition(body.decode())
    print(f"scraped {len(body)} bytes from {base}/metrics "
          f"({len(violations)} exposition violations)")
    graph = json.loads(
        urllib.request.urlopen(f"{base}/debug/graph", timeout=5).read()
    )
    busiest = max(graph, key=lambda r: r["ns"])
    print(f"busiest operator: {busiest['name']} "
          f"({busiest['rows']} rows, {busiest['ns'] / 1e6:.2f} ms total)")
    hist = REGISTRY.get("pathway_operator_tick_seconds")
    if hist is not None and hist._children:
        slowest = max(
            hist._children.items(), key=lambda kv: kv[1].quantile(0.95)
        )
        print(f"tick time p50/p95 for {slowest[0][0]}: "
              f"{slowest[1].quantile(0.5) * 1e3:.3f} ms / "
              f"{slowest[1].quantile(0.95) * 1e3:.3f} ms")
    server.shutdown()


if __name__ == "__main__":
    pw.run(monitoring_level="none", with_http_server=True)
    _scrape_and_report()
