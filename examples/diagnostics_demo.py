"""A deliberately sick pipeline — the Graph Doctor's demo patient.

Every block below trips a different rule, so

    python -m pathway_tpu.analysis --fail-on never examples/diagnostics_demo.py

shows the full diagnostic surface (dead-node, dead-column,
unbounded-state, universe-safety, shard-exchange, shard-nondeterminism,
shard-reducer, graph-stats) with declaration-site provenance. Do not use
it as a template for real pipelines.
"""

import random

import pathway_tpu as pw


class EventSubject(pw.io.python.ConnectorSubject):
    def run(self) -> None:
        self.close()


class EventSchema(pw.Schema):
    user: str
    amount: int


events = pw.io.python.read(EventSubject(), schema=EventSchema)


@pw.udf(deterministic=False)
def jitter(x: int) -> float:
    return x + random.random()


# dead-column: `unused` is computed and never read again
enriched = events.select(
    pw.this.user,
    amount=jitter(pw.this.amount),  # shard-nondeterminism: feeds a groupby
    unused=pw.this.amount * 2,
)

# unbounded-state: streaming groupby with no window/behavior;
# shard-exchange: grouping forces a row exchange under sharding;
# shard-reducer: tuple() without sort_by is arrival-order dependent
totals = enriched.groupby(pw.this.user).reduce(
    pw.this.user,
    total=pw.reducers.sum(pw.this.amount),
    history=pw.reducers.tuple(pw.this.amount),
)

# universe-safety: restricting to a key set with no declared relation
labels = pw.debug.table_from_markdown(
    """
    label
    vip
    """
)
labeled = labels.with_universe_of(totals)

# dead-node: declared, never written or consumed
orphan = events.select(doubled=pw.this.amount * 2)

pw.io.null.write(totals)
pw.io.null.write(labeled)

if __name__ == "__main__":
    pw.run(diagnostics="warn")
