"""Trace Weaver demo — serve a tiny RAG store, query it, dump the trace.

Tier-1 runs ``python -m pathway_tpu.analysis examples/tracing_demo.py``
over this file (build-only: the graph is declared, the engine never
starts). Executed directly (JAX_PLATFORMS=cpu-safe), it starts the
VectorStoreServer threaded, sends one ``/v1/retrieve`` query carrying a
W3C ``traceparent`` header, and then prints the stitched span tree —
root (HTTP) → engine tick → operator → embed → KNN — plus where the
Chrome trace-event JSON landed (drag it into ui.perfetto.dev). See
README "Observability → Tracing" for the knobs.
"""

import json
import os
import socket
import time
import urllib.request

import pathway_tpu as pw
from pathway_tpu.xpacks.llm.embedders import SentenceTransformerEmbedder
from pathway_tpu.xpacks.llm.vector_store import VectorStoreServer


class DocSchema(pw.Schema):
    data: str


def build_server() -> VectorStoreServer:
    # toy dims: this demo is about the trace, not embedding quality
    embedder = SentenceTransformerEmbedder(
        dim=32, depth=1, heads=2, max_len=64, batch_size=32
    )
    docs = pw.debug.table_from_rows(
        DocSchema,
        [(f"document {i} about topic {i % 4}",) for i in range(8)],
    )
    return VectorStoreServer(docs, embedder=embedder)


def main() -> None:
    import importlib

    from pathway_tpu.observability.tracing import get_tracer

    # the module, not the re-exported `run` function: the build-only flag
    # lives in the module namespace (same dance as analysis/__main__.py)
    _run = importlib.import_module("pathway_tpu.internals.run")

    server = build_server()
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    # threaded=True runs pw.run in a daemon thread; under the analysis
    # gate that pw.run is a no-op, so only the declaration above matters
    server.run_server(host="127.0.0.1", port=port, threaded=True)
    if _run._build_only:
        return  # analysis gate: graph declared, nothing to serve

    traceparent = f"00-{os.urandom(16).hex()}-{os.urandom(8).hex()}-01"
    result = None
    deadline = time.time() + 120
    while time.time() < deadline:
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/retrieve",
                data=json.dumps({"query": "topic 2", "k": 3}).encode(),
                headers={
                    "Content-Type": "application/json",
                    "traceparent": traceparent,
                },
            )
            with urllib.request.urlopen(req, timeout=30) as resp:
                result = json.loads(resp.read().decode())
                echoed = resp.headers.get("traceparent")
            if result:
                break
        except Exception:
            pass
        time.sleep(0.5)  # server up but store not yet indexed

    if not result:
        print("server did not answer in time")
        return
    trace_id = traceparent.split("-")[1]
    print(f"retrieved {len(result)} docs; response traceparent: {echoed}")
    print(f"trace {trace_id}:")
    print(get_tracer().format_tree(trace_id))
    out_path = "/tmp/pathway_trace_demo.json"
    pw.debug.trace(path=out_path)
    print(f"full Chrome trace-event JSON written to {out_path} "
          "(load it at ui.perfetto.dev)")
    try:
        pw.internals.parse_graph.G.runtime.stop()
    except Exception:
        pass


if __name__ == "__main__":
    main()
