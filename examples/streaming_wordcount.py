"""Windowed streaming wordcount — a Graph-Doctor-clean pipeline.

Tier-1 runs ``python -m pathway_tpu.analysis examples/streaming_wordcount.py``
over this file (tests/test_graph_doctor.py): the aggregation is windowed
with a temporal behavior, so closed windows free their state and the
doctor reports no error-severity findings.
"""

import pathway_tpu as pw


class WordSubject(pw.io.python.ConnectorSubject):
    def run(self) -> None:
        for t, word in enumerate(["tpu", "dataflow", "tpu", "pathway"]):
            self.next(word=word, event_time=t)
        self.close()


class WordSchema(pw.Schema):
    word: str
    event_time: int


words = pw.io.python.read(WordSubject(), schema=WordSchema)

counts = words.windowby(
    pw.this.event_time,
    window=pw.temporal.tumbling(duration=10),
    instance=pw.this.word,
    behavior=pw.temporal.common_behavior(cutoff=30),
).reduce(
    word=pw.this._pw_instance,
    count=pw.reducers.count(),
)

pw.io.null.write(counts)

if __name__ == "__main__":
    pw.run(diagnostics="warn")
