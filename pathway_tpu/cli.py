"""`pathway-tpu` CLI — multi-process launcher + record/replay flags.

TPU-native counterpart of the reference CLI
(reference: python/pathway/cli.py — `pathway spawn` launches N OS
processes with PATHWAY_PROCESSES/PATHWAY_PROCESS_ID/PATHWAY_FIRST_PORT env
vars; `--record`/`--replay_mode` set PATHWAY_REPLAY_STORAGE /
PATHWAY_SNAPSHOT_ACCESS). On TPU pods the unit of scale-out is one JAX
process per host over the same mesh, so `spawn` sets the standard JAX
distributed env (coordinator address, process count/index) alongside the
pathway ones.
"""

from __future__ import annotations

import argparse
import os
import secrets
import subprocess
import sys


def _spawn(args, extra: list[str]) -> int:
    n = args.processes
    env_base = dict(os.environ)
    env_base["PATHWAY_PROCESSES"] = str(n)
    # per-job shared secret authenticating host-mesh frames (HMAC); see
    # parallel/host_exchange.py
    env_base.setdefault("PATHWAY_DCN_SECRET", secrets.token_hex(32))
    env_base["PATHWAY_THREADS"] = str(args.threads)
    env_base["PATHWAY_FIRST_PORT"] = str(args.first_port)
    # -t T workers = T engine key-shards over the device mesh (reference:
    # PATHWAY_THREADS timely workers per process, config.rs:88-121; here
    # engine/sharded.py execs). The XLA flag only widens the host-CPU
    # fallback pool — on a TPU host make_mesh picks the real chips.
    if args.threads > 1:
        env_base["PATHWAY_ENGINE_SHARDS"] = str(args.threads)
        flags = env_base.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env_base["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={args.threads}"
            ).strip()
    if args.record:
        env_base["PATHWAY_REPLAY_STORAGE"] = args.record_path
        env_base["PATHWAY_SNAPSHOT_ACCESS"] = "record"
    elif args.replay_mode:
        env_base["PATHWAY_REPLAY_STORAGE"] = args.record_path
        env_base["PATHWAY_SNAPSHOT_ACCESS"] = args.replay_mode
    if not extra:
        print("nothing to run", file=sys.stderr)
        return 2
    if extra and extra[0] == "--":
        extra = extra[1:]
    if getattr(args, "supervise", False):
        # Phoenix Mesh: run the group under the restart supervisor — a
        # dead rank tears the group down and the whole group respawns
        # from the latest group-committed snapshot generation, up to
        # PATHWAY_MESH_MAX_RESTARTS times (parallel/supervisor.py)
        from pathway_tpu.parallel.supervisor import GroupSupervisor

        env_base.setdefault(
            "JAX_COORDINATOR_ADDRESS", f"127.0.0.1:{args.first_port}"
        )
        env_base.setdefault("JAX_NUM_PROCESSES", str(n))

        def rank_env(pid: int) -> dict:
            return {"JAX_PROCESS_ID": str(pid)}

        sup = GroupSupervisor(
            extra,
            n,
            env=env_base,
            rank_env=rank_env,
            max_restarts=args.max_restarts,
        )
        return sup.run()
    procs = []
    for pid in range(n):
        env = dict(env_base)
        env["PATHWAY_PROCESS_ID"] = str(pid)
        # JAX multi-host convention: one process per host on a pod slice
        env.setdefault("JAX_COORDINATOR_ADDRESS", f"127.0.0.1:{args.first_port}")
        env.setdefault("JAX_NUM_PROCESSES", str(n))
        env["JAX_PROCESS_ID"] = str(pid)
        procs.append(subprocess.Popen(extra, env=env))
    code = 0
    for p in procs:
        code = p.wait() or code
    return code


def _spawn_from_env(args, extra: list[str]) -> int:
    """`spawn-from-env` — read the spawn arguments from PATHWAY_SPAWN_ARGS
    (reference: cli.py spawn-from-env, used by container entrypoints)."""
    spawn_args = os.environ.get("PATHWAY_SPAWN_ARGS", "").split()
    return main(["spawn", *spawn_args, "--", *extra] if extra else ["spawn", *spawn_args])


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="pathway-tpu")
    sub = parser.add_subparsers(dest="command", required=True)
    sp = sub.add_parser("spawn", help="launch a program over N processes")
    sp.add_argument("--processes", "-n", type=int, default=1)
    sp.add_argument("--threads", "-t", type=int, default=1)
    sp.add_argument("--first-port", type=int, default=10000)
    sp.add_argument(
        "--supervise",
        action="store_true",
        help="restart the whole group on rank failure (Phoenix Mesh), "
        "up to --max-restarts times",
    )
    sp.add_argument("--max-restarts", type=int, default=None)
    sp.add_argument("--record", action="store_true")
    sp.add_argument("--record-path", default="./record")
    sp.add_argument(
        "--replay-mode", dest="replay_mode", choices=["replay", "full"], default=None
    )
    sub.add_parser("spawn-from-env", help="spawn with args from PATHWAY_SPAWN_ARGS")
    args, extra = parser.parse_known_args(argv)
    if args.command == "spawn":
        return _spawn(args, extra)
    if args.command == "spawn-from-env":
        return _spawn_from_env(args, extra)
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
