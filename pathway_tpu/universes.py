"""pw.universes — universe relationship promises (reference:
python/pathway/universes.py). Metadata-only assertions letting the user
vouch for key-set relationships the engine cannot deduce; the microbatch
engine verifies alignment at run time, so these are advisory exactly as
in the reference's in-place semantics."""

from __future__ import annotations

from typing import Any


def promise_are_pairwise_disjoint(self: Any, *others: Any) -> None:
    """Assert the universes of all given tables are pairwise disjoint."""
    for other in others:
        self.promise_universes_are_disjoint(other)


def promise_are_equal(*tables: Any) -> None:
    """Assert all given tables share one universe (reference:
    universes.promise_are_equal)."""
    if not tables:
        return
    first = tables[0]
    for other in tables[1:]:
        other.promise_universe_is_equal_to(first)


def promise_is_subset_of(self: Any, *others: Any) -> None:
    """Assert self's universe is a subset of each other's."""
    for other in others:
        self.promise_universe_is_subset_of(other)
