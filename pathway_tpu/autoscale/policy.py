"""Flux Pilot policy — every scaling decision is a pure function of one
:class:`PlaneObservation` snapshot.

The controller (controller.py) reads the Fleet Lens rings and distills
them into an observation; :meth:`AutoscalePolicy.decide` maps that
observation to exactly one :class:`Decision`.  Nothing here touches a
clock, the environment, a sampler, or a journal — the no-flap /
no-down-under-burn properties are checkable by brute force over
synthetic observations (tests/test_autoscale.py).

Hysteresis is asymmetric by design:

* **Scale up** when the worst SLO burn has been above 1.0 continuously
  for ``up_window_s`` — or immediately when the predictor's forecast
  burn crosses 1.0 (capacity must be ready *before* the surge the
  forecast models, which is why the controller stretches the forecast
  horizon to cover the observed actuation cost).
* **Scale down** only when the plane has been drained — worst burn at
  or below ``low_water`` (strictly inside the up threshold, so the two
  bands never touch) — continuously for the much longer
  ``down_window_s``, and no forecast predicts a surge.  A scale-down
  NEVER fires while any SLO burn exceeds 1.0.
* A ``cooldown_s`` lock after every actuation (applied or rolled back)
  bounds the decision rate regardless of how the signals oscillate.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

HOLD = "hold"
UP = "up"
DOWN = "down"

_MIN_ENV = "PATHWAY_AUTOSCALE_MIN_RANKS"
_MAX_ENV = "PATHWAY_AUTOSCALE_MAX_RANKS"
_UP_WINDOW_ENV = "PATHWAY_AUTOSCALE_UP_WINDOW_S"
_DOWN_WINDOW_ENV = "PATHWAY_AUTOSCALE_DOWN_WINDOW_S"
_COOLDOWN_ENV = "PATHWAY_AUTOSCALE_COOLDOWN_S"
_LOW_WATER_ENV = "PATHWAY_AUTOSCALE_LOW_WATER"
_STEP_ENV = "PATHWAY_AUTOSCALE_STEP"
_HORIZON_ENV = "PATHWAY_AUTOSCALE_HORIZON_S"


def _env_float(env: dict, name: str, default: float) -> float:
    raw = env.get(name, "")
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def _env_int(env: dict, name: str, default: int) -> int:
    raw = env.get(name, "")
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        return default


@dataclass(frozen=True)
class AutoscaleConfig:
    """The ``PATHWAY_AUTOSCALE_*`` knob family, resolved once."""

    min_ranks: int = 1
    max_ranks: int = 4
    up_window_s: float = 15.0
    down_window_s: float = 120.0
    cooldown_s: float = 60.0
    #: drain threshold as a burn fraction — strictly below the 1.0 up
    #: threshold so the hysteresis band has width
    low_water: float = 0.5
    step: int = 1
    #: minimum forecast lead; the controller stretches it to cover the
    #: observed actuation cost so capacity lands before the surge
    horizon_s: float = 30.0

    @classmethod
    def from_env(cls, env: dict | None = None) -> "AutoscaleConfig":
        env = dict(os.environ) if env is None else env
        return cls(
            min_ranks=max(_env_int(env, _MIN_ENV, 1), 1),
            max_ranks=max(_env_int(env, _MAX_ENV, 4), 1),
            up_window_s=max(_env_float(env, _UP_WINDOW_ENV, 15.0), 0.0),
            down_window_s=max(_env_float(env, _DOWN_WINDOW_ENV, 120.0), 0.0),
            cooldown_s=max(_env_float(env, _COOLDOWN_ENV, 60.0), 0.0),
            low_water=min(
                max(_env_float(env, _LOW_WATER_ENV, 0.5), 0.0), 0.99
            ),
            step=max(_env_int(env, _STEP_ENV, 1), 1),
            horizon_s=max(_env_float(env, _HORIZON_ENV, 30.0), 0.0),
        )


@dataclass(frozen=True)
class PlaneObservation:
    """One instant of the plane as the policy sees it — the controller
    assembles it from the signal rings, its own duration markers, and
    the predictor.  ``max_burn`` is the worst burn rate across every
    declared ``PATHWAY_SLO_*`` target (None = no target has data yet:
    the policy holds, it never acts blind)."""

    mono: float
    ranks: int
    max_burn: float | None
    #: continuous seconds max_burn has been > 1.0 (0 when it is not)
    burn_high_for_s: float = 0.0
    #: continuous seconds max_burn has been <= low_water (0 otherwise)
    drained_for_s: float = 0.0
    #: forecast worst burn at the controller's horizon, if a predictor
    #: is armed
    predicted_burn: float | None = None
    cooldown_remaining_s: float = 0.0
    action_in_flight: bool = False
    #: EWMA of observed resize wall time (elastic feedback) — carried in
    #: the observation so decisions can be replayed from journal data
    actuation_cost_s: float = 0.0


@dataclass(frozen=True)
class Decision:
    action: str  # HOLD | UP | DOWN
    target_ranks: int
    reason: str

    @property
    def actionable(self) -> bool:
        return self.action != HOLD


class AutoscalePolicy:
    """Pure hysteresis controller.  ``decide`` never mutates state and
    consults nothing but the observation and the frozen config."""

    def __init__(self, config: AutoscaleConfig | None = None):
        self.config = config or AutoscaleConfig.from_env()

    def decide(self, obs: PlaneObservation) -> Decision:
        cfg = self.config
        ranks = int(obs.ranks)
        if obs.action_in_flight:
            return Decision(HOLD, ranks, "actuation in flight")
        if obs.cooldown_remaining_s > 0.0:
            return Decision(
                HOLD,
                ranks,
                f"cooldown ({obs.cooldown_remaining_s:.1f}s remaining)",
            )
        if cfg.min_ranks >= cfg.max_ranks:
            return Decision(
                HOLD, ranks, "min_ranks == max_ranks (pinned by config)"
            )
        if obs.max_burn is None:
            return Decision(HOLD, ranks, "no SLO burn data")

        sustained_up = (
            obs.max_burn > 1.0 and obs.burn_high_for_s >= cfg.up_window_s
        )
        predicted_up = (
            obs.predicted_burn is not None and obs.predicted_burn > 1.0
        )
        if sustained_up or predicted_up:
            if ranks >= cfg.max_ranks:
                return Decision(HOLD, ranks, "burning but at max_ranks")
            target = min(ranks + cfg.step, cfg.max_ranks)
            why = (
                f"burn {obs.max_burn:.2f} > 1.0 for "
                f"{obs.burn_high_for_s:.1f}s"
                if sustained_up
                else f"predicted burn {obs.predicted_burn:.2f} > 1.0"
            )
            return Decision(UP, target, why)

        # the hard guard: a scale-down is structurally impossible while
        # any SLO burn exceeds 1.0, whatever the duration markers claim
        drained = (
            obs.max_burn < 1.0
            and obs.max_burn <= cfg.low_water
            and obs.drained_for_s >= cfg.down_window_s
            and (
                obs.predicted_burn is None
                or obs.predicted_burn <= cfg.low_water
            )
        )
        if drained:
            if ranks <= cfg.min_ranks:
                return Decision(HOLD, ranks, "drained but at min_ranks")
            target = max(ranks - cfg.step, cfg.min_ranks)
            return Decision(
                DOWN,
                target,
                f"burn {obs.max_burn:.2f} <= low-water {cfg.low_water:g} "
                f"for {obs.drained_for_s:.1f}s",
            )
        return Decision(HOLD, ranks, "inside hysteresis band")
