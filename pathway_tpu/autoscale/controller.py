"""Flux Pilot controller — the actuation loop between Fleet Lens and
Shard Flux.

``step()`` distills the signal rings into one
:class:`~pathway_tpu.autoscale.policy.PlaneObservation`, asks the pure
policy for a decision, and — for an actionable one — drives exactly one
resize through the plane's actuator.  Actions are strictly serialized
(one in flight; the policy holds behind the in-flight flag), every
decision / actuation / failure lands in the incident journal
(``autoscale-decision`` / ``autoscale-applied`` /
``autoscale-rollback``, all persisted) so chaos benches assert scaling
windows FROM the journal, and the cost proxy
``pathway_autoscale_rank_seconds_total`` integrates ranks over time —
the number the autoscaler exists to beat static provisioning on.

Actuators map the decision onto the mechanisms PR 15 built:

* :class:`SupervisorActuator` — ``GroupSupervisor.resize(m, reshard=…)``
  for a supervised engine group; a reshard callback that raises rides
  the supervisor's ``resize-rollback`` path (old size respawns, budget
  untouched) and surfaces here as a failed actuation.
* :class:`ServingPlaneActuator` — ``DeltaStreamServer.reshard(m)`` then
  replica adoption (``ReplicaServer.adopt_shard_map``) then
  ``FailoverRouter.swap_shard_map`` at the commit barrier.
* :class:`CallbackActuator` — any ``fn(m)`` (tests, benches, embedders).

Resize cost is fed back: each actuation's wall time updates an EWMA
that (a) rides every observation (``actuation_cost_s``) and (b)
stretches the predictor horizon, so a plane whose transfers take 40 s
starts scaling 40 s earlier.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable

from pathway_tpu.autoscale.policy import (
    DOWN,
    HOLD,
    UP,
    AutoscaleConfig,
    AutoscalePolicy,
    Decision,
    PlaneObservation,
)
from pathway_tpu.autoscale.predictor import LoadForecaster
from pathway_tpu.observability.registry import REGISTRY, MetricsRegistry

_INTERVAL_ENV = "PATHWAY_AUTOSCALE_INTERVAL_MS"


class ActuationError(RuntimeError):
    """A resize the mechanism reported as failed/rolled back."""


# --- actuators --------------------------------------------------------------


class CallbackActuator:
    """``fn(m)`` performs the whole resize; raise to signal rollback."""

    def __init__(self, fn: Callable[[int], Any], label: str = "callback"):
        self._fn = fn
        self.label = label

    def resize(self, m: int) -> Any:
        return self._fn(m)


class SupervisorActuator:
    """Engine-group actuation via ``GroupSupervisor.resize``.

    ``reshard_for(old_n, new_n)`` returns the transfer callback for one
    resize (typically a closure over ``elastic.mesh.reshard_stores``
    with the old/new store roots), or None for log-replay resizes.  The
    supervisor applies the resize at its next poll; we block until the
    group either commits the new size or journals ``resize-rollback``,
    and surface the rollback as :class:`ActuationError` so the
    controller journals it and backs off."""

    def __init__(
        self,
        supervisor: Any,
        reshard_for: Callable[[int, int], Callable[[], Any] | None]
        | None = None,
        *,
        timeout_s: float = 120.0,
        poll_s: float = 0.05,
    ):
        self.supervisor = supervisor
        self.reshard_for = reshard_for
        self.timeout_s = float(timeout_s)
        self.poll_s = float(poll_s)
        self.label = "supervisor"

    def resize(self, m: int) -> Any:
        sup = self.supervisor
        old_n = int(sup.n)
        cb = self.reshard_for(old_n, int(m)) if self.reshard_for else None
        mark = len(sup.events)
        sup.resize(int(m), reshard=cb)
        deadline = time.monotonic() + self.timeout_s
        while time.monotonic() < deadline:
            for _ts, kind, detail in sup.events[mark:]:
                if kind == "resize-rollback":
                    raise ActuationError(f"supervisor rollback: {detail}")
                if kind == "group-resize":
                    return {"old": old_n, "new": int(sup.n)}
            time.sleep(self.poll_s)
        raise ActuationError(
            f"resize {old_n} -> {m} not applied within {self.timeout_s}s"
        )


class ServingPlaneActuator:
    """Serving-plane actuation: writer reshard → replica adoption →
    router shard-map swap, in commit order.  ``members_for(m)`` names
    the new shard map (one member list per shard) for the router;
    ``adopt(m)`` performs whatever replica-side adoption the embedder
    wires (spawning members, calling ``adopt_shard_map`` on survivors).
    A raise anywhere leaves the old router map in force — the writer's
    transition guard fences stale members either way."""

    def __init__(
        self,
        writer: Any,
        *,
        router: Any = None,
        members_for: Callable[[int], list] | None = None,
        adopt: Callable[[int], Any] | None = None,
    ):
        self.writer = writer
        self.router = router
        self.members_for = members_for
        self.adopt = adopt
        self.label = "serving"

    def resize(self, m: int) -> Any:
        res = self.writer.reshard(int(m))
        if self.adopt is not None:
            self.adopt(int(m))
        if self.router is not None and self.members_for is not None:
            self.router.swap_shard_map(self.members_for(int(m)))
        return res


# --- controller -------------------------------------------------------------


class AutoscaleController:
    """One plane's control loop.  Drive it with ``step()`` (benches,
    tests) or ``start()`` a thread on the configured cadence."""

    def __init__(
        self,
        actuator: Any,
        *,
        ranks: int,
        config: AutoscaleConfig | None = None,
        policy: AutoscalePolicy | None = None,
        predictor: LoadForecaster | None = None,
        sampler: Any = None,
        interval_s: float | None = None,
        registry: MetricsRegistry = REGISTRY,
    ):
        self.actuator = actuator
        self.config = config or (policy.config if policy else None) or (
            AutoscaleConfig.from_env()
        )
        self.policy = policy or AutoscalePolicy(self.config)
        self.predictor = predictor
        self._sampler = sampler
        self.ranks = int(ranks)
        if interval_s is None:
            try:
                interval_s = (
                    float(os.environ.get(_INTERVAL_ENV, "1000") or 1000)
                    / 1000.0
                )
            except ValueError:
                interval_s = 1.0
        self.interval_s = max(float(interval_s), 0.01)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._in_flight = False
        self._cooldown_until: float | None = None
        self._high_since: float | None = None
        self._drained_since: float | None = None
        self._last_step_mono: float | None = None
        self._last_action: tuple[str, float] | None = None  # (dir, mono)
        self._act_cost_s = 0.0
        self._act_costs = 0
        self.last_decision: Decision | None = None
        self.resizes = 0
        self.registry = registry
        self._m_rank_seconds = registry.counter(
            "pathway_autoscale_rank_seconds_total",
            "rank-seconds provisioned under the autoscaler — the cost "
            "proxy the SCALE bench compares against static provisioning",
        )
        self._m_decisions = registry.counter(
            "pathway_autoscale_decisions_total",
            "policy decisions, by action (hold / up / down)",
            labelnames=("action",),
        )
        self._m_flaps = registry.counter(
            "pathway_autoscale_flaps_total",
            "direction reversals within two cooldown windows of the "
            "previous action — the oscillation the hysteresis bands "
            "exist to prevent",
        )
        self._m_cooldown_holds = registry.counter(
            "pathway_autoscale_cooldown_holds_total",
            "actionable pressure held back by the cooldown lock",
        )
        self._m_rollbacks = registry.counter(
            "pathway_autoscale_rollbacks_total",
            "actuations that failed and rolled back to the old size",
        )
        self._m_ranks = registry.gauge(
            "pathway_autoscale_ranks",
            "current rank count as the controller believes it",
        )
        self._m_ranks.set_function(lambda: self.ranks)

    # --- observation ------------------------------------------------------

    def _burn_now(self) -> float | None:
        sampler = self._sampler
        if sampler is None:
            from pathway_tpu.observability.signals import get_sampler

            sampler = get_sampler()
        if sampler is None:
            return None
        vals = [
            b.get("burn")
            for b in sampler.burn_rates().values()
            if b.get("burn") is not None
        ]
        return max(vals) if vals else None

    def observe(self, now_mono: float | None = None) -> PlaneObservation:
        now = time.monotonic() if now_mono is None else now_mono
        cfg = self.config
        burn = self._burn_now()
        with self._lock:
            if burn is None:
                self._high_since = None
                self._drained_since = None
            else:
                if burn > 1.0:
                    if self._high_since is None:
                        self._high_since = now
                else:
                    self._high_since = None
                if burn <= cfg.low_water:
                    if self._drained_since is None:
                        self._drained_since = now
                else:
                    self._drained_since = None
            high_for = now - self._high_since if self._high_since else 0.0
            drained_for = (
                now - self._drained_since if self._drained_since else 0.0
            )
            cooldown = (
                max(self._cooldown_until - now, 0.0)
                if self._cooldown_until is not None
                else 0.0
            )
            in_flight = self._in_flight
            act_cost = self._act_cost_s
        predicted = None
        if self.predictor is not None and burn is not None:
            self.predictor.observe(now, burn)
            # lead the surge by at least one actuation: a plane whose
            # transfers take 40 s must start scaling 40 s earlier
            horizon = max(cfg.horizon_s, act_cost)
            predicted = self.predictor.forecast(horizon, now)
        return PlaneObservation(
            mono=now,
            ranks=self.ranks,
            max_burn=burn,
            burn_high_for_s=high_for,
            drained_for_s=drained_for,
            predicted_burn=predicted,
            cooldown_remaining_s=cooldown,
            action_in_flight=in_flight,
            actuation_cost_s=act_cost,
        )

    # --- the loop body ----------------------------------------------------

    def step(self, now_mono: float | None = None) -> Decision:
        from pathway_tpu.observability.journal import record as journal_record

        now = time.monotonic() if now_mono is None else now_mono
        with self._lock:
            if self._last_step_mono is not None:
                dt = max(now - self._last_step_mono, 0.0)
                if dt:
                    self._m_rank_seconds.inc(self.ranks * dt)
            self._last_step_mono = now
        obs = self.observe(now)
        decision = self.policy.decide(obs)
        self.last_decision = decision
        self._m_decisions.labels(decision.action).inc()
        if not decision.actionable:
            if "cooldown" in decision.reason and (
                (obs.max_burn or 0.0) > 1.0
                or obs.drained_for_s >= self.config.down_window_s
            ):
                self._m_cooldown_holds.inc()
            return decision

        old = self.ranks
        journal_record(
            "autoscale-decision",
            f"{decision.action} {old} -> {decision.target_ranks}: "
            f"{decision.reason}",
            persist=True,
            action=decision.action,
            from_ranks=old,
            to_ranks=decision.target_ranks,
            max_burn=obs.max_burn,
            predicted_burn=obs.predicted_burn,
        )
        with self._lock:
            self._in_flight = True
        t0 = time.monotonic()
        try:
            self.actuator.resize(decision.target_ranks)
        except Exception as e:
            self._m_rollbacks.inc()
            journal_record(
                "autoscale-rollback",
                f"{decision.action} {old} -> {decision.target_ranks} "
                f"failed ({type(e).__name__}: {e}); staying at {old}",
                persist=True,
                action=decision.action,
                from_ranks=old,
                to_ranks=decision.target_ranks,
            )
            with self._lock:
                self._in_flight = False
                # lock out retries for a cooldown: a failing transfer
                # must not be hammered.  `now` (not the wall thread
                # clock) so virtual-time drivers stay consistent
                self._cooldown_until = now + self.config.cooldown_s
            return decision
        seconds = time.monotonic() - t0
        with self._lock:
            self._in_flight = False
            self.ranks = decision.target_ranks
            self.resizes += 1
            self._act_costs += 1
            self._act_cost_s = (
                seconds
                if self._act_costs == 1
                else 0.7 * self._act_cost_s + 0.3 * seconds
            )
            self._cooldown_until = now + self.config.cooldown_s
            # the burn history predates the new topology: restart the
            # duration markers instead of acting on stale windows
            self._high_since = None
            self._drained_since = None
            if (
                self._last_action is not None
                and self._last_action[0] != decision.action
                and now - self._last_action[1]
                < 2.0 * self.config.cooldown_s + 1e-9
            ):
                self._m_flaps.inc()
            self._last_action = (decision.action, now)
        journal_record(
            "autoscale-applied",
            f"{decision.action} {old} -> {decision.target_ranks} in "
            f"{seconds:.3f}s",
            persist=True,
            action=decision.action,
            from_ranks=old,
            to_ranks=decision.target_ranks,
            seconds=seconds,
        )
        return decision

    # --- thread driver ----------------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.step()
            except Exception:
                pass

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="pathway-autoscale", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None

    # --- introspection (/debug/autoscale, plane doctor) -------------------

    def status(self) -> dict:
        cfg = self.config
        with self._lock:
            cooldown = (
                max(self._cooldown_until - time.monotonic(), 0.0)
                if self._cooldown_until is not None
                else 0.0
            )
            d = self.last_decision
            return {
                "armed": True,
                "running": self._thread is not None
                and self._thread.is_alive(),
                "ranks": self.ranks,
                "resizes": self.resizes,
                "in_flight": self._in_flight,
                "cooldown_remaining_s": round(cooldown, 3),
                "actuation_cost_s": round(self._act_cost_s, 4),
                "actuator": getattr(self.actuator, "label", "custom"),
                "predictor": (
                    self.predictor.state()
                    if self.predictor is not None
                    else None
                ),
                "config": {
                    "min_ranks": cfg.min_ranks,
                    "max_ranks": cfg.max_ranks,
                    "up_window_s": cfg.up_window_s,
                    "down_window_s": cfg.down_window_s,
                    "cooldown_s": cfg.cooldown_s,
                    "low_water": cfg.low_water,
                    "step": cfg.step,
                    "horizon_s": cfg.horizon_s,
                },
                "last_decision": (
                    None
                    if d is None
                    else {
                        "action": d.action,
                        "target_ranks": d.target_ranks,
                        "reason": d.reason,
                    }
                ),
            }


# --- process-global controller ---------------------------------------------

_controller: AutoscaleController | None = None
_controller_lock = threading.Lock()


def arm_controller(
    actuator: Any, *, ranks: int, start: bool = False, **kw: Any
) -> AutoscaleController:
    """Create the process-global controller (the one the plane doctor's
    ``autoscale-coverage`` rule and ``/debug/autoscale`` see)."""
    global _controller
    with _controller_lock:
        if _controller is not None:
            _controller.stop()
        _controller = AutoscaleController(actuator, ranks=ranks, **kw)
    if start:
        _controller.start()
    return _controller


def get_controller() -> AutoscaleController | None:
    return _controller


def reset_controller() -> None:
    """Test hook: stop and forget the process-global controller."""
    global _controller
    with _controller_lock:
        if _controller is not None:
            try:
                _controller.stop()
            except Exception:
                pass
        _controller = None
