"""Flux Pilot predictor — a short-horizon forecaster over one signal
series.

Two terms, both cheap enough to run per controller step:

* **Holt level+trend**: exponentially-weighted level and slope with a
  shared time constant ``tau_s`` — the linear extrapolation fires on
  any sustained ramp within a couple of time constants, no period
  knowledge needed.
* **Diurnal phase profile** (optional, ``period_s``): a per-phase-bucket
  EWMA of the value at that point of the cycle, learned from the same
  ``observe`` stream (or seeded wholesale from a Fleet Lens ring via
  :meth:`seed`).  Once a bucket has data, the forecast also consults
  the profile at ``now + horizon`` — re-centered on the current level
  so a day-over-day amplitude shift does not stale the shape — and
  takes the max with the trend term.  Taking the max is deliberate:
  the autoscaler's failure mode is scaling up LATE (shed), not early
  (a few rank-seconds), so the forecast is conservative upward.

The forecaster is clock-free: callers pass monotonic timestamps in,
which is what makes lead-time properties unit-testable against a
synthetic diurnal generator (tests/test_autoscale.py) and lets the
bench compress a full day into seconds.
"""

from __future__ import annotations

import math


class LoadForecaster:
    def __init__(
        self,
        *,
        tau_s: float = 30.0,
        period_s: float | None = None,
        buckets: int = 48,
    ):
        self.tau_s = max(float(tau_s), 1e-6)
        self.period_s = None if period_s is None else max(float(period_s), 1e-6)
        self.buckets = max(int(buckets), 2)
        self._level: float | None = None
        self._slope = 0.0
        self._last_mono: float | None = None
        self._profile: list[float | None] = [None] * self.buckets
        self._n = 0

    # --- learning ---------------------------------------------------------

    def _bucket(self, mono: float) -> int:
        assert self.period_s is not None
        phase = (mono % self.period_s) / self.period_s
        return min(int(phase * self.buckets), self.buckets - 1)

    def observe(self, mono: float, value: float) -> None:
        value = float(value)
        if self._level is None or self._last_mono is None:
            self._level = value
            self._slope = 0.0
        else:
            dt = mono - self._last_mono
            if dt > 0.0:
                alpha = 1.0 - math.exp(-dt / self.tau_s)
                prev = self._level
                # Holt: project the old level forward, then correct
                self._level = (
                    alpha * value + (1.0 - alpha) * (prev + self._slope * dt)
                )
                inst = (self._level - prev) / dt
                self._slope = alpha * inst + (1.0 - alpha) * self._slope
        self._last_mono = mono
        self._n += 1
        if self.period_s is not None:
            b = self._bucket(mono)
            cur = self._profile[b]
            self._profile[b] = (
                value if cur is None else 0.7 * cur + 0.3 * value
            )

    def seed(self, points: list[tuple[float, float]]) -> None:
        """Warm-start from ring history — ``[(mono, value), ...]``
        oldest-first, e.g. ``SignalRing.points()``."""
        for mono, value in points:
            self.observe(mono, value)

    # --- forecasting ------------------------------------------------------

    def forecast(self, horizon_s: float, now_mono: float | None = None) -> float | None:
        """Predicted worst value over the NEXT ``horizon_s`` seconds, or
        None before any observation.  Never negative.

        The profile term is the max over every phase bucket the window
        [now, now + horizon] touches — a point estimate at exactly
        ``now + horizon`` would look PAST a surge whose peak falls
        inside the window and wave a scale-down through mid-surge."""
        if self._level is None or self._last_mono is None:
            return None
        if now_mono is None:
            now_mono = self._last_mono
        ahead = max(now_mono - self._last_mono, 0.0) + max(horizon_s, 0.0)
        trend = self._level + self._slope * ahead
        best = trend
        if self.period_s is not None:
            here = self._profile[self._bucket(now_mono)]
            # every bucket the look-ahead window touches, at bucket
            # resolution (whole cycle when the window spans it)
            span = min(ahead, self.period_s)
            step = self.period_s / self.buckets
            peak: float | None = None
            off = 0.0
            while off <= span:
                v = self._profile[self._bucket(now_mono + off)]
                if v is not None and (peak is None or v > peak):
                    peak = v
                off += step
            if peak is not None:
                # re-center the profile on the current level so the
                # learned SHAPE survives day-over-day amplitude drift
                # — but only upward: a level BELOW the profile is
                # usually the mitigation working (extra ranks soaking
                # the surge), and discounting the profile for it would
                # let a scale-down through at the surge peak.  A surge
                # that is genuinely gone decays out of the profile via
                # its own EWMA instead.
                bias = (
                    max(self._level - here, 0.0)
                    if here is not None
                    else 0.0
                )
                best = max(best, peak + bias)
        return max(best, 0.0)

    def lead_crossing(
        self,
        threshold: float,
        max_horizon_s: float,
        now_mono: float | None = None,
        resolution_s: float = 1.0,
    ) -> float | None:
        """Smallest horizon (seconds) at which the forecast crosses
        ``threshold``, scanned to ``max_horizon_s`` — None if it never
        does.  This is the lead time a scale-up gets over the raw
        signal."""
        h = 0.0
        step = max(float(resolution_s), 1e-3)
        while h <= max_horizon_s:
            v = self.forecast(h, now_mono)
            if v is not None and v > threshold:
                return h
            h += step
        return None

    def state(self) -> dict:
        return {
            "level": self._level,
            "slope": self._slope,
            "observations": self._n,
            "period_s": self.period_s,
            "profile_coverage": sum(
                1 for v in self._profile if v is not None
            )
            / self.buckets
            if self.period_s is not None
            else None,
        }
