"""Flux Pilot — the SLO-driven autoscaler that closes the control loop
over Shard Flux.

Fleet Lens (observability/signals.py) answers "what has the plane been
doing"; Shard Flux (parallel/supervisor.py ``resize``, parallel/
replicate.py ``DeltaStreamServer.reshard``, serving/router.py
``swap_shard_map``) makes rank/shard count a live knob.  This package
is the policy plane between them:

* :mod:`~pathway_tpu.autoscale.policy` — hysteresis decisions as a pure
  function of one :class:`PlaneObservation` snapshot (asymmetric
  up/down windows, low-water drain mark, cooldown lock, min/max rank
  bounds).
* :mod:`~pathway_tpu.autoscale.predictor` — a short-horizon load
  forecaster (EWMA level+trend with an optional diurnal phase profile)
  so scale-up fires *ahead* of a modeled surge, not after the shed.
* :mod:`~pathway_tpu.autoscale.controller` — the actuation loop:
  serialized resizes against the existing mechanisms, every decision /
  actuation / rollback journaled (``autoscale-decision`` /
  ``autoscale-applied`` / ``autoscale-rollback``), and the
  ``pathway_autoscale_rank_seconds_total`` cost proxy.
"""

from pathway_tpu.autoscale.controller import (
    AutoscaleController,
    CallbackActuator,
    ServingPlaneActuator,
    SupervisorActuator,
    arm_controller,
    get_controller,
    reset_controller,
)
from pathway_tpu.autoscale.policy import (
    DOWN,
    HOLD,
    UP,
    AutoscaleConfig,
    AutoscalePolicy,
    Decision,
    PlaneObservation,
)
from pathway_tpu.autoscale.predictor import LoadForecaster

__all__ = [
    "AutoscaleConfig",
    "AutoscaleController",
    "AutoscalePolicy",
    "CallbackActuator",
    "Decision",
    "DOWN",
    "HOLD",
    "LoadForecaster",
    "PlaneObservation",
    "ServingPlaneActuator",
    "SupervisorActuator",
    "UP",
    "arm_controller",
    "get_controller",
    "reset_controller",
]
