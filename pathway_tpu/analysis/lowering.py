"""The Lowering Ledger: device-free TPU compilability proofs.

Every bench since r02 has run on the CPU backend, so the TPU-shaped
codepaths (ops/pallas_topk.py, ops/paged_attention.py, Tick Forge's
jitted segments) were only ever exercised in interpret mode — and the
BENCH_r02 k=10 crash proved interpret-green is NOT lowerable-green.
This module turns "will it compile for TPU" into a static, hardware-free
proof with three layers:

1. **Shared static gate** — ``check_tpu_block_rules`` / ``lane_pad`` /
   ``check_block_specs``, the single source of truth for the Mosaic
   (8, 128) tiling rules that both Pallas kernels previously duplicated.
   Violations raise :class:`LoweringRuleViolation`, a ``ValueError``
   carrying the violated rule's id.
2. **AOT prover** — :func:`prove_lowering` runs every registered kernel
   family through full TPU (Mosaic) lowering via
   ``jax.export.export(jax.jit(fn), platforms=["tpu"])`` against
   abstract ``ShapeDtypeStruct`` args: compile-only, zero device access,
   works under ``JAX_PLATFORMS=cpu``. Families cover the pow2 pad
   ladder plus the known crash shapes (k=10 lane pad, head_dim
   1/32/128/129); VMEM footprints are estimated statically from the
   BlockSpecs and checked against the per-core budget.
3. **Content-addressed manifest** — :func:`write_manifest` emits
   ``LOWERING_r16.json`` with a sha256 per case over the serialized
   StableHLO, so CI diffs catch lowering regressions (a kernel that
   stops lowering, a silently changed module) without hardware.

``engine/compile.py`` registers each segment program it builds at
runtime via :func:`register_program`, so a live process can prove its
actual compiled tick against the TPU rules too (family
``tick_forge_live``).

Module-level imports stay light (no jax): ops modules import this for
the shared gate, and ``pathway_tpu/__init__`` imports analysis early.
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import json
import math
from typing import Any, Callable, Iterable

from pathway_tpu.analysis.diagnostics import Diagnostic, Severity

# Mosaic vector-layout geometry: a vreg tiles (sublane, lane) = (8, 128)
# for 32-bit types; every Pallas block's trailing two dims must respect
# it (see /opt/skills/guides pallas guidance and the BENCH_r02 lesson).
SUBLANE = 8
LANE = 128

# Per-core VMEM budget the static estimator checks block residency
# against (v4/v5e-class cores carry 16 MiB of VMEM).
VMEM_LIMIT_BYTES = 16 * 1024 * 1024

MANIFEST_NAME = "LOWERING_r16.json"

# rule ids a finding/manifest entry can name
RULE_8X128 = "mosaic-8x128"
RULE_LANE_PAD = "lane-pad"
RULE_LOWER = "tpu-lowering"
RULE_VMEM = "vmem-budget"


class LoweringRuleViolation(ValueError):
    """A statically-decidable TPU lowering rule was violated.

    Subclasses ``ValueError`` so pre-existing gates (``pytest.raises
    (ValueError)`` in the kernel tests) keep working; carries the stable
    rule id so prover findings can name the violated rule."""

    def __init__(self, rule: str, message: str):
        super().__init__(message)
        self.rule = rule


def lane_pad(d: int) -> int:
    """``d`` padded up to the TPU lane width (multiple of 128) — the one
    rule both kernels apply to their minor output dims (pallas_topk's
    ``_kpad`` k-tiles, paged_attention's head_dim pool width)."""
    return -(-int(d) // LANE) * LANE


def check_tpu_block_rules(block_shape, array_shape) -> None:
    """Static mirror of the Mosaic lowering rule: the last two dims of a
    block must be divisible by (8, 128) respectively, or equal the
    corresponding overall-array dims. Raises
    :class:`LoweringRuleViolation` otherwise — the compiled-mode test
    gate calls this for every spec a kernel uses so an un-lowerable
    shape fails the suite even on the CPU backend."""
    if len(block_shape) != len(array_shape):
        raise LoweringRuleViolation(
            RULE_8X128,
            f"block rank {len(block_shape)} != array rank "
            f"{len(array_shape)}",
        )
    if len(block_shape) < 2:
        return
    checks = (
        (block_shape[-2], array_shape[-2], SUBLANE),
        (block_shape[-1], array_shape[-1], LANE),
    )
    for blk_dim, arr_dim, align in checks:
        if blk_dim % align != 0 and blk_dim != arr_dim:
            raise LoweringRuleViolation(
                RULE_8X128,
                f"block shape {tuple(block_shape)} vs array "
                f"{tuple(array_shape)}: dim {blk_dim} is neither "
                f"divisible by {align} nor equal to the array dim "
                f"{arr_dim}",
            )


def check_block_specs(spec_pairs: Iterable[tuple[Any, tuple]]) -> None:
    """Gate a kernel's whole layout: ``spec_pairs`` is the
    [(BlockSpec, array_shape)] list the ops ``_specs`` builders return."""
    for spec, arr_shape in spec_pairs:
        check_tpu_block_rules(spec.block_shape, arr_shape)


def estimate_vmem_bytes(
    spec_pairs: Iterable[tuple[Any, tuple]],
    scratch_shapes: Iterable[tuple] = (),
    itemsize: int = 4,
) -> int:
    """Static VMEM residency of one grid step, from the BlockSpecs alone:
    every in/out block is double-buffered (Mosaic overlaps the next grid
    step's copy with compute), scratch is single-buffered."""
    blocks = sum(
        math.prod(spec.block_shape) * itemsize for spec, _ in spec_pairs
    )
    scratch = sum(math.prod(s) * itemsize for s in scratch_shapes)
    return 2 * blocks + scratch


# ---------------------------------------------------------------------------
# kernel-family registry


@dataclasses.dataclass
class LoweringCase:
    """One provable shape of one kernel family.

    ``build`` returns ``(fn, abstract_args)`` for the AOT export;
    ``static_check`` runs the shared gate (raises on violation);
    ``expect`` is "lower" for shapes that must compile and "reject" for
    shapes the gate must refuse (a gate that stops rejecting a known-bad
    shape is itself a regression); ``vmem`` returns the static VMEM
    estimate in bytes."""

    family: str
    name: str
    shape: dict
    build: Callable[[], tuple[Callable, tuple]] | None = None
    static_check: Callable[[], None] | None = None
    expect: str = "lower"  # "lower" | "reject"
    vmem: Callable[[], int] | None = None
    x64: bool = False


# family name -> provider returning that family's built-in case ladder
FAMILIES: dict[str, Callable[[], list[LoweringCase]]] = {}
# family name -> builder turning a user shape dict into one LoweringCase
FAMILY_SHAPES: dict[str, Callable[[dict], LoweringCase]] = {}


def kernel_family(name: str):
    """Register a kernel family's built-in case provider."""

    def deco(fn):
        FAMILIES[name] = fn
        return fn

    return deco


def family_shape(name: str):
    """Register a family's user-shape case builder (``--prove-shape``)."""

    def deco(fn):
        FAMILY_SHAPES[name] = fn
        return fn

    return deco


def parse_shape_spec(spec: str) -> tuple[str, dict]:
    """``"paged_attention:head_dim=129,b=4"`` -> (family, {dims}).
    Values parse as ints."""
    family, _, rest = spec.partition(":")
    family = family.strip()
    if not family:
        raise ValueError(f"empty family in shape spec {spec!r}")
    shape: dict = {}
    if rest.strip():
        for part in rest.split(","):
            key, sep, val = part.partition("=")
            if not sep:
                raise ValueError(
                    f"bad shape spec {spec!r}: expected key=value, "
                    f"got {part!r}"
                )
            try:
                shape[key.strip()] = int(val)
            except ValueError:
                raise ValueError(
                    f"bad shape spec {spec!r}: {val!r} is not an int"
                ) from None
    return family, shape


def case_for_shape(family: str, shape: dict) -> LoweringCase:
    if family not in FAMILY_SHAPES:
        raise ValueError(
            f"unknown kernel family {family!r}; "
            f"registered: {sorted(FAMILY_SHAPES)}"
        )
    case = FAMILY_SHAPES[family](dict(shape))
    # a user-supplied shape is an assertion it should ship: the gate
    # refusing it is an ERROR finding, never an expected rejection
    case.expect = "lower"
    return case


# --- pallas_topk -----------------------------------------------------------


def _topk_case(b: int, d: int, n: int, k: int, pad: bool = True):
    from pathway_tpu.ops import pallas_topk as pt

    if pad:

        def static_check():
            pt.validate_lowering(b, d, n, k)

        def build():
            import functools

            import jax
            import jax.numpy as jnp

            fn = functools.partial(
                pt.pallas_block_topk.__wrapped__, k=k, interpret=False
            )
            args = (
                jax.ShapeDtypeStruct((b, d), jnp.float32),
                jax.ShapeDtypeStruct((n, d), jnp.float32),
                jax.ShapeDtypeStruct((n,), jnp.bool_),
            )
            return fn, args

        def vmem():
            _g, ins, outs, _sh, _nb, _kp = pt._specs(b, d, n, k)
            return estimate_vmem_bytes(ins + outs)

        return LoweringCase(
            "pallas_topk",
            f"b{b}_d{d}_n{n}_k{k}",
            {"b": b, "d": d, "n": n, "k": k},
            build=build,
            static_check=static_check,
            vmem=vmem,
        )

    # raw un-lane-padded k tile — the exact layout BENCH_r02 shipped,
    # which the shared gate must keep rejecting
    nblk = max(n // pt.BLK, 1)

    def bad_static():
        check_tpu_block_rules((b, k), (b, nblk * k))

    return LoweringCase(
        "pallas_topk",
        f"unpadded_b{b}_k{k}_tile",
        {"b": b, "k": k, "nblk": nblk, "pad": 0},
        static_check=bad_static,
        expect="reject",
    )


@kernel_family("pallas_topk")
def _topk_cases() -> list[LoweringCase]:
    cases = [
        # the BENCH_r02 crash shape: k=10 forces the 128-lane pad
        _topk_case(8, 128, 2048, 10),
        _topk_case(8, 128, 2048, 1),
        _topk_case(8, 64, 1024, 100),
        _topk_case(16, 256, 4096, 128),
    ]
    # and the un-padded tile it replaced stays rejected
    cases.append(_topk_case(8, 128, 2048, 10, pad=False))
    return cases


@family_shape("pallas_topk")
def _topk_shape(shape: dict) -> LoweringCase:
    return _topk_case(
        shape.pop("b", 8),
        shape.pop("d", 128),
        shape.pop("n", 2048),
        shape.pop("k", 10),
        pad=bool(shape.pop("pad", 1)),
    )


# --- paged_attention -------------------------------------------------------


def _paged_case(
    b: int, h: int, p: int, dp: int, n_pages: int, max_pages: int
):
    from pathway_tpu.ops import paged_attention as pa

    def static_check():
        pa.validate_lowering(b, h, p, dp, n_pages, max_pages)

    expect = "lower" if dp % LANE == 0 else "reject"
    build = None
    vmem = None
    if expect == "lower":

        def build():
            import functools

            import jax
            import jax.numpy as jnp

            fn = functools.partial(
                pa.paged_attention.__wrapped__,
                sm_scale=1.0 / math.sqrt(dp),
                interpret=False,
            )
            args = (
                jax.ShapeDtypeStruct((b, h, dp), jnp.float32),
                jax.ShapeDtypeStruct((n_pages, h, p, dp), jnp.float32),
                jax.ShapeDtypeStruct((n_pages, h, p, dp), jnp.float32),
                jax.ShapeDtypeStruct((b, max_pages), jnp.int32),
                jax.ShapeDtypeStruct((b,), jnp.int32),
            )
            return fn, args

        def vmem():
            _g, ins, outs, _sh = pa._specs(b, h, p, dp, n_pages, max_pages)
            scratch = ((h, LANE), (h, LANE), (h, dp))
            return estimate_vmem_bytes(ins + outs, scratch)

    return LoweringCase(
        "paged_attention",
        f"b{b}_h{h}_p{p}_dp{dp}",
        {
            "b": b,
            "h": h,
            "p": p,
            "head_dim": dp,
            "n_pages": n_pages,
            "max_pages": max_pages,
        },
        build=build,
        static_check=static_check,
        expect=expect,
        vmem=vmem,
    )


@kernel_family("paged_attention")
def _paged_cases() -> list[LoweringCase]:
    return [
        _paged_case(8, 4, 16, 128, 32, 8),
        _paged_case(4, 8, 8, 256, 16, 4),
        # the head_dim ladder's known-bad rungs: 1, 32 and 129 are not
        # lane-padded and must be rejected by the shared gate
        _paged_case(8, 4, 16, 1, 32, 8),
        _paged_case(8, 4, 16, 32, 32, 8),
        _paged_case(8, 4, 16, 129, 32, 8),
    ]


@family_shape("paged_attention")
def _paged_shape(shape: dict) -> LoweringCase:
    return _paged_case(
        shape.pop("b", 8),
        shape.pop("h", 4),
        shape.pop("p", 16),
        shape.pop("head_dim", shape.pop("dp", 128)),
        shape.pop("n_pages", 32),
        shape.pop("max_pages", 8),
    )


# --- tick_forge (compiled segment programs) --------------------------------


def _forge_case(rows: int) -> LoweringCase:
    def build():
        import jax
        import numpy as np

        import pathway_tpu as pw
        from pathway_tpu.engine.compile import _build_program
        from pathway_tpu.engine.nodes import ALL_NODES

        # declare a canonical stateless chain (map + filter, the shapes
        # plan_segments fuses) without leaking nodes into the caller's
        # declared graph
        n0 = len(ALL_NODES)
        try:
            t = pw.debug.table_from_rows(
                pw.schema_from_types(x=int, y=float), [(1, 1.0)]
            )
            mapped = t.select(
                z=pw.this.x * 2 + 1, w=pw.this.y * 0.5 + pw.this.y
            )
            filt = mapped.filter(pw.this.z > 0)
            chain = [mapped._node, filt._node]
            external = list(chain[0].inputs[0].column_names)
            dtypes = {
                "x": np.dtype("int64"),
                "y": np.dtype("float64"),
            }
            prog = _build_program(chain, external, dtypes)
        finally:
            del ALL_NODES[n0:]
        args = tuple(
            jax.ShapeDtypeStruct((rows,), dtypes[c]) for c in prog.in_cols
        )
        return prog.fn, args

    return LoweringCase(
        "tick_forge",
        f"map_filter_rows{rows}",
        {"rows": rows, "cols": 2},
        build=build,
        x64=True,
    )


@kernel_family("tick_forge")
def _forge_cases() -> list[LoweringCase]:
    # three rungs of the pow2 row-bucket ladder engine/compile.py pads
    # batches onto (row_bucket): floor, a mid rung, a large rung
    return [_forge_case(8), _forge_case(1024), _forge_case(8192)]


@family_shape("tick_forge")
def _forge_shape(shape: dict) -> LoweringCase:
    return _forge_case(shape.pop("rows", 1024))


# --- live segment programs -------------------------------------------------

# segment programs the running engine registered (engine/compile.py
# SegmentRunner._program_for): proven under family "tick_forge_live"
_LIVE_PROGRAMS: dict[str, LoweringCase] = {}
_LIVE_CAP = 64


def register_program(
    name: str,
    fn: Callable,
    arg_structs: tuple,
    *,
    x64: bool = True,
    meta: dict | None = None,
) -> None:
    """Record a jitted segment program for device-free TPU proving.
    Called by the engine after each successful segment build; bounded,
    idempotent per name, and never raises (the ledger must not be able
    to take the tick down)."""
    try:
        if len(_LIVE_PROGRAMS) >= _LIVE_CAP and name not in _LIVE_PROGRAMS:
            return
        args = tuple(arg_structs)
        _LIVE_PROGRAMS[name] = LoweringCase(
            "tick_forge_live",
            name,
            dict(meta or {}),
            build=lambda: (fn, args),
            x64=x64,
        )
    except Exception:  # pragma: no cover - defensive: never break the tick
        pass


def live_cases() -> list[LoweringCase]:
    return [_LIVE_PROGRAMS[k] for k in sorted(_LIVE_PROGRAMS)]


def clear_live_programs() -> None:
    _LIVE_PROGRAMS.clear()


# ---------------------------------------------------------------------------
# the prover


@dataclasses.dataclass
class LoweringReport:
    """Outcome of one :func:`prove_lowering` pass: per-case manifest
    entries plus Doctor-style findings for anything that violated a rule
    or failed to lower."""

    platform: str
    entries: list[dict] = dataclasses.field(default_factory=list)
    findings: list[Diagnostic] = dataclasses.field(default_factory=list)

    def by_status(self, status: str) -> list[dict]:
        return [e for e in self.entries if e["status"] == status]

    def to_manifest(self) -> dict:
        entries = sorted(
            self.entries, key=lambda e: (e["family"], e["case"])
        )
        body = json.dumps(entries, sort_keys=True, default=str)
        return {
            "version": 1,
            "platform": self.platform,
            "vmem_limit_bytes": VMEM_LIMIT_BYTES,
            "content_sha256": hashlib.sha256(
                body.encode("utf-8")
            ).hexdigest(),
            "cases": entries,
        }


def _export_case(fn: Callable, args: tuple, platform: str, x64: bool):
    import jax
    from jax import export as jexport

    wrapped_t = getattr(jax.stages, "Wrapped", ())
    if not isinstance(fn, wrapped_t):
        fn = jax.jit(fn)
    ctx = (
        jax.experimental.enable_x64() if x64 else contextlib.nullcontext()
    )
    # drop caller-frame provenance from MLIR locations: the loc() lines
    # otherwise embed the *call site* of the prover, which would make
    # the content hash depend on who invoked it
    saved_limit = jax.config.jax_traceback_in_locations_limit
    jax.config.update("jax_traceback_in_locations_limit", 0)
    try:
        with ctx:
            return jexport.export(fn, platforms=[platform])(*args)
    finally:
        jax.config.update(
            "jax_traceback_in_locations_limit", saved_limit
        )


def _condense(exc: BaseException, limit: int = 300) -> str:
    """First line of the deepest cause — Mosaic wraps the real
    NotImplementedError several layers down."""
    root = exc
    while root.__cause__ is not None:
        root = root.__cause__
    msg = f"{type(root).__name__}: {root}".splitlines()[0]
    return msg[:limit]


def prove_lowering(
    families: Iterable[str] | None = None,
    cases: Iterable[LoweringCase] | None = None,
    platform: str = "tpu",
    include_live: bool = True,
) -> LoweringReport:
    """AOT-lower every selected case for ``platform`` with zero device
    access and return the report.

    Per case: (1) the shared static gate runs first — expected-reject
    cases must be refused here (a gate regression is an ERROR), and a
    gate refusal of an expected-lower case is an ERROR finding naming
    the kernel, shape and violated rule; (2) surviving cases AOT-export
    through the real Mosaic lowering pipeline and record a sha256 over
    the serialized StableHLO; (3) static VMEM estimates are checked
    against :data:`VMEM_LIMIT_BYTES`."""
    selected: list[LoweringCase]
    if cases is not None:
        selected = list(cases)
    else:
        fams = sorted(FAMILIES) if families is None else list(families)
        unknown = sorted(set(fams) - set(FAMILIES))
        if unknown:
            raise ValueError(
                f"unknown kernel family id(s) {unknown}; "
                f"registered: {sorted(FAMILIES)}"
            )
        selected = []
        for fam in fams:
            selected.extend(FAMILIES[fam]())
        if include_live:
            selected.extend(live_cases())

    report = LoweringReport(platform=platform)
    for case in selected:
        entry: dict = {
            "family": case.family,
            "case": case.name,
            "shape": dict(case.shape),
            "expect": case.expect,
        }
        report.entries.append(entry)

        violation: LoweringRuleViolation | None = None
        if case.static_check is not None:
            try:
                case.static_check()
            except LoweringRuleViolation as exc:
                violation = exc
            except ValueError as exc:  # a gate predating the rule tags
                violation = LoweringRuleViolation(RULE_8X128, str(exc))

        if case.expect == "reject":
            if violation is None:
                entry["status"] = "gate-regression"
                report.findings.append(
                    Diagnostic(
                        RULE_LOWER,
                        Severity.ERROR,
                        f"{case.family} {case.name} {case.shape}: the "
                        "shared lowering gate no longer rejects this "
                        "known-bad shape",
                        fix_hint="restore the check in "
                        "analysis/lowering.py (check_tpu_block_rules / "
                        "the family's validate_lowering)",
                        data={
                            "family": case.family,
                            "case": case.name,
                            "shape": dict(case.shape),
                        },
                    )
                )
            else:
                entry["status"] = "rejected"
                entry["rule"] = violation.rule
                entry["reason"] = str(violation)
            continue

        if violation is not None:
            entry["status"] = "gate-rejected"
            entry["rule"] = violation.rule
            entry["reason"] = str(violation)
            report.findings.append(
                Diagnostic(
                    RULE_LOWER,
                    Severity.ERROR,
                    f"kernel {case.family} shape {case.shape} rejected "
                    f"by the shared lowering gate "
                    f"(rule {violation.rule}): {violation}",
                    fix_hint="pad the offending dim with lane_pad() / "
                    "align blocks to the Mosaic (8, 128) tile — see "
                    "analysis/lowering.py",
                    data={
                        "family": case.family,
                        "case": case.name,
                        "shape": dict(case.shape),
                        "rule": violation.rule,
                    },
                )
            )
            continue

        if case.build is None:
            entry["status"] = "static-only"
        else:
            try:
                fn, args = case.build()
                exported = _export_case(fn, args, platform, case.x64)
                # hash the textual StableHLO, not the serialized
                # bytecode: the text is deterministic per shape while
                # the bytecode embeds per-process trace counters
                text = exported.mlir_module()
                entry["status"] = "lowered"
                entry["stablehlo_sha256"] = hashlib.sha256(
                    text.encode("utf-8")
                ).hexdigest()
                entry["mlir_bytes"] = len(text)
            except Exception as exc:
                entry["status"] = "lowering-failed"
                entry["error"] = _condense(exc)
                report.findings.append(
                    Diagnostic(
                        RULE_LOWER,
                        Severity.ERROR,
                        f"kernel {case.family} shape {case.shape} "
                        f"passed the static gate but failed "
                        f"{platform} lowering: {_condense(exc)}",
                        fix_hint="the static gate under-approximates a "
                        "Mosaic rule; reproduce with "
                        "jax.export.export(jax.jit(fn), "
                        "platforms=['tpu']) and extend the gate",
                        data={
                            "family": case.family,
                            "case": case.name,
                            "shape": dict(case.shape),
                            "rule": RULE_LOWER,
                        },
                    )
                )
                continue

        if case.vmem is not None:
            vmem = int(case.vmem())
            entry["vmem_bytes"] = vmem
            entry["vmem_frac"] = round(vmem / VMEM_LIMIT_BYTES, 4)
            if vmem > VMEM_LIMIT_BYTES:
                report.findings.append(
                    Diagnostic(
                        RULE_VMEM,
                        Severity.ERROR,
                        f"kernel {case.family} shape {case.shape}: "
                        f"static VMEM estimate {vmem} bytes exceeds the "
                        f"per-core budget {VMEM_LIMIT_BYTES}",
                        fix_hint="shrink the block shapes in the "
                        "family's _specs (smaller BLK / page size)",
                        data={
                            "family": case.family,
                            "case": case.name,
                            "shape": dict(case.shape),
                            "rule": RULE_VMEM,
                            "vmem_bytes": vmem,
                        },
                    )
                )
    return report


def write_manifest(
    report: LoweringReport, path: str = MANIFEST_NAME
) -> str:
    """Write the content-addressed manifest and return its path."""
    doc = report.to_manifest()
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True, default=str)
        fh.write("\n")
    return path
