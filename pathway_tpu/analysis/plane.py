"""Plane Doctor: deployment-scope rules over graph + environment.

The per-graph rules (analysis/rules.py) prove properties of one
declared dataflow. The invariants PRs 11–15 enforce at *runtime* —
snapshot coverage for elastic resizes, wire-codec efficiency, knob
coherence across the ``PATHWAY_*`` surface — are statically checkable
too, but their scope is the deployment plane (graph ⨯ exec metadata ⨯
environment), not a single node. These rules live in their own
registry (``PLANE_RULES``) and run via
:func:`pathway_tpu.analysis.doctor.run_plane_doctor` /
``python -m pathway_tpu.analysis --plane``.

Rules consume the same :class:`GraphFacts` instance as the graph rules
(node-anchored findings honor the same per-node ``suppress()``), plus
the exec metadata hooks the elastic and serving planes export:
``elastic.planner.reshard_capable`` (which exec classes can hand state
over as arrangements) and ``serving.config.plane_knobs`` (the
``PATHWAY_*`` environment snapshot).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable

from pathway_tpu.analysis.diagnostics import Diagnostic, Severity
from pathway_tpu.analysis.graph_facts import GraphFacts

PLANE_RULES: dict[str, Callable[[GraphFacts], Iterable[Diagnostic]]] = {}


def plane_rule(rule_id: str):
    """Register a deployment-scope rule (same contract as ``@rule``:
    a generator of Diagnostics over one GraphFacts)."""

    def deco(fn):
        PLANE_RULES[rule_id] = fn
        return fn

    return deco


def default_plane_rules() -> dict:
    return dict(PLANE_RULES)


# ---------------------------------------------------------------------------
# snapshot coverage (the Shard Flux precondition, ROADMAP 5c)


@plane_rule("snapshot-coverage")
def snapshot_coverage(facts: GraphFacts) -> Iterable[Diagnostic]:
    """Name every stateful exec lacking ``arranged_state``.

    Execs without it snapshot monolithically (one pickle blob): a
    replica boot re-unpickles the whole thing and a live resize (Shard
    Flux) cannot move its state as key-range arrangements — the node
    pins the old shard count until the operator gains arranged-state
    support. Flags the node *statically*, before anyone attempts a
    resize against it."""
    from pathway_tpu.elastic.planner import monolithic_state_nodes

    for node, exec_name in monolithic_state_nodes(facts.order):
        yield Diagnostic(
            "snapshot-coverage",
            Severity.WARNING,
            f"{exec_name} snapshots monolithically (no arranged_state): "
            "replica boots re-unpickle its whole state and a live "
            "resize carries it forward unmoved instead of handing it "
            "over as key-range arrangements",
            node,
            fix_hint="implement arranged_state()/load_arranged_state() "
            "on the exec (see GroupByExec), or suppress with "
            'pw.analysis.suppress(table, "snapshot-coverage") if the '
            "operator's state is accepted as resize-pinned",
            data={"exec": exec_name},
        )


# ---------------------------------------------------------------------------
# pickle on the hot path (ROADMAP 5a precondition)


def _pickles_when_encoded(dtype: Any) -> bool:
    """True when a column of this DType falls through to the pickle
    fallback in the wire codec (parallel/wire.py _encode_column) and the
    segment encoder (persistence/segments.py _encode_col): object
    storage that is not a uniform-ndarray column."""
    from pathway_tpu.internals import dtype as dt

    if dtype is None or dtype is dt.NONE:
        return False
    if isinstance(dtype, dt.ArrayDType):
        # uniform ndarray columns stack into one dense buffer
        return False
    try:
        nd = dt.np_storage_dtype(dtype)
    except Exception:
        return False
    return getattr(nd, "hasobject", False)


def _object_columns(node: Any) -> list[tuple[str, Any]]:
    dtypes = getattr(node, "_column_dtypes", None) or {}
    return [(c, dt_) for c, dt_ in dtypes.items() if _pickles_when_encoded(dt_)]


@plane_rule("pickle-hot-path")
def pickle_hot_path(facts: GraphFacts) -> Iterable[Diagnostic]:
    """Object columns crossing the wire or segment encoders.

    Both encoders fall back to per-column pickle for object-dtype
    columns that are not uniform ndarrays (str/bytes/json/tuple/
    datetime/Optional[...]). On an exchange edge that cost is paid per
    routed batch per tick; in arranged-state segments it is paid per
    snapshot. Dictionary/offsets encodings (ROADMAP 5a) remove it —
    until then, this rule makes the hot-path pickles visible."""
    from pathway_tpu.elastic.planner import reshard_capable

    try:
        from pathway_tpu.parallel import exchange_topology

        sharded = exchange_topology()["sharding_active"]
    except Exception:
        sharded = False
    wire_sev = Severity.WARNING if sharded else Severity.INFO

    seen: set[tuple[int, int, str]] = set()
    for node in facts.order:
        if node.id in facts.exchange_edges:
            for idx, inp in enumerate(node.inputs):
                for col, dt_ in _object_columns(inp):
                    key = (node.id, inp.id, col)
                    if key in seen:
                        continue
                    seen.add(key)
                    try:
                        # resolve prep columns (_g0, _a0_0) back to the
                        # user-facing source column where possible
                        label = facts.input_column_label(
                            node, col, side=min(idx, 1)
                        )
                    except Exception:
                        label = col
                    yield Diagnostic(
                        "pickle-hot-path",
                        wire_sev,
                        f"column {label!r} ({dt_}) crosses the shard "
                        f"exchange in front of {type(node).__name__} as "
                        "per-column pickle (wire codec object "
                        "fallback) — every routed batch pays "
                        "serialization on the tick path",
                        node,
                        fix_hint="store the payload as numeric/Array "
                        "columns, or keep object columns out of "
                        "exchanged tables until dictionary/offsets "
                        "encodings land (ROADMAP 5a)",
                        data={"column": col, "dtype": str(dt_)},
                    )
        if getattr(node, "is_stateful", False) and reshard_capable(node):
            for inp in node.inputs:
                for col, dt_ in _object_columns(inp):
                    key = (node.id, inp.id, col)
                    if key in seen:
                        continue
                    seen.add(key)
                    yield Diagnostic(
                        "pickle-hot-path",
                        Severity.INFO,
                        f"column {col!r} ({dt_}) enters the arranged "
                        f"state of {type(node).__name__} and will "
                        "per-column pickle in segment snapshots",
                        node,
                        fix_hint="numeric/Array payloads snapshot as "
                        "dense buffers; object columns re-pickle every "
                        "segment write",
                        data={"column": col, "dtype": str(dt_)},
                    )


# ---------------------------------------------------------------------------
# PATHWAY_* knob coherence


@plane_rule("knob-coherence")
def knob_coherence(facts: GraphFacts) -> Iterable[Diagnostic]:
    """Lint the ``PATHWAY_*`` environment for contradictions that today
    only fail at boot (ValueError in shard_map_from_env / QoSConfig
    .from_env) or silently degrade (a result cache that never
    invalidates, a gate with no deadline bound)."""
    import os

    from pathway_tpu.serving.config import (
        QoSConfig,
        serving_enabled_via_env,
    )

    def env(name: str) -> str:
        return os.environ.get(name, "").strip()

    # --- conflicting shard counts -------------------------------------
    raw_shards = env("PATHWAY_SERVING_SHARDS")
    raw_map = env("PATHWAY_SERVING_SHARD_MAP")
    shards = None
    if raw_shards:
        try:
            shards = int(raw_shards)
        except ValueError:
            yield Diagnostic(
                "knob-coherence",
                Severity.ERROR,
                f"PATHWAY_SERVING_SHARDS={raw_shards!r} is not an int",
                data={"knob": "PATHWAY_SERVING_SHARDS"},
            )
    shard_map = None
    if raw_map:
        try:
            from pathway_tpu.serving.router import shard_map_from_env

            shard_map = shard_map_from_env()
        except ValueError as exc:
            yield Diagnostic(
                "knob-coherence",
                Severity.ERROR,
                f"PATHWAY_SERVING_SHARD_MAP does not parse: {exc}",
                fix_hint="format: shard0host:port,shard0host:port|"
                "shard1host:port (| separates shards)",
                data={"knob": "PATHWAY_SERVING_SHARD_MAP"},
            )
    if shards is not None and shard_map is not None and len(
        shard_map
    ) != shards:
        yield Diagnostic(
            "knob-coherence",
            Severity.ERROR,
            f"conflicting shard counts: PATHWAY_SERVING_SHARDS={shards} "
            f"but PATHWAY_SERVING_SHARD_MAP describes "
            f"{len(shard_map)} shard(s) — the router would route "
            "against a fabric the engine does not run",
            fix_hint="make the map's |-separated shard count match "
            "PATHWAY_SERVING_SHARDS (or drop one of the knobs)",
            data={
                "knob": "PATHWAY_SERVING_SHARDS",
                "shards": shards,
                "map_shards": len(shard_map),
            },
        )

    # --- gated ingress without deadline bounds ------------------------
    gate_on = serving_enabled_via_env()
    cfg = None
    try:
        cfg = QoSConfig.from_env()
    except ValueError as exc:
        yield Diagnostic(
            "knob-coherence",
            Severity.ERROR,
            f"PATHWAY_SERVING_* does not parse: {exc}",
            data={"knob": "PATHWAY_SERVING_*"},
        )
    if gate_on and cfg is not None:
        if cfg.default_deadline_ms <= 0 or cfg.max_deadline_ms <= 0:
            yield Diagnostic(
                "knob-coherence",
                Severity.WARNING,
                "gated ingress without deadline bounds: "
                "PATHWAY_SERVING_ENABLED=1 but the deadline budget is "
                f"non-positive (DEADLINE_MS={cfg.default_deadline_ms}, "
                f"MAX_DEADLINE_MS={cfg.max_deadline_ms}) — queued "
                "requests can wait forever instead of shedding",
                fix_hint="set PATHWAY_SERVING_DEADLINE_MS and "
                "PATHWAY_SERVING_MAX_DEADLINE_MS to positive budgets",
                data={"knob": "PATHWAY_SERVING_DEADLINE_MS"},
            )
        elif cfg.default_deadline_ms > cfg.max_deadline_ms:
            yield Diagnostic(
                "knob-coherence",
                Severity.WARNING,
                f"PATHWAY_SERVING_DEADLINE_MS="
                f"{cfg.default_deadline_ms} exceeds "
                f"MAX_DEADLINE_MS={cfg.max_deadline_ms}: every "
                "default-budget request is silently clamped to the cap",
                fix_hint="lower DEADLINE_MS or raise MAX_DEADLINE_MS",
                data={"knob": "PATHWAY_SERVING_DEADLINE_MS"},
            )

    # --- cache without invalidation stream ----------------------------
    from pathway_tpu.serving.result_cache import cache_enabled_via_env

    if cache_enabled_via_env() and not env("PATHWAY_ROUTER_CACHE_WRITER"):
        yield Diagnostic(
            "knob-coherence",
            Severity.WARNING,
            "PATHWAY_ROUTER_CACHE=1 without "
            "PATHWAY_ROUTER_CACHE_WRITER: the hot-tenant result cache "
            "has no delta stream to invalidate against and serves "
            "stale results for the full TTL",
            fix_hint="point PATHWAY_ROUTER_CACHE_WRITER at the "
            "engine's delta feed (host:port), or disable the cache",
            data={"knob": "PATHWAY_ROUTER_CACHE"},
        )

    # --- tenancy armed with no gate to apply it -----------------------
    from pathway_tpu.serving.tenancy import tenancy_enabled_via_env

    if tenancy_enabled_via_env() and not gate_on:
        yield Diagnostic(
            "knob-coherence",
            Severity.INFO,
            "PATHWAY_TENANT_QOS=1 but PATHWAY_SERVING_ENABLED is off: "
            "per-tenant fair admission only applies inside the serving "
            "gate, so the knob is inert",
            fix_hint="set PATHWAY_SERVING_ENABLED=1 (or pass qos= to "
            "the rest_connector) to arm the gate tenancy rides on",
            data={"knob": "PATHWAY_TENANT_QOS"},
        )


# ---------------------------------------------------------------------------
# tick-scope coverage (PR 18: blind planes and silently-broken rooflines)


@plane_rule("tickscope-coverage")
def tickscope_coverage(facts: GraphFacts) -> Iterable[Diagnostic]:
    """Flag planes flying blind or with a broken roofline hook.

    INFO when a serving surface is live while the flight recorder is
    disabled (PATHWAY_TICKSCOPE=0): the first slow-tick incident on
    that plane will have no per-operator evidence to read back.
    WARNING when a plane has executed compiled ticks but the roofline
    has zero ``compiled_tick`` samples: the cost-analysis hook in
    engine/compile.py is silently broken (its registration is
    best-effort by design, so breakage shows up only here)."""
    from pathway_tpu.observability import tickscope

    status = tickscope.coverage_status()
    if status["serving_active"] and not status["recorder_enabled"]:
        yield Diagnostic(
            "tickscope-coverage",
            Severity.INFO,
            "serving surface live with the tick flight recorder "
            "disabled (PATHWAY_TICKSCOPE=0): slow-tick incidents on "
            "this plane will have no per-operator attribution",
            fix_hint="unset PATHWAY_TICKSCOPE (default-on) — the "
            "recorder's hot-loop cost is one `is None` check per "
            "node when idle and is covered by the obs_overhead bench "
            "budget when recording",
            data={"knob": "PATHWAY_TICKSCOPE"},
        )
    samples = status["roofline_samples"]
    if status["compiled_ticks"] > 0 and samples.get("compiled_tick", 0) == 0:
        yield Diagnostic(
            "tickscope-coverage",
            Severity.WARNING,
            f"{status['compiled_ticks']} compiled ticks executed but "
            "the roofline has zero compiled_tick samples: the "
            "cost-analysis observe hook (engine/compile.py "
            "_run_compiled) is silently broken and MFU attribution "
            "reads as 'no compiled work'",
            fix_hint="check that observability.tickscope imports "
            "cleanly in this environment; the hook swallows "
            "exceptions by contract, so an import/runtime error there "
            "only surfaces through this rule",
            data={"compiled_ticks": status["compiled_ticks"]},
        )


# ---------------------------------------------------------------------------
# autoscale coverage (PR 19: Flux Pilot — planes that CAN resize but
# nothing is watching, and controllers armed with nothing to watch)


@plane_rule("autoscale-coverage")
def autoscale_coverage(facts: GraphFacts) -> Iterable[Diagnostic]:
    """Flag control loops that are half-closed.

    WARNING when the plane is resizable (a sharded serving fabric is
    declared, or the graph holds reshard-capable stateful execs) but no
    Flux Pilot controller is armed: every surge is a page, not an
    actuation.  WARNING when a controller IS armed but not one
    ``PATHWAY_SLO_*`` target is set — its burn input is permanently
    None and the policy holds forever.  INFO when the controller is
    pinned (min_ranks == max_ranks): valid for a canary, but the loop
    can never act."""
    import os

    from pathway_tpu.autoscale import get_controller
    from pathway_tpu.elastic.planner import reshard_capable
    from pathway_tpu.observability.signals import slo_targets

    ctrl = get_controller()
    resizable = bool(os.environ.get("PATHWAY_SERVING_SHARD_MAP", "").strip())
    if not resizable:
        resizable = any(
            getattr(node, "is_stateful", False) and reshard_capable(node)
            for node in facts.order
        )
    if resizable and ctrl is None:
        yield Diagnostic(
            "autoscale-coverage",
            Severity.WARNING,
            "the plane is resizable (reshard-capable state or a sharded "
            "serving fabric) but no Flux Pilot controller is armed: "
            "SLO burns page a human instead of actuating a resize",
            fix_hint="arm one with pathway_tpu.autoscale.arm_controller"
            "(actuator, ranks=N, start=True) — or accept manual "
            "resizes and suppress this finding",
            data={"controller": None},
        )
    if ctrl is not None:
        targets = slo_targets()
        if not targets:
            yield Diagnostic(
                "autoscale-coverage",
                Severity.WARNING,
                "a Flux Pilot controller is armed but zero PATHWAY_SLO_* "
                "targets are set: its burn input is permanently None, "
                "so the policy holds forever and the loop is inert",
                fix_hint="declare at least one SLO target (e.g. "
                "PATHWAY_SLO_SHED_RATE=0.01) so the sampler produces "
                "burn rates the policy can act on",
                data={"slo_targets": 0},
            )
        cfg = ctrl.policy.config
        if cfg.min_ranks == cfg.max_ranks:
            yield Diagnostic(
                "autoscale-coverage",
                Severity.INFO,
                f"the armed controller is pinned at "
                f"{cfg.min_ranks} rank(s) (min_ranks == max_ranks): "
                "decisions always hold — fine for a canary, inert as a "
                "control loop",
                fix_hint="widen PATHWAY_AUTOSCALE_MIN_RANKS / "
                "PATHWAY_AUTOSCALE_MAX_RANKS to give the policy a band",
                data={
                    "min_ranks": cfg.min_ranks,
                    "max_ranks": cfg.max_ranks,
                },
            )
