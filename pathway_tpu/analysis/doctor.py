"""The Graph Doctor: run the registered rules over a declared graph.

Three entry points share this module:

- ``pw.run(diagnostics="warn"|"error"|"off")`` — internals/run.py calls
  :func:`run_doctor` before the engine builds a Runtime;
- ``python -m pathway_tpu.analysis script.py`` — builds the script's
  graph without executing it, then reports (analysis/__main__.py);
- ``pw.debug.diagnose(table)`` — notebook-friendly report scoped to the
  graph feeding one table.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Iterable

from pathway_tpu.analysis.diagnostics import Diagnostic, Severity
from pathway_tpu.analysis.graph_facts import GraphFacts
from pathway_tpu.analysis.rules import RULES, default_rules

logger = logging.getLogger("pathway_tpu.analysis")

_SUPPRESS_ATTR = "_doctor_suppress"


def suppress(table_or_node: Any, *rule_ids: str) -> Any:
    """Silence specific rules for one table/node (and only that node):
    ``pw.analysis.suppress(stats, "unbounded-state")``. Returns its
    argument so it chains inside pipeline definitions.

    Findings anchored at engine nodes the user API never hands out (the
    GroupByNode under a ``groupby().reduce()`` result, the JoinNode under
    a ``join().select()``) are silenced by suppressing the result table —
    the anchored node's direct consumer.
    """
    node = getattr(table_or_node, "_node", table_or_node)
    current = set(getattr(node, _SUPPRESS_ATTR, ()))
    current.update(rule_ids)
    setattr(node, _SUPPRESS_ATTR, frozenset(current))
    return table_or_node


def _suppressed(diag: Diagnostic, consumers: dict[int, list]) -> bool:
    if diag.node is None:
        return False
    # the anchored node, or its direct consumers: every operator node the
    # API keeps internal (GroupByNode, JoinNode, temporal joins) carries a
    # user-visible projection directly on top, so suppressing that result
    # table covers the operator's findings
    for n in (diag.node, *consumers.get(diag.node.id, ())):
        if diag.rule in getattr(n, _SUPPRESS_ATTR, ()):
            return True
    return False


@dataclass
class DoctorReport:
    diagnostics: list[Diagnostic] = field(default_factory=list)

    def __iter__(self):
        return iter(self.diagnostics)

    def __len__(self) -> int:
        return len(self.diagnostics)

    def by_rule(self, rule_id: str) -> list[Diagnostic]:
        return [d for d in self.diagnostics if d.rule == rule_id]

    def worst(self) -> Severity | None:
        return max(
            (d.severity for d in self.diagnostics), default=None
        )

    def count_at_least(self, severity: Severity) -> int:
        return sum(1 for d in self.diagnostics if d.severity >= severity)

    def format(
        self,
        min_severity: Severity = Severity.INFO,
        show_source: bool = True,
    ) -> str:
        shown = [
            d for d in self.diagnostics if d.severity >= min_severity
        ]
        if not shown:
            return "graph doctor: no findings"
        lines = [d.format(show_source=show_source) for d in shown]
        counts = {
            s: sum(1 for d in shown if d.severity == s)
            for s in reversed(Severity)
        }
        summary = ", ".join(
            f"{n} {s.name.lower()}" for s, n in counts.items() if n
        )
        lines.append(f"graph doctor: {len(shown)} finding(s) ({summary})")
        return "\n".join(lines)

    def to_list(self) -> list[dict]:
        return [d.to_dict() for d in self.diagnostics]


class GraphDoctorError(RuntimeError):
    """Raised by ``pw.run(diagnostics="error")`` before the engine starts
    when the doctor finds warning-or-worse problems."""

    def __init__(self, report: DoctorReport):
        self.report = report
        super().__init__(
            "graph doctor found problems (diagnostics='error'):\n"
            + report.format(min_severity=Severity.WARNING)
        )


_SEVERITY_ORDER = (Severity.ERROR, Severity.WARNING, Severity.INFO)


def run_doctor(
    outputs: Iterable[Any] | None = None,
    all_nodes: Iterable[Any] | None = None,
    rules: "dict | Iterable[str] | None" = None,
) -> DoctorReport:
    """Run the rule set over the declared graph and return the report.

    ``outputs`` defaults to the OutputNodes found in ``all_nodes``;
    ``all_nodes`` defaults to every node declared since the last
    ``G.clear()``. ``rules`` narrows to a subset (iterable of rule ids)
    or replaces the registry (dict)."""
    facts = GraphFacts(outputs=outputs, all_nodes=all_nodes)
    if rules is None:
        active = default_rules()
    elif isinstance(rules, dict):
        active = rules
    else:
        rules = list(rules)
        unknown = sorted(set(rules) - set(RULES))
        if unknown:
            raise ValueError(
                f"unknown rule id(s) {unknown}; "
                f"registered rules: {sorted(RULES)}"
            )
        active = {rid: RULES[rid] for rid in rules}
    diags: list[Diagnostic] = []
    for rule_id, fn in active.items():
        try:
            found = list(fn(facts))
        except Exception:
            logger.exception("graph doctor rule %r crashed", rule_id)
            continue
        diags.extend(
            d for d in found if not _suppressed(d, facts.consumers)
        )
    diags.sort(key=lambda d: (-int(d.severity), d.rule))
    return DoctorReport(diags)


def run_plane_doctor(
    outputs: Iterable[Any] | None = None,
    all_nodes: Iterable[Any] | None = None,
    rules: "dict | Iterable[str] | None" = None,
) -> DoctorReport:
    """Run the deployment-scope rules (analysis/plane.py) and return
    the report: snapshot coverage for elastic resizes, pickle-on-hot-
    path over the wire/segment encoders, ``PATHWAY_*`` knob coherence.
    Unlike :func:`run_doctor` this is meaningful even with NO declared
    graph (the knob lint is pure environment), so an empty graph is
    fine."""
    from pathway_tpu.analysis.plane import PLANE_RULES, default_plane_rules

    facts = GraphFacts(outputs=outputs, all_nodes=all_nodes)
    if rules is None:
        active = default_plane_rules()
    elif isinstance(rules, dict):
        active = rules
    else:
        rules = list(rules)
        unknown = sorted(set(rules) - set(PLANE_RULES))
        if unknown:
            raise ValueError(
                f"unknown plane rule id(s) {unknown}; "
                f"registered plane rules: {sorted(PLANE_RULES)}"
            )
        active = {rid: PLANE_RULES[rid] for rid in rules}
    diags: list[Diagnostic] = []
    for rule_id, fn in active.items():
        try:
            found = list(fn(facts))
        except Exception:
            logger.exception("plane doctor rule %r crashed", rule_id)
            continue
        diags.extend(
            d for d in found if not _suppressed(d, facts.consumers)
        )
    diags.sort(key=lambda d: (-int(d.severity), d.rule))
    return DoctorReport(diags)


def check_before_run(seeds: list, mode: str) -> None:
    """The pw.run() integration: run the doctor and act per `mode`
    ("off" | "warn" | "error"). Raises GraphDoctorError in error mode
    when any warning-or-worse diagnostic is found."""
    if mode in (None, "off"):
        return
    if mode not in ("warn", "error"):
        raise ValueError(
            f"diagnostics={mode!r}: expected 'off', 'warn' or 'error'"
        )
    report = run_doctor(outputs=seeds)
    if mode == "error" and report.count_at_least(Severity.WARNING):
        raise GraphDoctorError(report)
    for diag in report:
        if diag.severity >= Severity.WARNING:
            logger.warning("%s", diag.format(show_source=False))
        else:
            logger.info("%s", diag.format(show_source=False))
