"""Built-in Graph Doctor rules + the extensible registry.

A rule is a function `(facts: GraphFacts) -> Iterable[Diagnostic]`
registered under a stable id. Third-party packages (or user conftest
code) add rules with::

    from pathway_tpu.analysis import rule

    @rule("my-rule")
    def check_my_invariant(facts):
        for node in facts.order:
            ...
            yield Diagnostic("my-rule", Severity.WARNING, "...", node)

Rule ids double as the suppression handles:
``pw.analysis.suppress(table, "unbounded-state")``.
"""

from __future__ import annotations

from collections import Counter
from typing import Callable, Iterable

from pathway_tpu.analysis.diagnostics import Diagnostic, Severity
from pathway_tpu.analysis.graph_facts import GraphFacts
from pathway_tpu.engine.nodes import (
    ConcatNode,
    DeduplicateNode,
    GroupByNode,
    InputNode,
    JoinNode,
    Node,
    OutputNode,
    RowwiseNode,
    UniverseSetOpNode,
)
from pathway_tpu.engine.temporal_nodes import (
    AsofJoinNode,
    AsofNowJoinNode,
    IntervalJoinNode,
)
from pathway_tpu.internals.expression import iter_apply_expressions

RuleFn = Callable[[GraphFacts], Iterable[Diagnostic]]

RULES: dict[str, RuleFn] = {}


def rule(rule_id: str) -> Callable[[RuleFn], RuleFn]:
    """Register a Graph Doctor rule under `rule_id` (replacing any
    previous registration of the same id)."""

    def deco(fn: RuleFn) -> RuleFn:
        RULES[rule_id] = fn
        return fn

    return deco


# ---------------------------------------------------------------------------
# 1. dead nodes / dead columns


@rule("dead-node")
def dead_nodes(facts: GraphFacts) -> Iterable[Diagnostic]:
    """Declared nodes whose results can never reach any registered
    output — built but silently discarded work."""
    if not facts.outputs:
        return  # nothing registered yet: reachability is undefined
    for node in facts.order:
        if node.id in facts.reachable or isinstance(node, OutputNode):
            continue
        # flag only the FRONTIER: dead nodes none of whose consumers are
        # also dead (the deepest dead table) — one diagnostic per dead
        # chain instead of one per node
        if any(
            c.id not in facts.reachable for c in facts.consumers[node.id]
        ):
            continue
        if node.column_names and all(
            c.startswith("_") for c in node.column_names
        ):
            continue  # library scaffolding (probe/prep tables), not user work
        kind = "source" if isinstance(node, InputNode) else "table"
        yield Diagnostic(
            "dead-node",
            Severity.WARNING,
            f"this {kind} never reaches any output; it is built but its "
            "rows are discarded",
            node,
            fix_hint="write/subscribe it, feed it into a consumed table, "
            "or delete the declaration",
        )


@rule("dead-column")
def dead_columns(facts: GraphFacts) -> Iterable[Diagnostic]:
    """Computed columns no downstream consumer ever reads."""
    from pathway_tpu.engine.expression_eval import InternalColRef

    for node in facts.order:
        if not isinstance(node, RowwiseNode):
            continue
        if node.id not in facts.reachable or not facts.consumers[node.id]:
            continue  # dead-node territory / externally captured
        live = facts.live_columns.get(node.id)
        if live is None:
            continue
        for name in node.column_names:
            if name in live or name.startswith("_"):
                continue  # "_"-prefixed: engine-internal prep columns
            if isinstance(node.exprs.get(name), InternalColRef):
                continue  # zero-cost passthrough, not computed work
            yield Diagnostic(
                "dead-column",
                Severity.INFO,
                f"column {name!r} is computed but never read by any "
                "consumer on the way to an output",
                node,
                fix_hint=f"drop {name!r} from the select/with_columns, or "
                "consume it downstream",
                data={"column": name},
            )


# ---------------------------------------------------------------------------
# 2. unbounded state

_STATEFUL_JOINS = (JoinNode, IntervalJoinNode, AsofJoinNode, AsofNowJoinNode)


@rule("unbounded-state")
def unbounded_state(facts: GraphFacts) -> Iterable[Diagnostic]:
    """Stateful operators fed by a streaming source with no temporal
    guard (Forget/Buffer/Freeze from `behavior=`) on the path and no
    instance cap: their keyed state grows without bound for the lifetime
    of the run."""
    for node in facts.order:
        if not getattr(node, "is_stateful", False):
            continue
        if not facts.has_unguarded_streaming_input(node):
            continue
        if isinstance(node, GroupByNode):
            if getattr(node, "_windowed", False):
                yield Diagnostic(
                    "unbounded-state",
                    Severity.INFO,
                    "windowed aggregation over a streaming source without "
                    "a temporal behavior: state grows with the number of "
                    "open windows",
                    node,
                    fix_hint="pass behavior=pw.temporal.common_behavior("
                    "cutoff=...) (or exactly_once_behavior) to windowby "
                    "so closed windows free their state",
                )
            else:
                yield Diagnostic(
                    "unbounded-state",
                    Severity.WARNING,
                    "groupby over a streaming source holds one aggregate "
                    "per distinct key forever: state grows without bound",
                    node,
                    fix_hint="aggregate inside windowby(...) with a "
                    "temporal behavior, or bound the input with "
                    "a Forget (pw.temporal) before grouping",
                )
        elif isinstance(node, _STATEFUL_JOINS):
            yield Diagnostic(
                "unbounded-state",
                Severity.WARNING,
                f"{type(node).__name__.removesuffix('Node')} over a "
                "streaming source retains every row of both sides "
                "forever: state grows without bound",
                node,
                fix_hint="use asof_now semantics for query streams, add a "
                "temporal behavior, or bound the inputs with a window",
            )
        elif isinstance(node, DeduplicateNode):
            if node.instance_cols:
                yield Diagnostic(
                    "unbounded-state",
                    Severity.WARNING,
                    "deduplicate over a streaming source keeps one entry "
                    "per distinct instance: state grows with instance "
                    "cardinality",
                    node,
                    fix_hint="drop instance= for a single bounded slot, "
                    "pick a low-cardinality instance, or bound the input "
                    "temporally",
                )
        # other stateful nodes (sort, ix, aligned select, ...) also grow,
        # but proportionally to the LIVE key set, which retractions bound;
        # flagging them would be noise


# ---------------------------------------------------------------------------
# 3. universe safety


def _rel(a, b) -> str:
    if a is None or b is None:
        return "unknown"
    if a is b:
        return "equal"
    if a.is_subset_of(b) or b.is_subset_of(a):
        return "subset"
    if a.is_disjoint_from(b):
        return "disjoint"
    return "unrelated"


@rule("universe-safety")
def universe_safety(facts: GraphFacts) -> Iterable[Diagnostic]:
    """Re-checks the key-set relations row-aligned operators depend on,
    surfaced as diagnostics with declaration-site tracebacks instead of
    the bare build/runtime exceptions."""
    for node in facts.order:
        if isinstance(node, RowwiseNode) and len(node.inputs) > 1:
            primary = getattr(node.inputs[0], "_universe", None)
            for other in node.inputs[1:]:
                r = _rel(primary, getattr(other, "_universe", None))
                if r == "equal":
                    continue
                if r == "subset":
                    yield Diagnostic(
                        "universe-safety",
                        Severity.INFO,
                        "row-aligned expression mixes tables related only "
                        "by a subset promise; missing keys surface at run "
                        "time",
                        node,
                        fix_hint="verify the promise "
                        "(promise_is_subset_of / with_universe_of) holds "
                        "for every input, or join explicitly",
                    )
                else:
                    yield Diagnostic(
                        "universe-safety",
                        Severity.ERROR,
                        "row-aligned expression mixes tables over "
                        f"{r} universes: rows cannot be matched by key",
                        node,
                        fix_hint="use with_universe_of / "
                        "pw.universes.promise_is_subset_of to assert how "
                        "the key sets relate, or join the tables instead",
                    )
        elif isinstance(node, UniverseSetOpNode) and node.mode == "restrict":
            if getattr(node, "_intentional_restrict", False):
                continue  # having(): dropping missing keys IS the point
            primary = getattr(node.inputs[0], "_universe", None)
            for other in node.inputs[1:]:
                r = _rel(primary, getattr(other, "_universe", None))
                if r in ("equal", "subset"):
                    continue
                yield Diagnostic(
                    "universe-safety",
                    Severity.WARNING,
                    "with_universe_of/restrict over universes with no "
                    "declared relation: rows missing from the target key "
                    "set silently drop",
                    node,
                    fix_hint="promise the subset relation explicitly "
                    "(pw.universes.promise_is_subset_of) so the intent "
                    "is checked",
                )
        elif isinstance(node, ConcatNode):
            for i, a in enumerate(node.inputs):
                ua = getattr(a, "_universe", None)
                for b in node.inputs[i + 1:]:
                    r = _rel(ua, getattr(b, "_universe", None))
                    if r == "disjoint":
                        yield Diagnostic(
                            "universe-safety",
                            Severity.INFO,
                            "concat relies on a pairwise-disjointness "
                            "PROMISE; a key collision would only surface "
                            "at run time",
                            node,
                            fix_hint="use concat_reindex to rehash ids "
                            "if disjointness is not structurally "
                            "guaranteed",
                        )
                    elif r == "unrelated":
                        yield Diagnostic(
                            "universe-safety",
                            Severity.ERROR,
                            "concat over universes that are not promised "
                            "disjoint: duplicate keys would collide",
                            node,
                            fix_hint="call pw.universes."
                            "promise_are_pairwise_disjoint first, or use "
                            "concat_reindex",
                        )


# ---------------------------------------------------------------------------
# 4. shard safety (the parallel/ layer)

_ORDER_DEPENDENT_REDUCERS = {
    "stateful": Severity.WARNING,  # arbitrary combine fn: not provably
    # commutative/associative — cross-shard merge order is unspecified
    "earliest": Severity.INFO,  # tie order at equal times is
    "latest": Severity.INFO,  # arrival-dependent across shards
}


@rule("shard-exchange")
def shard_exchange(facts: GraphFacts) -> Iterable[Diagnostic]:
    """Operators whose key columns force a row exchange when the engine
    runs sharded (PATHWAY_ENGINE_SHARDS / multi-process DCN)."""
    for node in facts.order:
        fx = facts.exchange_edges.get(node.id)
        if not fx:
            continue
        edges = [
            (
                label,
                [
                    facts.input_column_label(node, k, side)
                    for k in keys
                ],
            )
            for side, (label, keys) in enumerate(fx)
        ]
        desc = "; ".join(
            f"{label} routed by ({', '.join(keys) or 'id'})"
            for label, keys in edges
        )
        yield Diagnostic(
            "shard-exchange",
            Severity.INFO,
            f"forces a row exchange under sharding: {desc}",
            node,
            data={"edges": [keys for _l, keys in edges]},
        )


@rule("shard-nondeterminism")
def shard_nondeterminism(facts: GraphFacts) -> Iterable[Diagnostic]:
    """Non-deterministic UDFs whose values cross an exchange boundary:
    re-evaluation on retraction/replay may produce different values on
    different shards, breaking cross-replica consistency (the EQuARX
    concern applied to the dataflow layer)."""
    # nodes downstream of which an exchange occurs
    exchanging = set(facts.exchange_edges)
    feeds_exchange: dict[int, bool] = {}
    for node in reversed(facts.order):
        feeds_exchange[node.id] = node.id in exchanging or any(
            feeds_exchange.get(c.id, False)
            for c in facts.consumers[node.id]
        )
    for node in facts.order:
        if not isinstance(node, RowwiseNode):
            continue
        if not feeds_exchange.get(node.id, False):
            continue
        bad = []
        for name, e in node.exprs.items():
            for a in iter_apply_expressions(e):
                if a._deterministic is False:
                    bad.append(
                        getattr(a, "_udf_name", None) or f"column {name!r}"
                    )
        for label in dict.fromkeys(bad):
            yield Diagnostic(
                "shard-nondeterminism",
                Severity.WARNING,
                f"non-deterministic UDF {label} feeds an exchange "
                "boundary: retraction replay may route or value rows "
                "differently across shards",
                node,
                fix_hint="declare the UDF deterministic=True if it is, "
                "or materialize its result before the exchange (e.g. via "
                "a connector) so every shard sees one value",
            )


@rule("shard-reducer")
def shard_reducer(facts: GraphFacts) -> Iterable[Diagnostic]:
    """Reducers that are not commutative/associative merge-safe when a
    groupby runs sharded."""
    for node in facts.order:
        if not isinstance(node, GroupByNode):
            continue
        for out_col, spec in node.reducer_specs.items():
            sev = _ORDER_DEPENDENT_REDUCERS.get(spec.kind)
            if sev is None:
                # tuple/ndarray without an explicit sort key depend on
                # arrival order per group
                if spec.kind in ("tuple", "ndarray") and not node.sort_by:
                    sev = Severity.INFO
                else:
                    continue
            label = facts.output_column_label(node, out_col)
            yield Diagnostic(
                "shard-reducer",
                sev,
                f"reducer {spec.kind!r} (column {label!r}) is "
                "order-dependent: under sharding its result depends on "
                "per-shard arrival order",
                node,
                fix_hint="use a commutative reducer (sum/count/min/max), "
                "add sort_by= to fix the order, or accept "
                "run-to-run variation",
                data={"reducer": spec.kind, "column": label},
            )


# ---------------------------------------------------------------------------
# 5. serving admission


@rule("serving-admission")
def serving_admission(facts: GraphFacts) -> Iterable[Diagnostic]:
    """REST ingress with no Surge Gate: every HTTP request drops
    straight into the InputSession, so overload manifests as unbounded
    queueing (and unbounded memory) instead of explicit shedding."""
    for node in facts.order:
        if not isinstance(node, InputNode):
            continue
        subject = getattr(getattr(node, "source", None), "subject", None)
        # type-name check: the http module (aiohttp) need not be loaded
        # for graphs that don't use it
        if subject is None or type(subject).__name__ != "RestServerSubject":
            continue
        if getattr(subject, "_qos", None) is not None:
            continue
        yield Diagnostic(
            "serving-admission",
            Severity.WARNING,
            "rest_connector ingress has no admission bound: under "
            "overload, requests queue without limit instead of shedding "
            "with 429/Retry-After, and nothing batches or expires them",
            node,
            fix_hint="pass qos=pathway_tpu.serving.QoSConfig(...) to "
            "rest_connector / run_server (or set "
            "PATHWAY_SERVING_ENABLED=1) to put the endpoint behind the "
            "Surge Gate",
        )


# ---------------------------------------------------------------------------
# 5a. replicated serving (Replica Shield)


@rule("unreplicated-serving")
def unreplicated_serving(facts: GraphFacts) -> Iterable[Diagnostic]:
    """A gated REST ingress serving an external index with NO stale
    read path and NO replica set: during any recovery window (peer
    failure, restore replay, supervised restart) every read hard-503s
    for the whole window — the Surge Gate can shed politely, but
    nothing can answer.  PR 8's stale responder or a Replica Shield
    replica set (serving/replica.py + serving/router.py) each close the
    gap; INFO when replicas exist but nothing bounds staleness, so a
    partitioned writer silently serves ever-older data.  Shard Harbor
    facets: WARNING when a replicated plane has no standby writer
    (PATHWAY_REPL_STANDBY — the single ingest writer is an SPOF), INFO
    when the shard layout leaves some key range with a single owner."""
    import os

    from pathway_tpu.engine.index_node import ExternalIndexNode

    index_nodes = [
        n for n in facts.order if isinstance(n, ExternalIndexNode)
    ]
    if not index_nodes:
        return
    replicas = [
        u
        for u in os.environ.get("PATHWAY_SERVING_REPLICAS", "").split(",")
        if u.strip()
    ]
    # a Shard Harbor plane may be configured ONLY via the shard map —
    # its members ARE the replica set.  Reuse the router's parser (the
    # single source of truth); a torn map is the router's boot error,
    # not this rule's concern, so fall back to the count heuristics.
    from pathway_tpu.serving.router import shard_map_from_env

    try:
        shard_map = shard_map_from_env()
    except ValueError:
        shard_map = None
    if shard_map:
        replicas = replicas + [u for part in shard_map for u in part]
    from pathway_tpu.serving import degrade

    first_gated = None
    for node in facts.order:
        if not isinstance(node, InputNode):
            continue
        subject = getattr(getattr(node, "source", None), "subject", None)
        if subject is None or type(subject).__name__ != "RestServerSubject":
            continue
        if getattr(subject, "_qos", None) is None:
            continue  # ungated ingress is serving-admission's finding
        route = getattr(subject, "_route", "/")
        if not replicas and degrade.stale_responder(route) is None:
            yield Diagnostic(
                "unreplicated-serving",
                Severity.WARNING,
                f"gated REST ingress {route!r} serves an external index "
                "with no stale responder registered and no replica set "
                "configured: every read hard-503s for the entire "
                "recovery window (restore replay, peer failure, "
                "supervised restart)",
                node,
                fix_hint="register a degraded answer path with "
                "pathway_tpu.serving.degrade.register_stale_responder("
                f"{route!r}, fn), or configure read replicas "
                "(PATHWAY_SERVING_REPLICAS + serving/replica.py) behind "
                "the failover router",
                data={"route": route, "index_nodes": len(index_nodes)},
            )
        elif replicas:
            if not os.environ.get("PATHWAY_SERVING_MAX_STALENESS_MS", ""):
                yield Diagnostic(
                    "unreplicated-serving",
                    Severity.INFO,
                    f"REST ingress {route!r} has {len(replicas)} "
                    "replica(s) configured but max-staleness is "
                    "unbounded: a partitioned or dead writer keeps "
                    "serving ever-older answers with no shed point",
                    node,
                    fix_hint="set PATHWAY_SERVING_MAX_STALENESS_MS (or "
                    "have clients send x-pathway-max-staleness-ms) so "
                    "reads past the freshness bound shed explicitly "
                    "with 503 + Retry-After",
                    data={"route": route, "replicas": len(replicas)},
                )
            if not os.environ.get("PATHWAY_REPL_STANDBY", ""):
                yield Diagnostic(
                    "unreplicated-serving",
                    Severity.WARNING,
                    f"REST ingress {route!r} has a replicated read "
                    "plane but NO standby writer configured: the "
                    "single ingest writer is the last serving SPOF — "
                    "kill it and every replica serves permanently "
                    "stale data with nothing publishing deltas, "
                    "snapshotting, or ingesting",
                    node,
                    fix_hint="run a standby writer (python -m "
                    "pathway_tpu.parallel.standby -- <writer argv>) "
                    "and point replicas at its takeover endpoint via "
                    "PATHWAY_REPL_STANDBY=host:port",
                    data={"route": route, "replicas": len(replicas)},
                )
        if first_gated is None:
            first_gated = node
    # Shard Harbor: a shard whose key range has ONE owner turns any
    # single member death into a partial-corpus outage (bounded reads
    # shed 503 for that key range until it recovers).  One plane-level
    # finding, anchored at the first gated ingress.
    if first_gated is None:
        return
    if shard_map:
        # the map names exact ownership: per-shard claims are precise
        single_owner = [
            s for s, part in enumerate(shard_map) if len(part) == 1
        ]
        if single_owner:
            yield Diagnostic(
                "unreplicated-serving",
                Severity.INFO,
                f"shard(s) {single_owner} of the serving plane have a "
                "single owner: one member death makes that key range "
                "unavailable (bounded reads shed 503 naming the "
                "shard) until the supervisor restarts it",
                first_gated,
                fix_hint="give every shard at least two members in "
                "PATHWAY_SERVING_SHARD_MAP",
                data={"single_owner_shards": single_owner},
            )
    else:
        try:
            n_shards = int(
                os.environ.get("PATHWAY_SERVING_SHARDS", "1") or 1
            )
        except ValueError:
            n_shards = 1
        # count-only pigeonhole: fewer than 2 members per shard on
        # average guarantees SOME shard has a single owner — which one
        # depends on the layout only the shard map can name
        if n_shards > 1 and replicas and len(replicas) < 2 * n_shards:
            yield Diagnostic(
                "unreplicated-serving",
                Severity.INFO,
                f"{len(replicas)} replica(s) over "
                f"PATHWAY_SERVING_SHARDS={n_shards} leaves at least "
                "one shard with a single owner (which one depends on "
                "the layout): one member death makes that key range "
                "unavailable (bounded reads shed 503 naming the "
                "shard) until the supervisor restarts it",
                first_gated,
                fix_hint="raise the replica count to at least "
                f"{2 * n_shards} (2 per shard), or declare exact "
                "ownership via PATHWAY_SERVING_SHARD_MAP for a "
                "per-shard diagnosis",
                data={"shards": n_shards, "replicas": len(replicas)},
            )


# ---------------------------------------------------------------------------
# 5c. tenant fairness (Tenant Weave)


@rule("tenant-fairness")
def tenant_fairness(facts: GraphFacts) -> Iterable[Diagnostic]:
    """A replicated serving plane with tenant-blind admission: the
    Surge Gate bounds TOTAL load, but one hot tenant can fill the
    admission queue (and drain the endpoint token bucket) so the shed
    lands on whoever arrives next — usually a tail tenant that sent one
    request all day.  WARNING when a gated REST ingress fronts a
    replicated plane without per-tenant fair admission
    (``PATHWAY_TENANT_QOS``); INFO when the router's result cache is
    armed without a delta-stream invalidation source, because a
    TTL-only cache can serve answers up to a full TTL staler than the
    corpus (time-based staleness only)."""
    import os

    from pathway_tpu.serving.result_cache import cache_enabled_via_env
    from pathway_tpu.serving.router import shard_map_from_env
    from pathway_tpu.serving.tenancy import tenancy_enabled_via_env

    replicas = [
        u
        for u in os.environ.get("PATHWAY_SERVING_REPLICAS", "").split(",")
        if u.strip()
    ]
    try:
        shard_map = shard_map_from_env()
    except ValueError:
        shard_map = None
    replicated = bool(
        replicas or shard_map or os.environ.get("PATHWAY_REPL_PORT", "")
    )
    first_gated = None
    for node in facts.order:
        if not isinstance(node, InputNode):
            continue
        subject = getattr(getattr(node, "source", None), "subject", None)
        if subject is None or type(subject).__name__ != "RestServerSubject":
            continue
        if getattr(subject, "_qos", None) is None:
            continue  # ungated ingress is serving-admission's finding
        if first_gated is None:
            first_gated = node
        route = getattr(subject, "_route", "/")
        if replicated and not tenancy_enabled_via_env():
            yield Diagnostic(
                "tenant-fairness",
                Severity.WARNING,
                f"gated REST ingress {route!r} on a replicated serving "
                "plane has tenant-blind admission: one hot tenant can "
                "fill the admission queue and the shed lands on the "
                "queue tail, starving every other tenant",
                node,
                fix_hint="set PATHWAY_TENANT_QOS=1 (per-tenant "
                "fair-share buckets + weighted-fair EDF ordering, "
                "identity from the x-pathway-tenant header; weight "
                "classes via PATHWAY_TENANT_WEIGHTS)",
                data={"route": route, "replicas": len(replicas)},
            )
    if first_gated is None:
        return
    if cache_enabled_via_env() and not os.environ.get(
        "PATHWAY_ROUTER_CACHE_WRITER", ""
    ):
        yield Diagnostic(
            "tenant-fairness",
            Severity.INFO,
            "the router result cache is enabled "
            "(PATHWAY_ROUTER_CACHE=1) without a delta-stream "
            "invalidation source: entries expire by TTL only "
            "(PATHWAY_ROUTER_CACHE_TTL_MS), so a hit can be up to a "
            "full TTL staler than the corpus instead of provably "
            "current as of the stream's applied tick",
            first_gated,
            fix_hint="point PATHWAY_ROUTER_CACHE_WRITER=host:port at "
            "the writer's delta endpoint (PATHWAY_REPL_PORT) so each "
            "tick's changed keys evict exactly the affected entries",
        )


# ---------------------------------------------------------------------------
# 5d. generation serving (Token Loom)


@rule("generation-serving")
def generation_serving(facts: GraphFacts) -> Iterable[Diagnostic]:
    """A ``/generate`` route without a deadline bound or admission gate
    is unbounded DEVICE MEMORY, not just unbounded queueing: every
    admitted generation pins KV pages for its whole decode, so nothing
    ever reclaims them without deadline propagation, and an ungated
    ingress lets a burst exhaust the page pool for everyone.  WARNING
    on either; INFO when the KV page-pool size runs on the default —
    the pool IS the generation plane's memory budget and deserves an
    explicit statement (``PATHWAY_GENERATE_PAGES``)."""
    import os

    from pathway_tpu.generate.scheduler import (
        DEFAULT_PAGES,
        generate_enabled_via_env,
    )

    # graph-declared generate ingress: a rest_connector route named
    # like /generate feeding the engine
    gen_nodes = []
    for node in facts.order:
        if not isinstance(node, InputNode):
            continue
        subject = getattr(getattr(node, "source", None), "subject", None)
        if subject is None or type(subject).__name__ != "RestServerSubject":
            continue
        route = getattr(subject, "_route", "/") or "/"
        if "generate" not in str(route):
            continue
        gen_nodes.append((node, route, getattr(subject, "_qos", None)))
    # env-armed generation plane (serving/replica.py role):
    # PATHWAY_GENERATE=1 mounts /generate on the replica
    env_armed = generate_enabled_via_env()
    if not gen_nodes and not env_armed:
        return
    for node, route, qos in gen_nodes:
        if qos is None:
            yield Diagnostic(
                "generation-serving",
                Severity.WARNING,
                f"generate ingress {route!r} has no admission gate: "
                "every request starts a decode that pins KV pages "
                "until completion — an unbounded burst exhausts the "
                "page pool (device memory), not just the queue",
                node,
                fix_hint="pass qos=pathway_tpu.serving.QoSConfig(...) "
                "(or set PATHWAY_SERVING_ENABLED=1) so generations "
                "shed explicitly before touching the device",
                data={"route": route},
            )
    anchor = gen_nodes[0][0] if gen_nodes else None
    if env_armed or gen_nodes:
        deadline_bounded = bool(
            os.environ.get("PATHWAY_SERVING_DEADLINE_MS", "")
            or os.environ.get("PATHWAY_SERVING_MAX_DEADLINE_MS", "")
        )
        if not deadline_bounded:
            yield Diagnostic(
                "generation-serving",
                Severity.WARNING,
                "generation serving has no configured deadline bound: "
                "deadline propagation is what drops expired "
                "generations MID-decode and reclaims their KV pages — "
                "unbounded decode is unbounded device memory",
                anchor,
                fix_hint="set PATHWAY_SERVING_DEADLINE_MS (the default "
                "budget applied when x-pathway-deadline-ms is absent) "
                "and/or PATHWAY_SERVING_MAX_DEADLINE_MS (the clamp on "
                "client budgets) for the generate route",
            )
        if not os.environ.get("PATHWAY_GENERATE_PAGES", ""):
            yield Diagnostic(
                "generation-serving",
                Severity.INFO,
                "the KV page pool is running on its default size "
                f"({DEFAULT_PAGES} pages): the pool is the generation "
                "plane's device-memory budget — size it explicitly "
                "for the expected concurrent sequences x "
                "(prompt+max_tokens)/page_size",
                anchor,
                fix_hint="set PATHWAY_GENERATE_PAGES (and "
                "PATHWAY_GENERATE_PAGE_SIZE) to the planned budget",
            )


# ---------------------------------------------------------------------------
# 5b. recoverability (Phoenix Mesh)


@rule("unrecoverable-state")
def unrecoverable_state(facts: GraphFacts) -> Iterable[Diagnostic]:
    """Stateful nodes whose snapshots cannot participate in group
    recovery: a node fed (transitively) by BOTH a transient fixture and
    a persisted connector disables operator snapshots for the whole
    graph (persistence/_runtime_glue.py mixed-dependency guard), so a
    kill/restart must replay the FULL input log — recovery time grows
    with history instead of churn, and the Phoenix Mesh supervisor's
    restart budget buys much less."""
    tainted: set[int] = set()
    logged: set[int] = set()
    for node in facts.order:
        if isinstance(node, InputNode):
            if getattr(node.source, "transient", False):
                tainted.add(node.id)
            else:
                logged.add(node.id)
            continue
        if any(inp.id in tainted for inp in node.inputs):
            tainted.add(node.id)
        if any(inp.id in logged for inp in node.inputs):
            logged.add(node.id)
    for node in facts.order:
        if not getattr(node, "is_stateful", False):
            continue
        if node.id in tainted and node.id in logged:
            yield Diagnostic(
                "unrecoverable-state",
                Severity.INFO,
                "this stateful node mixes transient fixture input with a "
                "persisted connector: operator snapshots are disabled for "
                "the whole graph, so group recovery (Phoenix Mesh "
                "supervisor restart) replays the full input log instead "
                "of restoring the latest committed snapshot generation",
                node,
                fix_hint="feed the node from persisted connectors only, "
                "or give the fixture a persistent source (pw.io.*) so "
                "snapshots stay enabled",
            )


# ---------------------------------------------------------------------------
# 5c. elastic resharding (Shard Flux)


@rule("elastic-resharding")
def elastic_resharding(facts: GraphFacts) -> Iterable[Diagnostic]:
    """A supervised multi-rank group can resize N→M ranks with zero
    replay ONLY when every stateful exec snapshots as arrangements
    (``arranged_state`` — the segment-handoff substrate
    elastic/mesh.py re-partitions by jk).  Any stateful exec still on
    the monolithic-pickle path (e.g. the temporal_nodes interval/asof
    monoliths) pins the WHOLE group's resize to the log-replay
    fallback: its keyed state cannot be split by key range, so
    ``GroupSupervisor.resize`` carries it forward un-moved and a grown
    rank rebuilds it from the log.  WARNING once on the group, INFO
    naming each pinning exec."""
    from pathway_tpu.elastic.planner import reshard_capable
    from pathway_tpu.parallel import exchange_topology

    topo = exchange_topology()
    if topo["dcn_processes"] <= 1:
        return  # single-rank: nothing to resize live
    pinned = [
        node
        for node in facts.order
        if getattr(node, "is_stateful", False)
        and reshard_capable(node) is False
    ]
    if not pinned:
        return
    yield Diagnostic(
        "elastic-resharding",
        Severity.WARNING,
        f"this {topo['dcn_processes']}-rank group holds state that "
        f"cannot ride a key-range segment handoff: {len(pinned)} "
        "stateful exec(s) snapshot monolithically, so a live resize "
        "(GroupSupervisor.resize / elastic.mesh.reshard_stores) "
        "falls back to log replay for them — resize pause grows with "
        "history instead of moved key ranges",
        pinned[0],
        fix_hint="rebase the named execs onto arrangement-backed "
        "snapshots (arranged_state), or accept log-replay resizes "
        "for this graph",
    )
    for node in pinned:
        yield Diagnostic(
            "elastic-resharding",
            Severity.INFO,
            f"{type(node).__name__} snapshots monolithically (no "
            "arranged_state): its keyed state cannot be split by key "
            "range during an elastic resize",
            node,
        )


# ---------------------------------------------------------------------------
# 6. join vectorization

_ROWWISE_JOINS = (IntervalJoinNode, AsofJoinNode, AsofNowJoinNode)


@rule("join-vectorization")
def join_vectorization(facts: GraphFacts) -> Iterable[Diagnostic]:
    """Joins whose declared mode statically forces the rowwise path.
    Equijoins run on the columnar arrangement (delta-join) engine —
    roughly an order of magnitude faster per steady-state tick — unless
    PATHWAY_JOIN_ROWWISE pins them to the dict oracle; temporal joins
    (interval/asof/asof_now) have no vectorized path yet."""
    import os

    rowwise_forced = os.environ.get("PATHWAY_JOIN_ROWWISE", "") not in (
        "",
        "0",
    )
    for node in facts.order:
        if isinstance(node, JoinNode) and rowwise_forced:
            yield Diagnostic(
                "join-vectorization",
                Severity.WARNING,
                "PATHWAY_JOIN_ROWWISE=1 pins this join to the rowwise "
                "dict oracle: every steady-state tick loops per row in "
                "Python instead of probing the columnar arrangement "
                "(~5-10x slower)",
                node,
                fix_hint="unset PATHWAY_JOIN_ROWWISE (the oracle path "
                "exists for differential testing, not serving)",
            )
        elif isinstance(node, _ROWWISE_JOINS):
            yield Diagnostic(
                "join-vectorization",
                Severity.INFO,
                f"{type(node).__name__} always runs the rowwise "
                "touched-group path — its match rules (interval/asof "
                "bounds) have no columnar delta-join implementation yet; "
                "expect per-row Python cost on every tick",
                node,
                fix_hint="for equality-only match conditions prefer a "
                "plain join, which runs on the arrangement engine",
            )


# ---------------------------------------------------------------------------
# 7. graph stats

_STATE_ESTIMATES = {
    "GroupByNode": "O(distinct groups x reducer state)",
    "JoinNode": "O(left rows + right rows)",
    "UpdateRowsNode": "O(rows of both sides)",
    "SortNode": "O(live rows)",
    "DeduplicateNode": "O(distinct instances)",
    "IxNode": "O(rows of both sides)",
    "UniverseSetOpNode": "O(live rows)",
    "GradualBroadcastNode": "O(live rows)",
    "BufferNode": "O(rows held before the watermark)",
    "ForgetNode": "O(rows inside the retention window)",
    "RowwiseNode": "O(live rows x inputs)",
    "IntervalJoinNode": "O(rows inside the interval bounds)",
    "AsofJoinNode": "O(live rows of both sides)",
    "AsofNowJoinNode": "O(right rows + emitted matches)",
    "SessionAssignNode": "O(live rows per instance)",
}


def _compile_member(node: Node) -> bool:
    from pathway_tpu.engine.compile import classify_node

    try:
        return classify_node(node)[0]
    except Exception:
        return False


@rule("compile-boundary")
def compile_boundary(facts: GraphFacts) -> Iterable[Diagnostic]:
    """Tick Forge visibility: every exec that FORCES a stateless chain
    back to the per-operator interpreter — a node adjacent (producer or
    consumer) to compilable operators that is itself not lowerable — is
    named with its reason (object-valued expression, UDF, stateful
    state, ...), so a user wondering why /debug/graph shows a segment
    boundary can see the exact expression/operator that drew it.  INFO
    severity: boundaries are normal; the diagnostic is a map, not a
    complaint."""
    from pathway_tpu.engine.compile import classify_node

    for node in facts.order:
        try:
            ok, reason = classify_node(node)
        except Exception:
            continue
        if ok or reason == "__io__":
            continue
        # only boundaries that actually cut a chain are interesting:
        # the node must touch at least one compilable neighbor
        if not (
            any(_compile_member(i) for i in node.inputs)
            or any(
                _compile_member(c) for c in facts.consumers.get(node.id, [])
            )
        ):
            continue
        yield Diagnostic(
            "compile-boundary",
            Severity.INFO,
            f"compiled-tick chain boundary: this operator runs on the "
            f"interpreter ({reason}); the adjacent stateless chain is "
            f"fused up to here",
            node,
        )


# ---------------------------------------------------------------------------
# 8. observability coverage (Fleet Lens)


@rule("observability-coverage")
def observability_coverage(facts: GraphFacts) -> Iterable[Diagnostic]:
    """A replicated or sharded plane nobody can see: takeovers, ejections
    and reshards leave no record, and the first debugging tool arrives
    AFTER the incident.  WARNING when a replicated/sharded serving plane
    runs in this process with no monitoring server armed (no /metrics,
    /debug/signals, /debug/events, /fleet/* — and no signal sampler or
    crash hooks, which arming installs) or with tracing disabled (the
    stitched /fleet/trace view cannot cross this member); INFO when the
    signal sampler runs but no ``PATHWAY_SLO_*`` target is declared —
    burn rates have nothing to burn against."""
    import os

    from pathway_tpu.internals import monitoring_server
    from pathway_tpu.observability.signals import (
        get_sampler,
        signals_enabled,
        slo_targets,
    )
    from pathway_tpu.observability.tracing import get_tracer
    from pathway_tpu.serving.router import shard_map_from_env

    replicas = [
        u
        for u in os.environ.get("PATHWAY_SERVING_REPLICAS", "").split(",")
        if u.strip()
    ]
    try:
        shard_map = shard_map_from_env()
    except ValueError:
        shard_map = None
    replicated = bool(
        replicas or shard_map or os.environ.get("PATHWAY_REPL_PORT", "")
    )
    if replicated:
        with monitoring_server._servers_lock:
            armed = bool(monitoring_server._servers)
        if not armed:
            yield Diagnostic(
                "observability-coverage",
                Severity.WARNING,
                "replicated/sharded serving plane with no monitoring "
                "server armed in this process: no /metrics scrape, no "
                "SLO signal rings, no incident journal endpoint, no "
                "postmortem crash hooks — the fleet's failure story "
                "goes unrecorded",
                None,
                fix_hint="call "
                "pathway_tpu.internals.monitoring_server."
                "start_http_server() (pw.run(with_http_server=True)); "
                "arming it also starts the signal sampler and installs "
                "the crash hooks",
                data={
                    "replicas": len(replicas),
                    "shards": len(shard_map or []),
                },
            )
        if not get_tracer().enabled:
            yield Diagnostic(
                "observability-coverage",
                Severity.WARNING,
                "tracing is disabled (PATHWAY_TRACING=0) on a "
                "replicated/sharded plane: the stitched /fleet/trace "
                "view cannot cross this member, so a slow request's "
                "router -> replica -> writer path is invisible",
                None,
                fix_hint="unset PATHWAY_TRACING (default on) — the "
                "span ring is bounded and costs microseconds per hop",
            )
    if (get_sampler() is not None or signals_enabled()) and not slo_targets():
        yield Diagnostic(
            "observability-coverage",
            Severity.INFO,
            "the SLO signal sampler is armed but no PATHWAY_SLO_* "
            "target is declared: signal rings fill, burn rates have "
            "nothing to burn against, and /debug/signals reports "
            "trends without verdicts",
            None,
            fix_hint="declare targets, e.g. PATHWAY_SLO_SHED_RATE=0.01 "
            "PATHWAY_SLO_TTFT_P99_MS=500 PATHWAY_SLO_STALENESS_S=5 "
            "(see README 'Observability' for the full signal "
            "inventory)",
        )


@rule("graph-stats")
def graph_stats(facts: GraphFacts) -> Iterable[Diagnostic]:
    """One INFO report: node counts per type, exchange edges, estimated
    state per stateful operator, streaming/static source split."""
    from pathway_tpu.parallel import exchange_topology

    counts = Counter(type(n).__name__ for n in facts.order)
    n_stream = sum(
        1
        for n in facts.order
        if isinstance(n, InputNode) and facts.is_streaming(n)
    )
    n_static = sum(1 for n in facts.order if isinstance(n, InputNode)) - (
        n_stream
    )
    stateful = [n for n in facts.order if getattr(n, "is_stateful", False)]
    topo = exchange_topology()
    lines = [
        f"{len(facts.order)} nodes "
        f"({len(facts.reachable)} reach an output), "
        f"{n_stream} streaming + {n_static} static sources, "
        f"{len(stateful)} stateful operators, "
        f"{sum(len(v) for v in facts.exchange_edges.values())} exchange "
        f"edges (topology: {topo['engine_shards']} engine shard(s) x "
        f"{topo['dcn_processes']} process(es))",
        "node counts: "
        + ", ".join(f"{k}={v}" for k, v in sorted(counts.items())),
    ]
    if stateful:
        lines.append("estimated state:")
        for n in stateful:
            est = _STATE_ESTIMATES.get(type(n).__name__, "O(live rows)")
            lines.append(f"  {n!r}: {est}")
    yield Diagnostic("graph-stats", Severity.INFO, "\n".join(lines), None)


def default_rules() -> dict[str, RuleFn]:
    return dict(RULES)
