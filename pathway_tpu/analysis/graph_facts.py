"""Derived facts over a declared ParseGraph: reachability, streaming
provenance, temporal bounding, column liveness, exchange edges.

All rules consume one `GraphFacts` instance so each walk over the node
graph happens once per doctor run. The analyses are conservative: where
a node type is unknown the pass assumes it reads every input column and
propagates streaming-ness unchanged.
"""

from __future__ import annotations

from typing import Any, Iterable

from pathway_tpu.engine.nodes import (
    BufferNode,
    ConcatNode,
    DeduplicateNode,
    FilterNode,
    FlattenNode,
    ForgetNode,
    FreezeNode,
    GroupByNode,
    InputNode,
    IxNode,
    JoinNode,
    Node,
    OutputNode,
    ReindexNode,
    RowwiseNode,
    SortNode,
    UniverseSetOpNode,
    UpdateRowsNode,
)
from pathway_tpu.engine.runtime import StreamingSource, collect_nodes
from pathway_tpu.engine.sharded import exchange_facts

# operators that bound temporal state downstream: a Forget retracts rows
# past the watermark (the canonical state cap); Buffer/Freeze come from
# the same `behavior=` desugaring and mark a consciously-managed temporal
# scope (stdlib/temporal/temporal_behavior.py)
TEMPORAL_GUARDS = (ForgetNode, BufferNode, FreezeNode)


class GraphFacts:
    def __init__(
        self,
        outputs: Iterable[Node] | None = None,
        all_nodes: Iterable[Node] | None = None,
    ):
        if all_nodes is None:
            from pathway_tpu.engine.nodes import ALL_NODES

            all_nodes = list(ALL_NODES)
        self.outputs = list(outputs) if outputs is not None else [
            n for n in all_nodes if isinstance(n, OutputNode)
        ]
        # nodes reaching an output (topological, inputs first)
        self.reachable_order = collect_nodes(self.outputs)
        self.reachable = {n.id for n in self.reachable_order}
        # the WHOLE declared graph, outputs or not
        self.order = collect_nodes(list(all_nodes) + self.outputs)
        self.consumers: dict[int, list[Node]] = {n.id: [] for n in self.order}
        for node in self.order:
            for inp in node.inputs:
                self.consumers[inp.id].append(node)
        self._streaming = self._propagate_streaming()
        self._unguarded = self._propagate_unguarded_streaming()
        self.live_columns = self._column_liveness()
        self.exchange_edges: dict[int, list[tuple[str, tuple[str, ...]]]] = {}
        for node in self.order:
            fx = exchange_facts(node)
            if fx:
                self.exchange_edges[node.id] = fx

    # --- streaming provenance ---------------------------------------------

    @staticmethod
    def _is_streaming_input(node: Node) -> bool:
        return isinstance(node, InputNode) and isinstance(
            node.source, StreamingSource
        )

    def _propagate_streaming(self) -> dict[int, bool]:
        out: dict[int, bool] = {}
        for node in self.order:
            if isinstance(node, InputNode):
                out[node.id] = self._is_streaming_input(node)
            else:
                out[node.id] = any(out[i.id] for i in node.inputs)
        return out

    def _propagate_unguarded_streaming(self) -> dict[int, bool]:
        """True when some STREAMING source reaches the node with no
        temporal guard (Forget/Buffer/Freeze) anywhere on the path — the
        precondition for unbounded keyed state."""
        out: dict[int, bool] = {}
        for node in self.order:
            if isinstance(node, InputNode):
                out[node.id] = self._is_streaming_input(node)
            elif isinstance(node, TEMPORAL_GUARDS):
                out[node.id] = False
            else:
                out[node.id] = any(out[i.id] for i in node.inputs)
        return out

    def is_streaming(self, node: Node) -> bool:
        return self._streaming.get(node.id, False)

    def has_unguarded_streaming_input(self, node: Node) -> bool:
        return any(self._unguarded.get(i.id, False) for i in node.inputs)

    # --- user-facing column labels -----------------------------------------

    def input_column_label(self, node: Node, col: str, side: int = 0) -> str:
        """Name an operator's key column in user terms: prep columns a
        groupby/join manufactures (`_g0`, `_a0_0`) resolve through the
        RowwiseNode that computed them back to the referenced source
        column, when the prep is a plain reference."""
        from pathway_tpu.engine.expression_eval import InternalColRef

        side = min(side, len(node.inputs) - 1) if node.inputs else 0
        inp = node.inputs[side] if node.inputs else None
        if isinstance(inp, RowwiseNode):
            e = inp.exprs.get(col)
            if isinstance(e, InternalColRef) and e._name != "id":
                return e._name
        return col

    def output_column_label(self, node: Node, col: str) -> str:
        """Name an operator's output slot (`_agg1`) the way the consuming
        select exposes it to the user, when recoverable."""
        from pathway_tpu.engine.expression_eval import InternalColRef

        for c in self.consumers.get(node.id, ()):
            if not isinstance(c, RowwiseNode):
                continue
            try:
                idx = c.inputs.index(node)
            except ValueError:
                continue
            for uname, e in c.exprs.items():
                if (
                    isinstance(e, InternalColRef)
                    and e._input_index == idx
                    and e._name == col
                ):
                    return uname
        return col

    # --- column liveness ---------------------------------------------------

    def _column_liveness(self) -> dict[int, "set[str] | None"]:
        """Per node: the set of its output columns any consumer may read,
        or None for "all" (the conservative default). A superset of the
        runtime's `annotate_live_columns` (engine/runtime.py) — this pass
        understands more node types because it powers the dead-column
        diagnostic, not just the join fast path."""
        from pathway_tpu.engine.expression_eval import InternalColRef

        live: dict[int, set[str] | None] = {}
        for node in self.order:
            # terminal tables may be captured externally (pw.debug, io
            # writers added later): everything live unless consumed
            live[node.id] = set() if self.consumers[node.id] else None
        for node in self.outputs:
            live[node.id] = None

        def demand(node: Node, cols: "set[str] | None") -> None:
            if cols is None:
                live[node.id] = None
            elif live[node.id] is not None:
                live[node.id] |= cols  # type: ignore[operator]

        def expr_refs(exprs, n_inputs: int) -> list[set]:
            sets: list[set] = [set() for _ in range(n_inputs)]

            def walk(e):
                if isinstance(e, InternalColRef):
                    if e._name != "id" and 0 <= e._input_index < n_inputs:
                        sets[e._input_index].add(e._name)
                    return
                for c in e._children:
                    walk(c)

            for e in exprs:
                walk(e)
            return sets

        for node in reversed(self.order):
            own = live[node.id]
            if isinstance(node, RowwiseNode):
                per_input = expr_refs(node.exprs.values(), len(node.inputs))
                for pos, inp in enumerate(node.inputs):
                    demand(inp, per_input[pos])
            elif isinstance(node, FilterNode):
                refs = expr_refs([node.predicate], 1)[0]
                demand(node.inputs[0], None if own is None else refs | own)
            elif isinstance(node, ReindexNode):
                refs = expr_refs([node.key_expr], 1)[0]
                demand(node.inputs[0], None if own is None else refs | own)
            elif isinstance(node, GroupByNode):
                need = set(node.key_columns())
                if node.sort_by:
                    need.add(node.sort_by)
                for spec in node.reducer_specs.values():
                    need.update(spec.arg_cols)
                demand(node.inputs[0], need)
            elif isinstance(node, JoinNode):
                for side, prefix, on in (
                    (0, "l.", node.left_on),
                    (1, "r.", node.right_on),
                ):
                    if own is None:
                        demand(node.inputs[side], None)
                    else:
                        need = set(on)
                        need.update(
                            c[len(prefix):]
                            for c in own
                            if c.startswith(prefix)
                        )
                        demand(node.inputs[side], need)
            elif isinstance(node, SortNode):
                demand(node.inputs[0], set(node.key_columns()))
            elif isinstance(node, FlattenNode):
                if own is None:
                    demand(node.inputs[0], None)
                else:
                    need = {
                        c for c in own if c in node.inputs[0].column_names
                    }
                    need.add(node.flatten_col)
                    demand(node.inputs[0], need)
            elif isinstance(node, TEMPORAL_GUARDS):
                refs = {node.threshold_col, node.current_time_col}
                demand(node.inputs[0], None if own is None else refs | own)
            elif isinstance(
                node, (ConcatNode, UpdateRowsNode, UniverseSetOpNode)
            ):
                # pass-through column names (UniverseSetOp reads only the
                # primary input's values; the others gate by key)
                for inp in node.inputs:
                    shared = set(inp.column_names) & (own or set())
                    demand(inp, None if own is None else shared)
            elif isinstance(node, IxNode):
                refs = {node.ptr_col}
                demand(node.inputs[0], refs)
                demand(node.inputs[1], own)
            else:
                for inp in node.inputs:
                    demand(inp, None)
        return live
