"""Structured diagnostics emitted by the Graph Doctor.

Each diagnostic carries a stable rule id, a severity, the engine node it
is anchored to (with the declaration-site trace frame captured at build
time — engine/nodes.py Node.trace), and a fix hint. The ahead-of-time
stance mirrors XLA's compilation model: problems a static pass can prove
about the declared dataflow should surface before the engine runs.
"""

from __future__ import annotations

import enum
import linecache
from dataclasses import dataclass, field
from typing import Any


class Severity(enum.IntEnum):
    INFO = 1
    WARNING = 2
    ERROR = 3

    @classmethod
    def parse(cls, value: "Severity | str") -> "Severity":
        if isinstance(value, Severity):
            return value
        try:
            return cls[str(value).upper()]
        except KeyError:
            raise ValueError(
                f"unknown severity {value!r}; expected one of "
                f"{[s.name.lower() for s in cls]}"
            ) from None

    @property
    def tag(self) -> str:
        return {self.INFO: "I", self.WARNING: "W", self.ERROR: "E"}[self]


def node_provenance(node: Any) -> str:
    """`<GroupByNode#12> declared at file.py:10 in main` — repr + the user
    frame captured when the node was built."""
    if node is None:
        return "<graph>"
    out = repr(node)
    trace = getattr(node, "trace", None)
    if trace:
        fname, lineno, func = trace
        out += f" declared at {fname}:{lineno} in {func}"
    return out


def declaration_line(node: Any) -> str | None:
    """The source line that declared the node, when resolvable."""
    trace = getattr(node, "trace", None)
    if not trace:
        return None
    line = linecache.getline(trace[0], trace[1]).strip()
    return line or None


@dataclass
class Diagnostic:
    rule: str
    severity: Severity
    message: str
    node: Any = None
    fix_hint: str | None = None
    data: dict = field(default_factory=dict)

    def format(self, show_source: bool = True) -> str:
        lines = [
            f"[{self.severity.tag}] {self.rule}: {self.message}",
            f"    at {node_provenance(self.node)}",
        ]
        if show_source:
            src = declaration_line(self.node)
            if src:
                lines.append(f"       | {src}")
        if self.fix_hint:
            lines.append(f"    fix: {self.fix_hint}")
        return "\n".join(lines)

    def to_dict(self) -> dict:
        trace = getattr(self.node, "trace", None)
        return {
            "rule": self.rule,
            "severity": self.severity.name.lower(),
            "message": self.message,
            "node": repr(self.node) if self.node is not None else None,
            "trace": (
                {"file": trace[0], "line": trace[1], "function": trace[2]}
                if trace
                else None
            ),
            "fix_hint": self.fix_hint,
            **({"data": self.data} if self.data else {}),
        }
