"""pathway_tpu.analysis — the Graph Doctor.

A pre-execution static-analysis pass over the declared dataflow
(`ParseGraph`): walks the registered node graph BEFORE the engine starts
and emits structured diagnostics (rule id, severity, node provenance
with declaration-site trace, fix hint) — the correctness-tooling
counterpart of XLA's ahead-of-time compilation model.

Entry points:

- ``pw.run(diagnostics="warn"|"error"|"off")``
- ``python -m pathway_tpu.analysis <script.py>`` (build, don't execute)
- ``pw.debug.diagnose(table)``
- library use: ``run_doctor()`` / ``GraphFacts`` / ``@rule`` to extend.
"""

from pathway_tpu.analysis.diagnostics import (
    Diagnostic,
    Severity,
    node_provenance,
)
from pathway_tpu.analysis.doctor import (
    DoctorReport,
    GraphDoctorError,
    check_before_run,
    run_doctor,
    run_plane_doctor,
    suppress,
)
from pathway_tpu.analysis.graph_facts import GraphFacts
from pathway_tpu.analysis.lowering import (
    LoweringCase,
    LoweringReport,
    LoweringRuleViolation,
    check_tpu_block_rules,
    lane_pad,
    prove_lowering,
    write_manifest,
)
from pathway_tpu.analysis.plane import PLANE_RULES, plane_rule
from pathway_tpu.analysis.rules import RULES, default_rules, rule

__all__ = [
    "Diagnostic",
    "DoctorReport",
    "GraphDoctorError",
    "GraphFacts",
    "LoweringCase",
    "LoweringReport",
    "LoweringRuleViolation",
    "PLANE_RULES",
    "RULES",
    "Severity",
    "check_before_run",
    "check_tpu_block_rules",
    "default_rules",
    "lane_pad",
    "node_provenance",
    "plane_rule",
    "prove_lowering",
    "rule",
    "run_doctor",
    "run_plane_doctor",
    "suppress",
    "write_manifest",
]
