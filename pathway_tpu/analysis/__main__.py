"""``python -m pathway_tpu.analysis <script.py>`` — build, don't execute.

Runs the user script with ``pw.run``/``pw.run_all`` turned into no-ops,
so the script *declares* its dataflow exactly as it would in production
but the engine never starts; then the Graph Doctor reports over the
declared graph. Exit status is governed by ``--fail-on`` (default:
nonzero when any ERROR-severity finding exists), so the command slots
into CI next to a type-checker.

``--plane`` widens the scope from one graph to the deployment plane:
the plane rules (snapshot coverage, pickle-on-hot-path, ``PATHWAY_*``
knob coherence — analysis/plane.py) run alongside the graph rules, and
the Lowering Ledger AOT-proves every registered TPU kernel family
against the real Mosaic lowering pipeline with zero device access
(works under ``JAX_PLATFORMS=cpu``), writing the content-addressed
``LOWERING_r16.json`` manifest. The script argument becomes optional:
knob lint + kernel proofs are meaningful with no graph at all.
"""

from __future__ import annotations

import argparse
import json
import runpy
import sys

from pathway_tpu.analysis.diagnostics import Severity
from pathway_tpu.analysis.doctor import (
    DoctorReport,
    run_doctor,
    run_plane_doctor,
)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m pathway_tpu.analysis",
        description="Graph Doctor: static analysis over the dataflow a "
        "pathway_tpu script declares, without executing it. Doctor "
        "options go BEFORE the script path; everything after it is "
        "passed through to the script (like `python` itself).",
    )
    parser.add_argument(
        "script",
        nargs="?",
        default=None,
        help="pipeline script to analyze (optional with --plane)",
    )
    parser.add_argument(
        "script_args",
        nargs=argparse.REMAINDER,
        help="arguments passed through to the script's sys.argv",
    )
    parser.add_argument(
        "--json",
        action="store_true",
        help="emit diagnostics as a JSON document instead of text",
    )
    parser.add_argument(
        "--min-severity",
        default="info",
        choices=["info", "warning", "error"],
        help="hide findings below this severity (default: info)",
    )
    parser.add_argument(
        "--fail-on",
        default="error",
        choices=["error", "warning", "never"],
        help="exit nonzero when a finding at/above this severity exists "
        "(default: error)",
    )
    parser.add_argument(
        "--rule",
        action="append",
        dest="rules",
        metavar="RULE_ID",
        help="run only this rule (repeatable)",
    )
    parser.add_argument(
        "--plane",
        action="store_true",
        help="deployment-plane mode: run the plane rules (snapshot "
        "coverage, pickle-hot-path, PATHWAY_* knob lint) and AOT-prove "
        "every TPU kernel family device-free, writing the lowering "
        "manifest",
    )
    parser.add_argument(
        "--manifest",
        default=None,
        metavar="PATH",
        help="where --plane writes the content-addressed lowering "
        "manifest (default: ./LOWERING_r16.json; 'none' skips)",
    )
    parser.add_argument(
        "--prove-shape",
        action="append",
        dest="prove_shapes",
        metavar="FAMILY:k=v,...",
        help="prove one extra kernel shape in --plane mode, e.g. "
        "paged_attention:head_dim=129 or pallas_topk:k=10,pad=0 "
        "(repeatable); shapes the shared gate rejects become ERROR "
        "findings",
    )
    args = parser.parse_args(argv)

    if args.script is None and not args.plane:
        parser.error("a script is required unless --plane is given")

    if args.script is not None:
        import importlib

        # the module, not the re-exported `run` function: the build-only
        # flag lives in the module namespace
        run_mod = importlib.import_module("pathway_tpu.internals.run")

        # declare-only mode: pw.run()/run_all() inside the script return
        # without building a Runtime
        run_mod._build_only = True
        saved_argv = sys.argv
        sys.argv = [args.script] + args.script_args
        try:
            runpy.run_path(args.script, run_name="__main__")
        finally:
            sys.argv = saved_argv
            run_mod._build_only = False

    from pathway_tpu.internals import parse_graph

    seeds = list(parse_graph.G.outputs) or None

    diagnostics = []
    manifest_doc = None
    try:
        # --rule may name ids from either registry; unknown ids error
        graph_rule_ids = args.rules
        plane_rule_ids = args.rules
        if args.plane and args.rules:
            from pathway_tpu.analysis.plane import PLANE_RULES
            from pathway_tpu.analysis.rules import RULES

            unknown = sorted(
                set(args.rules) - set(RULES) - set(PLANE_RULES)
            )
            if unknown:
                raise ValueError(
                    f"unknown rule id(s) {unknown}; registered: "
                    f"{sorted(set(RULES) | set(PLANE_RULES))}"
                )
            graph_rule_ids = [r for r in args.rules if r in RULES]
            plane_rule_ids = [r for r in args.rules if r in PLANE_RULES]
        if args.script is not None and (
            graph_rule_ids is None or graph_rule_ids
        ):
            diagnostics.extend(
                run_doctor(outputs=seeds, rules=graph_rule_ids)
            )
        if args.plane and (plane_rule_ids is None or plane_rule_ids):
            diagnostics.extend(
                run_plane_doctor(outputs=seeds, rules=plane_rule_ids)
            )
    except ValueError as e:  # e.g. a typoed --rule id
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.plane:
        from pathway_tpu.analysis import lowering as ledger

        extra_cases = []
        for spec in args.prove_shapes or ():
            try:
                family, shape = ledger.parse_shape_spec(spec)
                extra_cases.append(ledger.case_for_shape(family, shape))
            except ValueError as e:
                print(f"error: {e}", file=sys.stderr)
                return 2
        lowering_report = ledger.prove_lowering()
        if extra_cases:
            extra = ledger.prove_lowering(cases=extra_cases)
            lowering_report.entries.extend(extra.entries)
            lowering_report.findings.extend(extra.findings)
        diagnostics.extend(lowering_report.findings)
        manifest_doc = lowering_report.to_manifest()
        if args.manifest != "none":
            path = args.manifest or ledger.MANIFEST_NAME
            ledger.write_manifest(lowering_report, path)

    report = DoctorReport(
        sorted(diagnostics, key=lambda d: (-int(d.severity), d.rule))
    )

    min_sev = Severity.parse(args.min_severity)
    if args.json:
        out: dict | list = [
            d.to_dict() for d in report if d.severity >= min_sev
        ]
        if args.plane:
            from pathway_tpu.serving.config import plane_knobs

            out = {
                "findings": out,
                "knobs": plane_knobs(),
                "lowering": manifest_doc,
            }
        print(json.dumps(out, indent=2, default=str))
    else:
        print(report.format(min_severity=min_sev))
        if manifest_doc is not None:
            counts: dict[str, int] = {}
            for case in manifest_doc["cases"]:
                counts[case["status"]] = counts.get(case["status"], 0) + 1
            summary = ", ".join(
                f"{n} {s}" for s, n in sorted(counts.items())
            )
            print(
                f"lowering ledger: {len(manifest_doc['cases'])} case(s) "
                f"({summary}) — sha256 "
                f"{manifest_doc['content_sha256'][:12]}"
            )

    if args.fail_on == "never":
        return 0
    threshold = Severity.parse(args.fail_on)
    return 1 if report.count_at_least(threshold) else 0


if __name__ == "__main__":
    sys.exit(main())
