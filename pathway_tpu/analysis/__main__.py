"""``python -m pathway_tpu.analysis <script.py>`` — build, don't execute.

Runs the user script with ``pw.run``/``pw.run_all`` turned into no-ops,
so the script *declares* its dataflow exactly as it would in production
but the engine never starts; then the Graph Doctor reports over the
declared graph. Exit status is governed by ``--fail-on`` (default:
nonzero when any ERROR-severity finding exists), so the command slots
into CI next to a type-checker.
"""

from __future__ import annotations

import argparse
import json
import runpy
import sys

from pathway_tpu.analysis.diagnostics import Severity
from pathway_tpu.analysis.doctor import run_doctor


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m pathway_tpu.analysis",
        description="Graph Doctor: static analysis over the dataflow a "
        "pathway_tpu script declares, without executing it. Doctor "
        "options go BEFORE the script path; everything after it is "
        "passed through to the script (like `python` itself).",
    )
    parser.add_argument("script", help="pipeline script to analyze")
    parser.add_argument(
        "script_args",
        nargs=argparse.REMAINDER,
        help="arguments passed through to the script's sys.argv",
    )
    parser.add_argument(
        "--json",
        action="store_true",
        help="emit diagnostics as a JSON list instead of text",
    )
    parser.add_argument(
        "--min-severity",
        default="info",
        choices=["info", "warning", "error"],
        help="hide findings below this severity (default: info)",
    )
    parser.add_argument(
        "--fail-on",
        default="error",
        choices=["error", "warning", "never"],
        help="exit nonzero when a finding at/above this severity exists "
        "(default: error)",
    )
    parser.add_argument(
        "--rule",
        action="append",
        dest="rules",
        metavar="RULE_ID",
        help="run only this rule (repeatable)",
    )
    args = parser.parse_args(argv)

    import importlib

    from pathway_tpu.internals import parse_graph

    # the module, not the re-exported `run` function: the build-only flag
    # lives in the module namespace
    run_mod = importlib.import_module("pathway_tpu.internals.run")

    # declare-only mode: pw.run()/run_all() inside the script return
    # without building a Runtime
    run_mod._build_only = True
    saved_argv = sys.argv
    sys.argv = [args.script] + args.script_args
    try:
        runpy.run_path(args.script, run_name="__main__")
    finally:
        sys.argv = saved_argv
        run_mod._build_only = False

    seeds = list(parse_graph.G.outputs) or None
    try:
        report = run_doctor(outputs=seeds, rules=args.rules)
    except ValueError as e:  # e.g. a typoed --rule id
        print(f"error: {e}", file=sys.stderr)
        return 2

    min_sev = Severity.parse(args.min_severity)
    if args.json:
        out = [
            d.to_dict() for d in report if d.severity >= min_sev
        ]
        print(json.dumps(out, indent=2, default=str))
    else:
        print(report.format(min_severity=min_sev))

    if args.fail_on == "never":
        return 0
    threshold = Severity.parse(args.fail_on)
    return 1 if report.count_at_least(threshold) else 0


if __name__ == "__main__":
    sys.exit(main())
