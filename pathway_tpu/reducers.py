"""pw.reducers — aggregation functions for reduce()
(reference: python/pathway/reducers.py; engine: src/engine/reduce.rs:22-38).
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import ColumnExpression, ReducerExpression
from pathway_tpu.internals.reducer_descriptors import ReducerDescriptor


def _first(ds):
    return ds[0] if ds else dt.ANY


def _float(_ds):
    return dt.FLOAT


def _int(_ds):
    return dt.INT


def _tuple(_ds):
    return dt.ANY_TUPLE


def _array(_ds):
    return dt.ANY_ARRAY


def count(*args: Any) -> ReducerExpression:
    """Number of rows in the group."""
    return ReducerExpression(
        ReducerDescriptor("count", "count", n_args=len(args), ret=_int), *args
    )


def sum(expression: Any) -> ReducerExpression:
    """Sum of values (int, float or numpy array — reference ArraySum)."""
    return ReducerExpression(
        ReducerDescriptor("sum", "sum", ret=_first), expression
    )


def avg(expression: Any) -> ReducerExpression:
    return ReducerExpression(
        ReducerDescriptor("avg", "avg", ret=_float), expression
    )


def min(expression: Any) -> ReducerExpression:  # noqa: A001
    return ReducerExpression(
        ReducerDescriptor("min", "min", ret=_first), expression
    )


def max(expression: Any) -> ReducerExpression:  # noqa: A001
    return ReducerExpression(
        ReducerDescriptor("max", "max", ret=_first), expression
    )


def argmin(expression: Any, id_expression: Any = None) -> ReducerExpression:
    from pathway_tpu.internals.thisclass import this

    args = (expression, id_expression if id_expression is not None else this.id)
    return ReducerExpression(
        ReducerDescriptor(
            "argmin", "argmin", n_args=2, ret=lambda ds: dt.POINTER
        ),
        *args,
    )


def argmax(expression: Any, id_expression: Any = None) -> ReducerExpression:
    from pathway_tpu.internals.thisclass import this

    args = (expression, id_expression if id_expression is not None else this.id)
    return ReducerExpression(
        ReducerDescriptor(
            "argmax", "argmax", n_args=2, ret=lambda ds: dt.POINTER
        ),
        *args,
    )


def unique(expression: Any) -> ReducerExpression:
    """The single distinct value of the group (Error if not unique)."""
    return ReducerExpression(
        ReducerDescriptor("unique", "unique", ret=_first), expression
    )


def any(expression: Any) -> ReducerExpression:  # noqa: A001
    """An arbitrary (but deterministic) value from the group."""
    return ReducerExpression(
        ReducerDescriptor("any", "any", ret=_first), expression
    )


def sorted_tuple(expression: Any, *, skip_nones: bool = False) -> ReducerExpression:
    return ReducerExpression(
        ReducerDescriptor(
            "sorted_tuple", "sorted_tuple", skip_nones=skip_nones, ret=_tuple
        ),
        expression,
    )


def tuple(expression: Any, *, skip_nones: bool = False) -> ReducerExpression:  # noqa: A001
    return ReducerExpression(
        ReducerDescriptor("tuple", "tuple", skip_nones=skip_nones, ret=_tuple),
        expression,
    )


def ndarray(expression: Any, *, skip_nones: bool = False) -> ReducerExpression:
    return ReducerExpression(
        ReducerDescriptor("ndarray", "ndarray", skip_nones=skip_nones, ret=_array),
        expression,
    )


def earliest(expression: Any) -> ReducerExpression:
    return ReducerExpression(
        ReducerDescriptor("earliest", "earliest", ret=_first), expression
    )


def latest(expression: Any) -> ReducerExpression:
    return ReducerExpression(
        ReducerDescriptor("latest", "latest", ret=_first), expression
    )


def stateful_single(combine_fn: Callable) -> Callable[..., ReducerExpression]:
    """Custom non-retractable reducer: fn(state, *values) -> new state
    (reference: stateful_single, internals/custom_reducers.py)."""

    def make(*args: Any) -> ReducerExpression:
        return ReducerExpression(
            ReducerDescriptor(
                "stateful_single",
                "stateful",
                n_args=len(args),
                fn=combine_fn,
                ret=lambda ds: dt.ANY,
            ),
            *args,
        )

    return make


def stateful_many(combine_fn: Callable) -> Callable[..., ReducerExpression]:
    """fn(state, rows: list[(values_tuple, count)]) -> new state."""

    def make(*args: Any) -> ReducerExpression:
        return ReducerExpression(
            ReducerDescriptor(
                "stateful_many",
                "stateful",
                n_args=len(args),
                fn=combine_fn,
                extra={"many": True},
                ret=lambda ds: dt.ANY,
            ),
            *args,
        )

    return make


def udf_reducer(reducer_cls: Any) -> Callable[..., ReducerExpression]:
    """Reducer from a BaseCustomAccumulator subclass
    (reference: udf_reducer, internals/custom_reducers.py)."""

    def make(*args: Any) -> ReducerExpression:
        return ReducerExpression(
            ReducerDescriptor(
                "udf_reducer",
                "custom_acc",
                n_args=len(args),
                extra={"cls": reducer_cls},
                ret=lambda ds: dt.ANY,
            ),
            *args,
        )

    return make


# aliases kept for reference-parity
int_sum = sum
float_sum = sum
npsum = sum
