"""Testing utilities — deterministic fault injection (Fault Forge)."""

from pathway_tpu.testing import faults

__all__ = ["faults"]
