"""Shared helpers for chaos/recovery harnesses (tests + bench).

The kill/restart matrix in ``tests/test_distributed.py`` and the
``bench.py chaos_recovery`` tier drive the same shape of experiment: a
multi-process DCN group writing jsonlines diff streams whose FOLDED
state must converge on the uninterrupted run's totals.  The folding
rules (``diff > 0`` installs a key's value, ``diff < 0`` removes it only
when it matches — a rewound incarnation may re-emit retractions the fold
must tolerate) and the mesh port probing are shared here so the two
harnesses cannot drift.
"""

from __future__ import annotations

import json
import random
import socket
import textwrap

# Replica Shield writer role, shared by the test chaos matrix
# (tests/test_distributed.py) and the `bench.py serve_chaos` tier so the
# two harnesses drive the SAME pipeline: streaming jsonlines docs ->
# deterministic pseudo-embedding -> TpuKnn external index (+ an empty
# query stream), persistence snapshots, and the PATHWAY_REPL_PORT delta
# publisher.  Env contract: PW_WRITER_DIR (base dir with docs/ and q/
# subdirs; a STOP file there stops the run), PATHWAY_REPLICA_DIM,
# PATHWAY_REPL_PORT, PATHWAY_DCN_SECRET.
REPL_WRITER_SCRIPT = textwrap.dedent(
    """
    import os, sys, json, time, pathlib, threading
    import jax
    jax.config.update("jax_platforms", "cpu")
    import pathway_tpu as pw
    from pathway_tpu.serving.replica import text_vector

    base = pathlib.Path(os.environ["PW_WRITER_DIR"])
    DIM = int(os.environ["PATHWAY_REPLICA_DIM"])
    stop_file = base / "STOP"

    class DocS(pw.Schema):
        text: str

    docs = pw.io.jsonlines.read(
        str(base / "docs"), schema=DocS, mode="streaming"
    )
    docs = docs.select(
        vec=pw.apply(lambda t: text_vector(t, DIM), docs.text),
        text=docs.text,
    )
    queries = pw.io.jsonlines.read(
        str(base / "q"), schema=DocS, mode="streaming"
    )
    queries = queries.select(
        vec=pw.apply(lambda t: text_vector(t, DIM), queries.text)
    )
    from pathway_tpu.stdlib.indexing import DataIndex, TpuKnn

    index = DataIndex(docs, TpuKnn(docs.vec, dimensions=DIM))
    res = index.query_as_of_now(queries.vec, number_of_matches=2).select(
        texts=pw.right.text
    )
    pw.io.null.write(res)

    def watch():
        # Shard Flux: a RESHARD file holding an int resplits the delta
        # publisher's shard map live (harness-scriptable — the writer
        # subprocess has no other control channel); consumed once per
        # content change.
        reshard_file = base / "RESHARD"
        last_reshard = None
        while not stop_file.exists():
            time.sleep(0.1)
            if reshard_file.exists():
                try:
                    want = int(reshard_file.read_text().strip())
                except (ValueError, OSError):
                    continue
                if want != last_reshard:
                    from pathway_tpu.parallel import replicate
                    pub = replicate.publisher()
                    if pub is not None:
                        res = pub.reshard(want)
                        last_reshard = want
                        print("WRITER-RESHARDED %s" % json.dumps(res),
                              flush=True)
        rt = pw.internals.parse_graph.G.runtime
        if rt is not None:
            rt.stop()

    threading.Thread(target=watch, daemon=True).start()
    cfg = pw.persistence.Config.simple_config(
        pw.persistence.Backend.filesystem(str(base / "pstorage")),
        snapshot_every=2,
    )
    pw.run(persistence_config=cfg, autocommit_duration_ms=30)
    print("WRITER-CLEAN-EXIT", flush=True)
    """
)


# Shard Flux mesh-resize worker, shared by tests/test_elastic.py and
# the `bench.py reshard_live` tier: a supervised jsonlines→groupby rank
# with a per-rank input dir + per-rank store, per-tick snapshots (so a
# resize cut is always snapshot-covered once input quiesces), and a
# REPLAYED line on exit — the zero-replay evidence the resize
# acceptance reads.  Env contract: PW_TEST_DIR (holds in<pid>/ dirs; a
# STOP file ends the run), plus the supervisor's PATHWAY_PROCESS_ID /
# PATHWAY_MESH_INCARNATION.
RESHARD_WORKER_SCRIPT = textwrap.dedent(
    """
    import os, json, signal, threading, time, pathlib
    import jax
    jax.config.update("jax_platforms", "cpu")
    import pathway_tpu as pw

    pid = int(os.environ["PATHWAY_PROCESS_ID"])
    inc = int(os.environ.get("PATHWAY_MESH_INCARNATION", "0"))
    base = pathlib.Path(os.environ["PW_TEST_DIR"])
    in_dir = base / f"in{pid}"
    pdir = base / f"pstorage{pid}"
    out_file = base / f"out{pid}_inc{inc}.jsonl"
    stop_file = base / "STOP"

    class S(pw.Schema):
        word: str

    t = pw.io.jsonlines.read(str(in_dir), schema=S, mode="streaming")
    r = t.groupby(t.word).reduce(t.word, count=pw.reducers.count())
    pw.io.jsonlines.write(r, str(out_file))

    def _stop(*_a):
        rt = pw.internals.parse_graph.G.runtime
        if rt is not None:
            rt.stop()

    # phase-1 freeze: the supervisor's resize SIGTERM is a GRACEFUL
    # stop — the run ends at a tick boundary and the final commit
    # snapshots, so the handoff cut covers the whole durable log
    # (zero-replay resize)
    signal.signal(signal.SIGTERM, _stop)

    def watch():
        while True:
            time.sleep(0.05)
            if stop_file.exists():
                _stop()
                return

    threading.Thread(target=watch, daemon=True).start()
    cfg = pw.persistence.Config.simple_config(
        pw.persistence.Backend.filesystem(str(pdir)),
        snapshot_every=1,
    )
    pw.run(persistence_config=cfg, autocommit_duration_ms=20)
    drv = pw.internals.parse_graph.G.last_runtime.persistence_driver
    print("REPLAYED %d" % drv.replayed_events, flush=True)
    print("CLEAN-EXIT", flush=True)
    """
)


def wait_snapshot_covered(roots, timeout_s: float = 90.0) -> bool:
    """Wait until every store in ``roots`` holds a committed operator
    -state generation that covers its whole durable log (state time ==
    last_time, live chunk list empty) — the quiesced group-safe cut a
    zero-replay resize starts from."""
    import json as _json
    import os as _os
    import time as _time

    deadline = _time.monotonic() + timeout_s
    while _time.monotonic() < deadline:
        ok = 0
        for root in roots:
            try:
                meta = _json.load(
                    open(_os.path.join(str(root), "metadata.json"))
                )
            except (OSError, ValueError):
                break
            state = meta.get("state")
            covered = (
                state is not None
                and int(state.get("time", -1))
                >= int(meta.get("last_time", 0))
                and not any(
                    v for v in meta.get("live_chunks", {}).values()
                )
            )
            if not covered:
                break
            ok += 1
        if ok == len(roots):
            return True
        _time.sleep(0.25)
    return False


def free_dcn_port(n: int = 2) -> int:
    """A base port where ``base..base+n-1`` are all currently free (the
    host mesh binds base_port + pid for every rank)."""
    for _ in range(50):
        base = random.randint(20000, 40000)
        ok = True
        for off in range(n):
            s = socket.socket()
            try:
                s.bind(("127.0.0.1", base + off))
            except OSError:
                ok = False
            finally:
                s.close()
            if not ok:
                break
        if ok:
            return base
    raise RuntimeError("no free port window")


def fold_diff_stream(paths, key_fields) -> dict:
    """Fold jsonlines diff streams into current state: key = tuple of
    ``key_fields``, value = tuple of every other field (sorted by name,
    excluding diff/time/id).  Insertions overwrite; a retraction removes
    the key only when it matches the current value, so replayed
    retractions from a restarted incarnation are absorbed."""
    state: dict = {}
    for p in paths:
        try:
            lines = open(p).read().splitlines()
        except OSError:
            continue
        for line in lines:
            if not line.strip():
                continue
            o = json.loads(line)
            key = tuple(o[f] for f in key_fields)
            val = tuple(
                v
                for f, v in sorted(o.items())
                if f not in ("diff", "time", "id", *key_fields)
            )
            if o["diff"] > 0:
                state[key] = val
            elif state.get(key) == val:
                del state[key]
    return state
