"""Shared helpers for chaos/recovery harnesses (tests + bench).

The kill/restart matrix in ``tests/test_distributed.py`` and the
``bench.py chaos_recovery`` tier drive the same shape of experiment: a
multi-process DCN group writing jsonlines diff streams whose FOLDED
state must converge on the uninterrupted run's totals.  The folding
rules (``diff > 0`` installs a key's value, ``diff < 0`` removes it only
when it matches — a rewound incarnation may re-emit retractions the fold
must tolerate) and the mesh port probing are shared here so the two
harnesses cannot drift.
"""

from __future__ import annotations

import json
import random
import socket
import textwrap

# Replica Shield writer role, shared by the test chaos matrix
# (tests/test_distributed.py) and the `bench.py serve_chaos` tier so the
# two harnesses drive the SAME pipeline: streaming jsonlines docs ->
# deterministic pseudo-embedding -> TpuKnn external index (+ an empty
# query stream), persistence snapshots, and the PATHWAY_REPL_PORT delta
# publisher.  Env contract: PW_WRITER_DIR (base dir with docs/ and q/
# subdirs; a STOP file there stops the run), PATHWAY_REPLICA_DIM,
# PATHWAY_REPL_PORT, PATHWAY_DCN_SECRET.
REPL_WRITER_SCRIPT = textwrap.dedent(
    """
    import os, sys, json, time, pathlib, threading
    import jax
    jax.config.update("jax_platforms", "cpu")
    import pathway_tpu as pw
    from pathway_tpu.serving.replica import text_vector

    base = pathlib.Path(os.environ["PW_WRITER_DIR"])
    DIM = int(os.environ["PATHWAY_REPLICA_DIM"])
    stop_file = base / "STOP"

    class DocS(pw.Schema):
        text: str

    docs = pw.io.jsonlines.read(
        str(base / "docs"), schema=DocS, mode="streaming"
    )
    docs = docs.select(
        vec=pw.apply(lambda t: text_vector(t, DIM), docs.text),
        text=docs.text,
    )
    queries = pw.io.jsonlines.read(
        str(base / "q"), schema=DocS, mode="streaming"
    )
    queries = queries.select(
        vec=pw.apply(lambda t: text_vector(t, DIM), queries.text)
    )
    from pathway_tpu.stdlib.indexing import DataIndex, TpuKnn

    index = DataIndex(docs, TpuKnn(docs.vec, dimensions=DIM))
    res = index.query_as_of_now(queries.vec, number_of_matches=2).select(
        texts=pw.right.text
    )
    pw.io.null.write(res)

    def watch():
        while not stop_file.exists():
            time.sleep(0.1)
        rt = pw.internals.parse_graph.G.runtime
        if rt is not None:
            rt.stop()

    threading.Thread(target=watch, daemon=True).start()
    cfg = pw.persistence.Config.simple_config(
        pw.persistence.Backend.filesystem(str(base / "pstorage")),
        snapshot_every=2,
    )
    pw.run(persistence_config=cfg, autocommit_duration_ms=30)
    print("WRITER-CLEAN-EXIT", flush=True)
    """
)


def free_dcn_port(n: int = 2) -> int:
    """A base port where ``base..base+n-1`` are all currently free (the
    host mesh binds base_port + pid for every rank)."""
    for _ in range(50):
        base = random.randint(20000, 40000)
        ok = True
        for off in range(n):
            s = socket.socket()
            try:
                s.bind(("127.0.0.1", base + off))
            except OSError:
                ok = False
            finally:
                s.close()
            if not ok:
                break
        if ok:
            return base
    raise RuntimeError("no free port window")


def fold_diff_stream(paths, key_fields) -> dict:
    """Fold jsonlines diff streams into current state: key = tuple of
    ``key_fields``, value = tuple of every other field (sorted by name,
    excluding diff/time/id).  Insertions overwrite; a retraction removes
    the key only when it matches the current value, so replayed
    retractions from a restarted incarnation are absorbed."""
    state: dict = {}
    for p in paths:
        try:
            lines = open(p).read().splitlines()
        except OSError:
            continue
        for line in lines:
            if not line.strip():
                continue
            o = json.loads(line)
            key = tuple(o[f] for f in key_fields)
            val = tuple(
                v
                for f, v in sorted(o.items())
                if f not in ("diff", "time", "id", *key_fields)
            )
            if o["diff"] > 0:
                state[key] = val
            elif state.get(key) == val:
                del state[key]
    return state
