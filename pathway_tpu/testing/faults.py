"""Fault Forge — deterministic, seeded fault injection for chaos tests.

The reference exercises its persistence/recovery guarantees with
integration tests that kill whole worker groups mid-run (reference:
integration_tests/wordcount); Fault Forge makes that style of test (and
the ``bench.py chaos_recovery`` tier) deterministic and scriptable: a
single ``PATHWAY_FAULTS`` spec arms a small set of hooks baked into the
hot paths, each of which is a no-op (one cached ``None`` check) when the
variable is unset.

Spec grammar — semicolon-separated directives, each ``name=arg:val,...``::

    PATHWAY_FAULTS="seed=7;kill=tick:5,pid:1;drop=ch:gb,nth:2"

Directives:

``seed=<int>``
    Seeds the plan RNG (used by probabilistic args; purely informative
    for count-based specs, which are deterministic by construction).
``kill=tick:<N>[,pid:<P>][,at:head|tail][,inc:<I>]``
    ``os._exit(FAULT_EXIT)`` when the N-th data tick starts (``head``,
    default) or ends (``tail`` — the group-visible "mid-tick" kill: peers
    are already exchanging the next round) on process P (default: every
    process). Fires only in supervisor incarnation I (default 0), so a
    restarted group does not re-kill itself.
``kill=replica:<R>[,tick:<T>][,inc:<I>]``
    Replica-scoped kill (Replica Shield): ``os._exit(FAULT_EXIT)`` on
    read replica R when it has APPLIED its T-th delta-stream tick
    (default 1) — the deterministic counter is the replica's per-process
    applied-tick count, so the kill lands at the same corpus state every
    run.  Same incarnation gating as engine kills: a supervised restart
    of the replica runs fault-free by default.  The delta stream itself
    is targeted with the wire directives below via its channel prefix
    (``ch:repl`` — e.g. ``delay=ch:repl,nth:3,ms:200``; the
    writer→standby leg alone via ``ch:repl:standby``).
``kill=ferry:<N>[,inc:<I>]``
    Shard Flux handoff kill: ``os._exit(FAULT_EXIT)`` after the
    SegmentFerry has sent (and had acknowledged) its N-th segment —
    the deterministic counter is the sender's per-process transferred
    -segment count, so a chaos leg lands the death at the same point
    of the handoff every run, always BEFORE the transfer's commit
    frame (the two-phase barrier must roll back cleanly: the old
    ownership map stays committed, the staged segments resume
    content-addressed).  ``at:`` is rejected (the transfer counter is
    the clock); incarnation-gated like every kill, so a supervised
    retry of the handoff runs fault-free by default.
``kill=writer:1[,tick:<T>][,inc:<I>]``
    Writer-scoped kill (Shard Harbor, symmetric with ``kill=replica``):
    ``os._exit(FAULT_EXIT)`` on the replication WRITER when it has
    PUBLISHED its T-th distinct delta-stream tick (default 1) — the
    deterministic counter is the delta publisher's distinct-tick count,
    so standby takeover lands at the same stream position every run.
    Fires only on a process that IS a publisher (PATHWAY_REPL_PORT
    armed); incarnation-gated like every kill, so the standby's
    takeover writer (bumped incarnation) runs fault-free by default.
``drop=ch:<prefix>,nth:<K>[,pid:<P>][,inc:<I>]``
    Silently drop the K-th wire frame sent on channels whose name starts
    with ``<prefix>`` (``bar`` = barrier frames, ``hb`` = heartbeats).
``dup=ch:<prefix>,nth:<K>[,pid:<P>][,inc:<I>]``
    Send the K-th matching frame twice (delivery is keyed per
    (channel, tick, src), so duplicates must be idempotent — asserted by
    the chaos tests).
``delay=ch:<prefix>,nth:<K>,ms:<D>[,pid:<P>][,inc:<I>]``
    Sleep D ms before sending the K-th matching frame.
``torn=nth:<K>[,pid:<P>][,inc:<I>]``
    ``os._exit(FAULT_EXIT)`` immediately before the K-th metadata commit
    that publishes a NEW operator-state generation — segments and state
    blobs are already on disk, the manifest pointer is not: the classic
    torn snapshot.
``slow_store=ms:<D>``
    Sleep D ms on every persistence-store put/get/get_buffer (I/O
    degradation, including the mmap segment-recovery reads).
``flood=tenant:<T>,rps:<R>[,ticks:<N>][,class:<C>][,inc:<I>]``
    Deterministic noisy neighbor (Tenant Weave): charge R synthetic
    requests to tenant T (weight class C) through the tenant ledger for
    every REAL admission processed — the ledger's admission counter is
    the clock, like ``kill=`` uses tick counters, so fairness tests
    need no wall-clock load generators.  ``ticks:N`` bounds the flood
    to the first N real admissions (default: the whole run); ``at:`` is
    rejected (admissions have no head/tail).  Incarnation-gated like
    every directive.

The incarnation comes from ``PATHWAY_MESH_INCARNATION`` (set by the
group supervisor, ``parallel/supervisor.py``); kill-like directives
default to incarnation 0 so a supervised restart runs fault-free and the
test can assert clean recovery.  ``FAULT_EXIT`` (= 23) distinguishes an
injected death from a genuine crash in supervisor logs and tests.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Any

FAULT_EXIT = 23  # exit code of every injected process death

_WIRE_DIRECTIVES = ("drop", "dup", "delay")

_plan: "FaultPlan | None | bool" = False  # False = not resolved yet


class FaultSpecError(ValueError):
    pass


class _Directive:
    __slots__ = ("name", "args", "fired")

    def __init__(self, name: str, args: dict[str, str]):
        self.name = name
        self.args = args
        self.fired = 0

    def arg_int(self, key: str, default: int | None = None) -> int | None:
        raw = self.args.get(key)
        if raw is None:
            if default is None:
                raise FaultSpecError(
                    f"fault directive {self.name!r} needs `{key}:<int>`"
                )
            return default
        try:
            return int(raw)
        except ValueError:
            raise FaultSpecError(
                f"fault directive {self.name!r}: {key}:{raw!r} is not an int"
            ) from None

    def matches_process(self, pid: int, incarnation: int) -> bool:
        want_pid = self.arg_int("pid", -1)
        if want_pid >= 0 and want_pid != pid:
            return False
        inc = self.args.get("inc", "0")
        if inc == "*":
            return True
        return int(inc) == incarnation


class FaultPlan:
    """Parsed ``PATHWAY_FAULTS`` spec + per-process deterministic state.

    Thread-safe: wire hooks run on per-peer sender threads, store hooks
    on whatever thread drives persistence."""

    def __init__(self, spec: str, pid: int, incarnation: int):
        self.spec = spec
        self.pid = pid
        self.incarnation = incarnation
        self.directives: list[_Directive] = []
        self._lock = threading.Lock()
        self._ticks = 0
        self._meta_commits = 0
        seed = 0
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise FaultSpecError(
                    f"fault directive {part!r}: expected name=arg:val,..."
                )
            name, _, rest = part.partition("=")
            name = name.strip()
            if name == "seed":
                seed = int(rest)
                continue
            args: dict[str, str] = {}
            for kv in rest.split(","):
                kv = kv.strip()
                if not kv:
                    continue
                if ":" not in kv:
                    raise FaultSpecError(
                        f"fault directive {name!r}: bad arg {kv!r} "
                        "(expected key:value)"
                    )
                k, _, v = kv.partition(":")
                args[k.strip()] = v.strip()
            known = ("kill", "torn", "slow_store", "flood") + _WIRE_DIRECTIVES
            if name not in known:
                raise FaultSpecError(
                    f"unknown fault directive {name!r} (known: "
                    f"{', '.join(known)})"
                )
            d = _Directive(name, args)
            # validate eagerly so a typo fails at startup, not mid-chaos
            inc_raw = args.get("inc", "0")
            if inc_raw != "*":
                try:
                    int(inc_raw)
                except ValueError:
                    raise FaultSpecError(
                        f"fault directive {name!r}: inc:{inc_raw!r} is "
                        "not an int (or '*')"
                    ) from None
            if args.get("pid") is not None:
                d.arg_int("pid")
            if name == "kill":
                if args.get("replica") is not None:
                    # replica-scoped kill: tick optional (default 1 =
                    # first applied delta tick); `at` is meaningless —
                    # replicas apply whole ticks, they never exchange
                    d.arg_int("replica")
                    if args.get("tick") is not None:
                        d.arg_int("tick")
                    if args.get("at") is not None:
                        raise FaultSpecError(
                            "kill: `at` does not apply to replica-"
                            "scoped kills (replicas have no tick "
                            "head/tail)"
                        )
                elif args.get("decode") is not None:
                    # decode-scoped kill: counts the generation
                    # scheduler's decode steps (generate/scheduler.py);
                    # `at` is meaningless — the step counter is the
                    # deterministic clock
                    d.arg_int("decode")
                    if args.get("at") is not None:
                        raise FaultSpecError(
                            "kill: `at` does not apply to decode-"
                            "scoped kills (the decode-step counter is "
                            "the clock)"
                        )
                elif args.get("ferry") is not None:
                    # ferry-scoped kill: counts the SegmentFerry's
                    # acknowledged segment transfers; `at` is
                    # meaningless (the transfer counter is the clock)
                    d.arg_int("ferry")
                    if args.get("at") is not None:
                        raise FaultSpecError(
                            "kill: `at` does not apply to ferry-"
                            "scoped kills (the segment-transfer "
                            "counter is the clock)"
                        )
                elif args.get("writer") is not None:
                    # writer-scoped kill: counts distinct PUBLISHED
                    # delta ticks; `at` is meaningless (the publish
                    # point is the deterministic clock)
                    d.arg_int("writer")
                    if args.get("tick") is not None:
                        d.arg_int("tick")
                    if args.get("at") is not None:
                        raise FaultSpecError(
                            "kill: `at` does not apply to writer-"
                            "scoped kills (the publish point is the "
                            "clock)"
                        )
                else:
                    d.arg_int("tick")
                    if args.get("at", "head") not in ("head", "tail"):
                        raise FaultSpecError(
                            "kill: `at` must be head or tail"
                        )
            elif name == "flood":
                if not args.get("tenant"):
                    raise FaultSpecError("flood: needs `tenant:<id>`")
                d.arg_int("rps")
                if args.get("ticks") is not None:
                    d.arg_int("ticks")
                if args.get("at") is not None:
                    raise FaultSpecError(
                        "flood: `at` does not apply (the admission "
                        "counter is the clock)"
                    )
            elif name == "torn":
                d.arg_int("nth")
            elif name == "slow_store":
                d.arg_int("ms")
            else:  # wire directives
                d.arg_int("nth")
                if "ch" not in args:
                    raise FaultSpecError(f"{name}: needs `ch:<prefix>`")
                if name == "delay":
                    d.arg_int("ms")
            self.directives.append(d)
        self.rng = random.Random(seed)
        self._slow_store_s = 0.0
        for d in self.directives:
            if d.name == "slow_store":
                self._slow_store_s = d.arg_int("ms") / 1000.0
        self._has_wire = any(
            d.name in _WIRE_DIRECTIVES for d in self.directives
        )
        self._wire_counts: dict[str, int] = {}

    # --- hooks ------------------------------------------------------------

    def _exit(self, what: str) -> None:
        import logging
        import sys

        logging.getLogger("pathway_tpu").warning(
            "fault forge: injected death (%s) on process %d", what, self.pid
        )
        # Fleet Lens: an injected FAULT_EXIT drops a postmortem bundle
        # (journal tail + spans + metrics + thread dump) exactly like a
        # real crash would — chaos runs exercise the forensics path too
        try:
            from pathway_tpu.observability.journal import journal

            j = journal()
            j.record(
                "fault-exit", f"injected death ({what})", persist=True
            )
            j.postmortem(f"fault-exit:{what}")
        except Exception:
            pass  # forensics must never block the injected death
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(FAULT_EXIT)

    def on_tick(self, t: int, phase: str = "head") -> None:
        """Called by the runtime at the head and tail of every data tick
        (t < END_OF_TIME). ``kill=tick:N`` counts head calls."""
        with self._lock:
            if phase == "head":
                self._ticks += 1
            n = self._ticks
        for d in self.directives:
            if d.name != "kill" or d.fired:
                continue
            if (
                d.args.get("replica") is not None
                or d.args.get("writer") is not None
                or d.args.get("decode") is not None
                or d.args.get("ferry") is not None
            ):
                continue  # replica-/writer-/decode-/ferry-scoped kills
                # fire in their own hooks (on_replica_tick /
                # on_writer_tick / on_decode_step / on_ferry_segment)
            if not d.matches_process(self.pid, self.incarnation):
                continue
            if d.args.get("at", "head") != phase:
                continue
            if n >= (d.arg_int("tick") or 0):
                d.fired += 1
                self._exit(f"kill at tick {n} ({phase})")

    def on_replica_tick(self, replica_id: int, n_applied: int) -> None:
        """Called by a read replica (serving/replica.py) after applying
        each delta-stream tick; ``n_applied`` is the deterministic
        per-process applied-tick counter ``kill=replica:R,tick:T``
        fires on."""
        for d in self.directives:
            if d.name != "kill" or d.fired:
                continue
            want = d.args.get("replica")
            if want is None or int(want) != int(replica_id):
                continue
            if not d.matches_process(self.pid, self.incarnation):
                continue
            if n_applied >= (d.arg_int("tick", 1) or 1):
                d.fired += 1
                self._exit(
                    f"kill replica {replica_id} after applied tick "
                    f"{n_applied}"
                )

    def on_writer_tick(self, n_published: int) -> None:
        """Called by the replication writer's delta publisher
        (parallel/replicate.py) after fanning out each DISTINCT tick;
        ``n_published`` is the deterministic per-process published-tick
        counter ``kill=writer:1,tick:T`` fires on."""
        for d in self.directives:
            if d.name != "kill" or d.fired:
                continue
            if d.args.get("writer") is None:
                continue
            if not d.matches_process(self.pid, self.incarnation):
                continue
            if n_published >= (d.arg_int("tick", 1) or 1):
                d.fired += 1
                self._exit(
                    f"kill writer after published tick {n_published}"
                )

    def on_decode_step(self, n_steps: int) -> None:
        """Called by the generation scheduler (generate/scheduler.py)
        after each completed decode step; ``n_steps`` is the
        deterministic per-process step counter ``kill=decode:N`` fires
        on — the chaos clock for mid-generation deaths."""
        for d in self.directives:
            if d.name != "kill" or d.fired:
                continue
            if d.args.get("decode") is None:
                continue
            if not d.matches_process(self.pid, self.incarnation):
                continue
            if n_steps >= (d.arg_int("decode") or 1):
                d.fired += 1
                self._exit(f"kill after decode step {n_steps}")

    def on_ferry_segment(self, n_sent: int) -> None:
        """Called by the SegmentFerry sender (elastic/ferry.py) after
        each ACKNOWLEDGED segment transfer; ``n_sent`` is the
        deterministic per-transfer counter ``kill=ferry:N`` fires on —
        the chaos clock for mid-handoff deaths (always before the
        transfer's commit frame, so the two-phase barrier rolls
        back)."""
        for d in self.directives:
            if d.name != "kill" or d.fired:
                continue
            if d.args.get("ferry") is None:
                continue
            if not d.matches_process(self.pid, self.incarnation):
                continue
            if n_sent >= (d.arg_int("ferry") or 1):
                d.fired += 1
                self._exit(f"kill after ferry segment {n_sent}")

    def flood_charges(
        self, admission_n: int
    ) -> list[tuple[str, str | None, int]]:
        """Tenant Weave hook, called by the tenant ledger per REAL
        admission (``admission_n`` = the ledger's deterministic 1-based
        admission counter).  Returns ``(tenant, weight_class, rps)``
        synthetic-charge triples for every live ``flood=`` directive —
        R charges per real admission, for the first ``ticks`` (default:
        unlimited) admissions."""
        charges: list[tuple[str, str | None, int]] = []
        for d in self.directives:
            if d.name != "flood":
                continue
            if not d.matches_process(self.pid, self.incarnation):
                continue
            ticks = d.arg_int("ticks", 0) or 0  # 0 = unlimited
            if ticks and admission_n > ticks:
                continue
            charges.append(
                (
                    d.args["tenant"],
                    d.args.get("class"),
                    d.arg_int("rps") or 0,
                )
            )
        return charges

    def on_wire_send(self, channel: str) -> tuple[str, float] | None:
        """Called by the mesh sender thread per outgoing frame. Returns
        None (send normally), ("drop", 0), ("dup", 0) or ("delay", s)."""
        if not self._has_wire:
            return None
        with self._lock:
            for idx, d in enumerate(self.directives):
                if d.name not in _WIRE_DIRECTIVES or d.fired:
                    continue
                if not d.matches_process(self.pid, self.incarnation):
                    continue
                if not channel.startswith(d.args["ch"]):
                    continue
                # counters are PER DIRECTIVE (keyed by position): two
                # same-kind directives on one channel prefix count their
                # matching frames independently
                key = str(idx)
                count = self._wire_counts.get(key, 0) + 1
                self._wire_counts[key] = count
                if count == (d.arg_int("nth") or 0):
                    d.fired += 1
                    if d.name == "delay":
                        return ("delay", (d.arg_int("ms") or 0) / 1000.0)
                    return (d.name, 0.0)
        return None

    def before_meta_commit(self, publishes_state: bool) -> None:
        """Called by the persistence driver immediately before writing
        metadata; ``publishes_state`` = this commit names a new operator
        -state generation (segments already durable)."""
        if not publishes_state:
            return
        with self._lock:
            self._meta_commits += 1
            n = self._meta_commits
        for d in self.directives:
            if d.name != "torn" or d.fired:
                continue
            if not d.matches_process(self.pid, self.incarnation):
                continue
            if n >= (d.arg_int("nth") or 0):
                d.fired += 1
                self._exit(f"torn snapshot before metadata commit {n}")

    def store_delay(self) -> None:
        if self._slow_store_s > 0.0:
            time.sleep(self._slow_store_s)

    def wrap_store(self, store: Any) -> Any:
        """Wrap a BackendStore so every put/get pays the slow-store
        delay. Other attributes pass through untouched."""
        if self._slow_store_s <= 0.0:
            return store
        return _SlowStore(store, self)


class _SlowStore:
    def __init__(self, inner: Any, plan: FaultPlan):
        self._inner = inner
        self._plan = plan

    def put(self, key: str, data: bytes) -> None:
        self._plan.store_delay()
        self._inner.put(key, data)

    def get(self, key: str):
        self._plan.store_delay()
        return self._inner.get(key)

    def get_buffer(self, key: str):
        # the mmap recovery-read path (segment restore) must pay the
        # injected I/O degradation too, or recovery timings lie
        self._plan.store_delay()
        return self._inner.get_buffer(key)

    def __getattr__(self, name: str):
        return getattr(self._inner, name)


def active() -> FaultPlan | None:
    """The process's fault plan, parsed once from PATHWAY_FAULTS (None
    when unset). The cached plan keeps deterministic counters across
    every hook site."""
    global _plan
    if _plan is False:
        spec = os.environ.get("PATHWAY_FAULTS", "")
        if not spec:
            _plan = None
        else:
            pid = int(os.environ.get("PATHWAY_PROCESS_ID", "0") or 0)
            inc = int(os.environ.get("PATHWAY_MESH_INCARNATION", "0") or 0)
            _plan = FaultPlan(spec, pid, inc)
    return _plan


def reset() -> None:
    """Drop the cached plan (tests re-arm with a fresh env)."""
    global _plan
    _plan = False
