"""Persistence engine glue: input event logs + offsets + resume.

TPU-native re-design of the reference's persistence split
(reference: src/persistence/input_snapshot.rs — Insert/Delete/AdvanceTime
event log per input; src/persistence/state.rs:35 MetadataAccessor — last
finalized time; src/connectors/mod.rs:222 rewind_from_disk_snapshot, then
reader.seek to stored offsets).

In the microbatch engine the driver advances one totally-ordered logical
clock, so the reference's multi-worker finalized-time consensus
(state.rs:291) collapses to: a tick is finalized the moment it completes.
A "commit" atomically records (input log chunks, per-source offsets,
metadata) so replay and seek can never disagree — the reference gets the
same property from snapshotting both under one frontier.

Resume = replay logged ticks through the freshly built node graph at their
original logical times (deterministic, same results), then restore source
offsets so connectors continue where they left off. At-least-once, like the
reference's OSS mode (README.md:110).
"""

from __future__ import annotations

import json
import pickle
from typing import Any

from pathway_tpu.engine.batch import END_OF_TIME, DiffBatch
from pathway_tpu.engine.nodes import InputNode
from pathway_tpu.engine.runtime import Runtime, StaticSource
from pathway_tpu.persistence.backends import BackendStore, store_for_backend

_META_KEY = "metadata.json"


def effective_persistent_id(node: InputNode, ordinal: int) -> str:
    """Stable id for an input across restarts (reference:
    src/engine/dataflow/persist.rs:37 effective_persistent_id): explicit
    `persistent_id` on the source wins; otherwise position in the graph."""
    pid = getattr(node.source, "persistent_id", None)
    if pid:
        return str(pid)
    return f"input-{ordinal}"


class _EmptyStatic(StaticSource):
    def events(self):
        return iter(())


class PersistenceDriver:
    """Wraps a Runtime: records every injected input batch, commits offsets
    on an interval, replays the log on startup."""

    def __init__(self, runtime: Runtime, config: Any):
        self.runtime = runtime
        self.store: BackendStore = store_for_backend(config.backend)
        self.snapshot_interval_ms = max(
            int(getattr(config, "snapshot_interval_ms", 0) or 0), 0
        )
        mode = getattr(config, "snapshot_access", None)
        if mode not in (None, "record", "replay", "full"):
            raise ValueError(
                f"invalid snapshot_access {mode!r}: expected 'record', "
                "'replay' or 'full' (reference: PATHWAY_SNAPSHOT_ACCESS)"
            )
        self.record = mode in (None, "record", "full")
        self.replay_allowed = mode in (None, "replay", "full")
        self.inputs: dict[str, InputNode] = {}
        ordinal = 0
        for node in runtime.order:
            if isinstance(node, InputNode):
                self.inputs[effective_persistent_id(node, ordinal)] = node
                ordinal += 1
        self._node_to_pid = {n.id: pid for pid, n in self.inputs.items()}
        self._pending: dict[str, list[tuple[int, list]]] = {
            pid: [] for pid in self.inputs
        }
        self._chunk_counts: dict[str, int] = {}
        self._last_commit_wall = 0.0
        self._committed_time = 0
        self._last_real_time = 0
        self._orig_tick = runtime.tick

    # --- commit path ----------------------------------------------------------

    def _load_meta(self) -> dict:
        raw = self.store.get(_META_KEY)
        if raw is None:
            return {"last_time": 0, "chunks": {}}
        return json.loads(raw.decode())

    def on_tick(self, t: int, injected: dict[int, list[DiffBatch]] | None = None):
        self._orig_tick(t, injected)
        if not self.record:
            return
        if injected:
            for nid, batches in injected.items():
                pid = self._node_to_pid.get(nid)
                if pid is None:
                    continue
                rows = [r for b in batches for r in b.iter_rows()]
                if rows:
                    self._pending[pid].append((t, rows))
        if t >= END_OF_TIME:
            self.commit(final=True)
            return
        self._last_real_time = max(self._last_real_time, t)
        import time as _time

        now = _time.monotonic()
        if (now - self._last_commit_wall) * 1000.0 >= self.snapshot_interval_ms:
            self._last_commit_wall = now
            self.commit()

    def commit(self, final: bool = False) -> None:
        """Atomically advance the durable frontier: flush pending log chunks,
        snapshot source offsets, then write metadata last (metadata names
        exactly the chunks+offsets that form the consistent cut)."""
        meta = self._load_meta()
        wrote = False
        for pid, pending in self._pending.items():
            if not pending:
                continue
            idx = self._chunk_counts.get(pid, meta["chunks"].get(pid, 0))
            self.store.put(
                f"inputs/{pid}/chunk-{idx:08d}.pkl", pickle.dumps(pending)
            )
            self._chunk_counts[pid] = idx + 1
            self._pending[pid] = []
            wrote = True
        offsets_changed = False
        for pid, node in self.inputs.items():
            state = None
            src = node.source
            session = getattr(src, "session", None)
            if session is not None and getattr(session, "last_offsets", None) is not None:
                # only offsets whose covered rows have been drained (and so
                # logged above) — a live src.offset_state() could run ahead
                # of the log and lose rows on resume
                state = session.last_offsets
            elif isinstance(src, StaticSource):
                state = {"__static_done__": True} if final else None
            if state is not None:
                self.store.put(f"offsets/{pid}.pkl", pickle.dumps(state))
                offsets_changed = True
        if wrote or offsets_changed or final:
            meta["chunks"].update(self._chunk_counts)
            meta["last_time"] = max(meta.get("last_time", 0), self._last_real_time)
            if final:
                meta["finished"] = True
            self.store.put(_META_KEY, json.dumps(meta).encode())
            self._committed_time = meta["last_time"]

    # --- resume path ----------------------------------------------------------

    def replay(self) -> None:
        """Feed logged events back through the graph at their original
        logical times, then restore connector offsets."""
        meta = self._load_meta()
        self._chunk_counts = dict(meta.get("chunks", {}))
        if not self.replay_allowed:
            return
        events: list[tuple[int, int, DiffBatch]] = []  # (time, node_id, batch)
        for pid, node in self.inputs.items():
            n_chunks = meta.get("chunks", {}).get(pid, 0)
            for i in range(n_chunks):
                raw = self.store.get(f"inputs/{pid}/chunk-{i:08d}.pkl")
                if raw is None:
                    continue
                for t, rows in pickle.loads(raw):
                    events.append(
                        (t, node.id, DiffBatch.from_rows(rows, node.column_names))
                    )
        events.sort(key=lambda e: e[0])
        i, n = 0, len(events)
        while i < n:
            t = events[i][0]
            injected: dict[int, list[DiffBatch]] = {}
            while i < n and events[i][0] == t:
                injected.setdefault(events[i][1], []).append(events[i][2])
                i += 1
            self._orig_tick(t, injected)
        # restore offsets so live sources continue past what was replayed
        for pid, node in self.inputs.items():
            raw = self.store.get(f"offsets/{pid}.pkl")
            if raw is None:
                continue
            state = pickle.loads(raw)
            src = node.source
            if isinstance(state, dict) and state.get("__static_done__"):
                if isinstance(src, StaticSource):
                    node.source = _EmptyStatic(node.column_names)
            elif hasattr(src, "seek"):
                src.seek(state)


def attach_persistence(runtime: Runtime, config: Any) -> PersistenceDriver:
    driver = PersistenceDriver(runtime, config)
    driver.replay()
    runtime.tick = driver.on_tick  # type: ignore[method-assign]
    return driver
