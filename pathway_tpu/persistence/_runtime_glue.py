"""Persistence engine glue: input event logs + offsets + resume.

TPU-native re-design of the reference's persistence split
(reference: src/persistence/input_snapshot.rs — Insert/Delete/AdvanceTime
event log per input; src/persistence/state.rs:35 MetadataAccessor — last
finalized time; src/connectors/mod.rs:222 rewind_from_disk_snapshot, then
reader.seek to stored offsets).

In the microbatch engine the driver advances one totally-ordered logical
clock, so the reference's multi-worker finalized-time consensus
(state.rs:291) collapses to: a tick is finalized the moment it completes.
A "commit" atomically records (input log chunks, per-source offsets,
metadata) so replay and seek can never disagree — the reference gets the
same property from snapshotting both under one frontier.

Resume = restore operator-state snapshots (reference:
src/persistence/operator_snapshot.rs:21-31 chunked state dumps +
src/engine/dataflow/persist.rs MaybePersist wrappers), then replay only the
log TAIL — events newer than the snapshot — then restore source offsets.
Each successful full-graph snapshot truncates the input log (compaction:
operator_snapshot.rs:342's background merge collapses to "delete covered
chunks" in the single-driver setting), so both restart time and log size
stay bounded by the churn since the last snapshot, not by history.
At-least-once, like the reference's OSS mode (README.md:110).

Operator snapshots are INCREMENTAL for arrangement-backed execs (the
differential-dataflow move: arranged collections ARE the checkpoint).
Such an exec exposes (residual, {name: Arrangement}) via
``arranged_state()``; every snapshot writes only segment files whose
content-addressed id has never been stored (``segments/<node>/<part>/
<epoch>-<segid>.seg``, persistence/segments.py) plus a tiny
manifest+residual blob per generation, and GC retires segment files no
retained generation references — so steady-state checkpoint bytes are
proportional to churn since the last snapshot, not to state size.
Recovery rebuilds the arrangements over mmap-backed buffers
(``BackendStore.get_buffer``) instead of unpickling monoliths or
replaying the input log.  ``PATHWAY_PERSIST_MONOLITH=1`` forces the old
whole-state pickling (differential testing / escape hatch).
"""

from __future__ import annotations

import json
import os
import pickle
from typing import Any

from pathway_tpu.engine.batch import END_OF_TIME, DiffBatch
from pathway_tpu.engine.nodes import InputNode
from pathway_tpu.engine.runtime import Runtime, StaticSource
from pathway_tpu.persistence.backends import BackendStore, store_for_backend
from pathway_tpu.persistence.segments import (
    load_arrangement,
    manifest_of,
    segment_to_bytes,
)

_META_KEY = "metadata.json"
_GROUP_COMMIT_KEY = "group_commit.json"  # durable audit record of the
# last gen-commit barrier agreement (Phoenix Mesh phase 2)

_M: dict | None = None


def _metrics() -> dict:
    """Persistence metrics, created once per process (label-free handles
    cached here, off the snapshot path — PR-6 convention)."""
    global _M
    if _M is None:
        from pathway_tpu.observability import REGISTRY

        _M = {
            "snap_bytes": REGISTRY.histogram(
                "pathway_persistence_snapshot_bytes",
                "bytes written per operator-state snapshot (incremental "
                "snapshots write only new segments + manifests)",
            ),
            "snap_seconds": REGISTRY.histogram(
                "pathway_persistence_snapshot_seconds",
                "wall seconds per operator-state snapshot",
            ),
            "segs_written": REGISTRY.counter(
                "pathway_persistence_segments_written_total",
                "arrangement segment files written to the persistence "
                "store",
            ),
            "segs_retired": REGISTRY.counter(
                "pathway_persistence_segments_retired_total",
                "dead arrangement segment files deleted by snapshot GC",
            ),
            "recovery_seconds": REGISTRY.gauge(
                "pathway_persistence_recovery_seconds",
                "wall seconds of the last recovery (operator-state "
                "restore + log-tail replay)",
            ),
        }
    return _M


def resume_point(store: Any) -> dict:
    """Where a takeover writer resumes from this store (Shard Harbor
    standby handoff): ``state_time`` = the newest committed operator
    -state generation's time (what :meth:`PersistenceDriver.replay`
    restores and floors the delta ring at), ``group_commit_time`` = the
    last durable phase-2 gen-commit barrier agreement (Phoenix Mesh
    audit record), ``last_time`` = the durable input-log frontier the
    connector-log replay walks to.  All -1 when absent — a fresh store
    means the takeover rebuilds from the log alone."""
    out = {"state_time": -1, "group_commit_time": -1, "last_time": -1}
    raw = store.get(_META_KEY)
    if raw is not None:
        meta = json.loads(raw.decode())
        out["last_time"] = int(meta.get("last_time", -1))
        if meta.get("state"):
            out["state_time"] = int(meta["state"].get("time", -1))
    marker = store.get(_GROUP_COMMIT_KEY)
    if marker is not None:
        out["group_commit_time"] = int(
            json.loads(marker.decode()).get("time", -1)
        )
    return out


def state_key(gen: int, ident) -> str:
    """Store key of one node's per-generation state blob — the layout
    contract shared with every out-of-process reader of a writer's
    store (replica hydration, elastic/mesh.py resharding)."""
    return PersistenceDriver._state_key(gen, ident)


def segment_key(ident, name: str, epoch: str, seg_id: int) -> str:
    """Store key of one content-addressed arrangement segment file —
    same cross-module contract as :func:`state_key`."""
    return PersistenceDriver._segment_key(ident, name, epoch, seg_id)


def effective_persistent_id(node: InputNode, ordinal: int) -> str:
    """Stable id for an input across restarts (reference:
    src/engine/dataflow/persist.rs:37 effective_persistent_id): explicit
    `persistent_id` on the source wins; otherwise position in the graph."""
    pid = getattr(node.source, "persistent_id", None)
    if pid:
        return str(pid)
    return f"input-{ordinal}"


class _EmptyStatic(StaticSource):
    def events(self):
        return iter(())


class PersistenceDriver:
    """Wraps a Runtime: records every injected input batch, commits offsets
    on an interval, replays the log on startup."""

    def __init__(self, runtime: Runtime, config: Any):
        self.runtime = runtime
        self.store: BackendStore = store_for_backend(config.backend)
        # Fault Forge: slow-store injection wraps every put/get; the
        # torn-snapshot hook fires in commit() right before metadata
        from pathway_tpu.testing import faults

        self._fault_plan = faults.active()
        if self._fault_plan is not None:
            self.store = self._fault_plan.wrap_store(self.store)
        self.snapshot_interval_ms = max(
            int(getattr(config, "snapshot_interval_ms", 0) or 0), 0
        )
        mode = getattr(config, "snapshot_access", None)
        if mode not in (None, "record", "replay", "full"):
            raise ValueError(
                f"invalid snapshot_access {mode!r}: expected 'record', "
                "'replay' or 'full' (reference: PATHWAY_SNAPSHOT_ACCESS)"
            )
        # SELECTIVE_PERSISTING: only explicitly-named operators persist;
        # inputs are neither logged nor offset-tracked
        pmode = getattr(config, "persistence_mode", None)
        pmode = getattr(pmode, "value", pmode)  # enum member or raw string
        self.selective = pmode == "selective_persisting"
        self.record = mode in (None, "record", "full") and not self.selective
        self.replay_allowed = mode in (None, "replay", "full")
        # explicit snapshot_access = record/replay DEBUGGING (reference:
        # PATHWAY_REPLAY_STORAGE + `pathway spawn --record`): the input log
        # is the artifact, so it is never compacted away by operator
        # snapshots and replay reproduces the run in full
        self.debug_mode = mode in ("record", "replay", "full")
        self.inputs: dict[str, InputNode] = {}
        ordinal = 0
        for node in runtime.order:
            if isinstance(node, InputNode):
                if getattr(node.source, "transient", False):
                    # debug/markdown fixtures are not persistable connectors
                    # (reference: only sources with persistent ids log and
                    # seek); they re-read fresh on every run and rely on
                    # operator snapshots (e.g. deduplicate state) to merge
                    ordinal += 1
                    continue
                self.inputs[effective_persistent_id(node, ordinal)] = node
                ordinal += 1
        self._node_to_pid = {n.id: pid for pid, n in self.inputs.items()}
        self._pending: dict[str, list[tuple[int, list]]] = {
            pid: [] for pid in self.inputs
        }
        self._chunk_counts: dict[str, int] = {}
        self._live_chunks: dict[str, list[int]] = {}
        # per-input offsets snapshot taken when that input's rows were
        # last appended to the log (see on_tick) — the only offsets
        # commit() is allowed to persist; _offsets_persisted remembers
        # the object identity last written so unchanged offsets skip
        # the store round trip on idle per-tick commits
        self._offsets_at_log: dict[str, Any] = {}
        self._offsets_persisted: dict[str, Any] = {}
        self._last_commit_wall = 0.0
        self._committed_time = 0
        self._last_real_time = 0
        self._orig_tick = runtime.tick
        # operator snapshots: on by default; every snapshot_every-th commit
        # dumps all exec states and truncates the covered log. Disabled in
        # record/replay debugging where the log must survive verbatim.
        self.snapshot_operators = (
            bool(getattr(config, "snapshot_operators", True))
            and not self.debug_mode
        )
        self.snapshot_every = max(
            int(getattr(config, "snapshot_every", 8) or 8), 1
        )
        self._commits_since_snapshot = 0
        # multi-process: GC deferred past the gen-commit barrier (phase
        # 2 of the two-phase generation commit) — files are only retired
        # once the whole group has confirmed a generation every rank can
        # restore
        self._pending_gc: tuple[dict, dict] | None = None
        self._group_commit_time: int | None = None
        # mixed dependency: a node fed by BOTH a transient source and a
        # logged one is excluded from snapshots (its transient rows re-feed)
        # yet needs the logged rows to rebuild — truncating the log would
        # lose them, so operator snapshots are disabled for such graphs
        # (log-only persistence, the pre-compaction behavior)
        if self.snapshot_operators and not self.selective:
            tainted: set[int] = set()
            logged: set[int] = set()
            logged_input_ids = {n.id for n in self.inputs.values()}
            for node in runtime.order:
                if isinstance(node, InputNode):
                    if getattr(node.source, "transient", False):
                        tainted.add(node.id)
                    elif node.id in logged_input_ids:
                        logged.add(node.id)
                    continue
                if any(inp.id in tainted for inp in node.inputs):
                    tainted.add(node.id)
                if any(inp.id in logged for inp in node.inputs):
                    logged.add(node.id)
            if tainted & logged:
                import logging

                logging.getLogger("pathway_tpu").info(
                    "operator snapshots disabled: graph mixes transient "
                    "fixtures with persisted connectors; falling back to "
                    "input-log persistence"
                )
                self.snapshot_operators = False
        self.replayed_events = 0  # observability: bounded-replay assertions
        self.restored_from_snapshot = False
        # incremental segment snapshots: segment keys this driver may skip
        # rewriting. Primed from the keys the DURABLE metadata references
        # — NOT from a store listing: a crash between segment writes and
        # the metadata commit leaves orphan files whose ids a restored
        # arrangement (whose seg-id counter rolled back with the durable
        # manifest) will mint again with different content; those must be
        # overwritten, not skipped.
        self.monolith = os.environ.get(
            "PATHWAY_PERSIST_MONOLITH", ""
        ) not in ("", "0")
        self._segments_present: set[str] = set()
        _boot_meta = self._load_meta()
        _boot_gens = [_boot_meta.get("state"), _boot_meta.get("prev_state")]
        _boot_gens += [
            r.get("state") for r in _boot_meta.get("retained_states", [])
        ]
        for gen_desc in _boot_gens:
            if gen_desc:
                self._segments_present.update(
                    gen_desc.get("segment_keys", ())
                )
        self._m = _metrics()
        # execs that keep a persistence ledger (a side arrangement of
        # per-group state blobs, e.g. GroupByExec) only pay for it when
        # snapshots will actually happen — enable before any tick runs
        if not self.monolith and (self.snapshot_operators or self.selective):
            for _ident, _cls, ex, _refeed in self._node_ordinals():
                hook = getattr(ex, "enable_state_ledger", None)
                if hook is not None:
                    hook()
        # multi-process: lockstep tick counter driving group-safe snapshot
        # points (identical on every process — ticks are barrier-agreed)
        self._ticks_seen = 0
        # set when the latest snapshot attempt aborted on an unpicklable
        # exec ("<class>#<ordinal>"); also mirrored into metadata
        self.degraded_snapshot: str | None = None

    # --- commit path ----------------------------------------------------------

    def _load_meta(self) -> dict:
        raw = self.store.get(_META_KEY)
        if raw is None:
            return {"last_time": 0, "chunks": {}}
        return json.loads(raw.decode())

    def _node_ordinals(self) -> list[tuple[Any, str, Any, bool]]:
        """(ordinal, class name, exec, inputs_refeed) for every
        snapshot-eligible node, ordinal = topo position — the stable
        cross-restart identity (same role as effective_persistent_id for
        inputs).  ``inputs_refeed`` marks nodes whose input rows arrive
        again on every run (transient fixtures / selective mode): only
        those may re-emit restored accumulator state, because their
        downstream consumers are NOT restored and must rebuild.

        Nodes fed (transitively) by a transient source re-process that
        source's rows on every run, so restoring their state would double
        -count; they are excluded — EXCEPT standalone accumulators
        (deduplicate), which the reference persists under their own
        persistent id precisely because their inputs re-feed
        (non-retractable stateful_reduce, operators/stateful_reduce.rs).

        SELECTIVE_PERSISTING keeps ONLY operators with an explicit
        `persistent_name`, keyed by that name (graph position is free to
        change between runs)."""
        if self.selective:
            out = []
            for node in self.runtime.order:
                name = getattr(node, "persistent_name", None)
                if name:
                    out.append(
                        (
                            f"name:{name}",
                            type(node).__name__,
                            self.runtime.execs[node.id],
                            True,  # selective mode never logs inputs
                        )
                    )
            return out
        tainted: set[int] = set()
        for node in self.runtime.order:
            if isinstance(node, InputNode) and getattr(
                node.source, "transient", False
            ):
                tainted.add(node.id)
            elif any(inp.id in tainted for inp in node.inputs):
                tainted.add(node.id)
        out = []
        for i, node in enumerate(self.runtime.order):
            ex = self.runtime.execs[node.id]
            if node.id in tainted and not getattr(
                ex, "persist_standalone", False
            ):
                continue
            out.append((i, type(node).__name__, ex, node.id in tainted))
        return out

    def on_tick(self, t: int, injected: dict[int, list[DiffBatch]] | None = None):
        hm = getattr(self.runtime, "host_mesh", None)
        if (
            hm is not None
            and self.record
            and self.snapshot_operators
            and t < END_OF_TIME
        ):
            # group-safe snapshot point: we are at the HEAD of a lockstep
            # tick, so the barrier that scheduled it has confirmed every
            # peer completed (and, with per-tick commits below, durably
            # logged) the previous tick. State captured here can never run
            # ahead of any peer's durable input log — the reference's
            # "all workers flushed up to T" consensus
            # (src/persistence/state.rs:291) realized on the tick barrier.
            if (
                self._ticks_seen > 0
                and self._ticks_seen % self.snapshot_every == 0
            ):
                self.commit(snapshot=True)
                self._group_commit(hm)
            self._ticks_seen += 1
        self._orig_tick(t, injected)
        if not self.record:
            # selective mode snapshots named operators on shutdown AND on
            # the regular commit interval — a killed process must not lose
            # the one thing this mode promises to persist
            if self.selective:
                if t >= END_OF_TIME:
                    self.commit(final=True)
                    return
                import time as _time

                now = _time.monotonic()
                if (
                    now - self._last_commit_wall
                ) * 1000.0 >= self.snapshot_interval_ms:
                    self._last_commit_wall = now
                    self._last_real_time = max(self._last_real_time, t)
                    meta = self._load_meta()
                    snap = self._snapshot_operators(meta)
                    if snap:
                        meta["state"] = snap
                        meta["last_time"] = max(
                            meta.get("last_time", 0), t
                        )
                        self.store.put(_META_KEY, json.dumps(meta).encode())
                        self._gc(meta, snap)
            return
        if injected:
            for nid, batches in injected.items():
                pid = self._node_to_pid.get(nid)
                if pid is None:
                    continue
                rows = [r for b in batches for r in b.iter_rows()]
                if rows:
                    self._pending[pid].append((t, rows))
        # capture offsets AT LOG TIME: commit() persists this pairing,
        # never the session's live last_offsets. The live value already
        # covers rows drained for the NEXT tick (the lockstep loop
        # drains before calling tick), so a commit running at the head
        # of a tick — the group-safe snapshot point — would otherwise
        # persist offsets ahead of the durable log and a mid-tick death
        # would LOSE those rows on resume (Fault Forge chaos matrix
        # pinned this down).
        for pid, node in self.inputs.items():
            session = getattr(node.source, "session", None)
            if session is not None and getattr(
                session, "last_offsets", None
            ) is not None:
                self._offsets_at_log[pid] = session.last_offsets
        if t >= END_OF_TIME:
            self.commit(final=True)
            if hm is not None:
                self._group_commit(hm)
            return
        self._last_real_time = max(self._last_real_time, t)
        import time as _time

        now = _time.monotonic()
        if hm is not None:
            # multi-process: the input log must be durable before the next
            # barrier round lets any peer snapshot state derived from this
            # tick's exchanged rows — commit every tick
            self._last_commit_wall = now
            self.commit()
        elif (now - self._last_commit_wall) * 1000.0 >= self.snapshot_interval_ms:
            self._last_commit_wall = now
            self.commit()

    def commit(self, final: bool = False, snapshot: bool = False) -> None:
        """Atomically advance the durable frontier: flush pending log chunks,
        snapshot source offsets (and, periodically, every operator's state),
        then write metadata last (metadata names exactly the chunks +
        offsets + state generation forming the consistent cut). A crash
        mid-commit leaves the previous metadata — and so the previous
        consistent cut — untouched."""
        meta = self._load_meta()
        if not self._live_chunks:
            self._live_chunks = {
                pid: list(v) for pid, v in meta.get("live_chunks", {}).items()
            }
        wrote = False
        for pid, pending in self._pending.items():
            if not pending:
                continue
            idx = self._chunk_counts.get(pid, meta["chunks"].get(pid, 0))
            self.store.put(
                f"inputs/{pid}/chunk-{idx:08d}.pkl", pickle.dumps(pending)
            )
            self._chunk_counts[pid] = idx + 1
            self._live_chunks.setdefault(pid, []).append(idx)
            self._pending[pid] = []
            wrote = True
        offsets_changed = False
        # selective mode: inputs are neither logged nor offset-tracked —
        # writing __static_done__ here would suppress sources on restart
        # with no log to reproduce them
        #
        # Offsets are STAGED under sequence-numbered keys and only become
        # current when the metadata naming them commits below — a crash
        # between the offsets write and the metadata write must leave the
        # previous consistent cut intact. (The old in-place
        # ``offsets/{pid}.pkl`` overwrite could run ahead of the named
        # log chunks and silently LOSE the torn commit's rows on resume;
        # Fault Forge's torn-snapshot smoke pinned this down.)
        offsets_named = dict(meta.get("offsets", {}))
        oseq = int(meta.get("offsets_seq", 0))
        retired_offsets: list[str] = []
        for pid, node in () if self.selective else self.inputs.items():
            state = None
            src = node.source
            session = getattr(src, "session", None)
            if session is not None:
                # only offsets captured when their covered rows were
                # appended to the log (on_tick) — the session's LIVE
                # last_offsets can already cover the next tick's drained
                # -but-unlogged rows when this commit runs at the head
                # of a tick (group-safe snapshot point)
                state = self._offsets_at_log.get(pid)
                # last_offsets is REASSIGNED per drain, so identity
                # detects change: idle ticks (DCN commits every tick)
                # skip the rewrite entirely
                if state is self._offsets_persisted.get(pid):
                    state = None
            elif isinstance(src, StaticSource):
                state = {"__static_done__": True} if final else None
            if state is not None:
                oseq += 1
                key = f"offsets/{pid}-{oseq:08d}.pkl"
                self.store.put(key, pickle.dumps(state))
                # the exact key this one supersedes (or the legacy
                # in-place key on first post-upgrade commit) retires
                # after the metadata naming the replacement is durable
                retired_offsets.append(
                    offsets_named.get(pid, f"offsets/{pid}.pkl")
                )
                offsets_named[pid] = key
                self._offsets_persisted[pid] = state
                offsets_changed = True
        snap = None
        self._commits_since_snapshot += 1
        dcn = getattr(self.runtime, "host_mesh", None) is not None
        if self.snapshot_operators and (
            final  # clean shutdown always snapshots: restarts restore
            # accumulator state (deduplicate) even for short runs
            or snapshot  # explicit group-safe snapshot point (on_tick)
            or (
                not dcn  # multi-process snapshots ONLY at group-safe points
                and wrote
                and self._commits_since_snapshot >= self.snapshot_every
            )
        ):
            snap = self._snapshot_operators(meta)
        if wrote or offsets_changed or final or snap:
            meta["chunks"].update(self._chunk_counts)
            meta["live_chunks"] = self._live_chunks
            meta["last_time"] = max(meta.get("last_time", 0), self._last_real_time)
            if offsets_changed:
                meta["offsets"] = offsets_named
                meta["offsets_seq"] = oseq
            if snap:
                if dcn:
                    # multi-process: RETAIN every superseded generation
                    # (state + the chunks between snapshots) until the
                    # gen-commit barrier confirms the whole group holds
                    # something newer — GC then trims the list back to
                    # one. Snapshot points are lockstep-aligned, so the
                    # steady-state list length is one (the old prev_state
                    # behavior); a degraded rank (unpicklable state)
                    # grows it, and every healthy rank keeps enough
                    # history for the group-min restore (reference:
                    # consistent frontier across workers,
                    # src/persistence/state.rs:291)
                    if meta.get("state"):
                        retained = list(meta.get("retained_states", ()))
                        retained.append(
                            {
                                "state": meta["state"],
                                "chunks": {
                                    pid: list(v)
                                    for pid, v in self._live_chunks.items()
                                },
                            }
                        )
                        meta["retained_states"] = retained
                        # legacy mirrors (older readers + replay fallback)
                        meta["prev_state"] = meta["state"]
                        meta["prev_chunks"] = retained[-1]["chunks"]
                meta["state"] = snap
                meta["live_chunks"] = self._live_chunks = {
                    pid: [] for pid in self._live_chunks
                }
            if final:
                meta["finished"] = True
            if self._fault_plan is not None:
                # torn-snapshot injection point: segments + state blobs
                # are durable, the metadata naming them is not
                self._fault_plan.before_meta_commit(snap is not None)
            self.store.put(_META_KEY, json.dumps(meta).encode())
            self._committed_time = meta["last_time"]
            if offsets_changed:
                # superseded offsets snapshots retire only AFTER the
                # metadata naming their replacements is durable;
                # targeted removes, not a per-commit prefix listing
                for key in retired_offsets:
                    self.store.remove(key)
            if snap:
                self._commits_since_snapshot = 0
                if dcn and self.record:
                    # phase 2 (the gen-commit barrier in on_tick) decides
                    # whether this generation's GC may run. Selective
                    # mode never joins that barrier (its interval
                    # snapshots are wall-clock-driven, not lockstep-
                    # aligned), so it keeps the immediate GC below.
                    self._pending_gc = (meta, snap)
                else:
                    self._gc(meta, snap)

    def _group_commit(self, hm) -> None:
        """Two-phase generation commit (Phoenix Mesh). Phase 1 is the
        local durable snapshot commit (commit(snapshot=True) — atomic on
        metadata). Phase 2 is this barrier: every rank exchanges the
        time of its newest durable generation; the group minimum —
        the newest state the WHOLE group can restore — gates the
        deferred GC (only once it has caught up to a rank's own
        generation does that rank retire superseded files, so no rank
        ever deletes what a lagging/degraded peer's group-min restore
        still needs) and is recorded under ``group_commit.json`` as the
        durable audit record of the agreement (restore re-derives the
        agreement with a live barrier over what is actually restorable;
        the retained-generation list guarantees that minimum exists on
        every rank even when a rank dies between the two phases)."""
        pending, self._pending_gc = self._pending_gc, None
        meta = pending[0] if pending is not None else self._load_meta()
        local = (
            int(meta["state"].get("time", 0)) if meta.get("state") else -1
        )
        vals = hm.barrier(("gen-commit", local))
        group = min(v[1] for v in vals.values())
        if group >= 0 and group != self._group_commit_time:
            self._group_commit_time = group
            self.store.put(
                _GROUP_COMMIT_KEY, json.dumps({"time": group}).encode()
            )
        if pending is not None:
            _meta, snap = pending
            if int(snap.get("time", -1)) <= group:
                self._gc(_meta, snap)

    @staticmethod
    def _state_key(gen: int, ident) -> str:
        if str(ident).isdigit():
            return f"states/gen-{gen:06d}/{int(ident):05d}.pkl"
        import urllib.parse

        return (
            f"states/gen-{gen:06d}/"
            f"{urllib.parse.quote(str(ident), safe='')}.pkl"
        )

    @staticmethod
    def _segment_key(ident, name: str, epoch: str, seg_id: int) -> str:
        import urllib.parse

        q = urllib.parse.quote(str(ident), safe="")
        return f"segments/{q}/{name}/{epoch}-{int(seg_id):012d}.seg"

    def _snapshot_operators(self, meta: dict) -> dict | None:
        """Dump every eligible exec's state under a fresh generation.
        Arrangement-backed execs snapshot INCREMENTALLY: their sealed
        segments are content-addressed by (node, part, epoch, seg id), so
        only ids never stored before are written — plus a small
        manifest+residual blob per generation.  Everything else pickles
        monolithically as before.  Returns the state descriptor, or None
        if ANY node failed to serialize — a partial snapshot must not
        truncate the log (correctness over compaction)."""
        import time as _time

        t0 = _time.monotonic()
        gen = int(meta.get("state", {}).get("gen", 0)) + 1
        nodes: dict[str, str] = {}
        written: list[str] = []  # this generation's state blobs
        new_segments: list[str] = []  # segment files first written now
        segment_keys: set[str] = set()  # every segment this gen references
        bytes_written = 0
        for ident, cls, ex, _refeed in self._node_ordinals():
            try:
                arranged = None if self.monolith else ex.arranged_state()
                seg_blobs: list[tuple[str, bytes]] = []
                if arranged is not None:
                    residual, arrs = arranged
                    manifests: dict[str, dict] = {}
                    for name, arr in arrs.items():
                        man = manifest_of(arr)
                        manifests[name] = man
                        by_id = {s.seg_id: s for s in arr.segments}
                        for sd in man["segments"]:
                            skey = self._segment_key(
                                ident, name, man["epoch"], sd["id"]
                            )
                            segment_keys.add(skey)
                            if skey not in self._segments_present:
                                seg_blobs.append(
                                    (skey, segment_to_bytes(by_id[sd["id"]]))
                                )
                    blob = pickle.dumps(
                        {
                            "__pw_arranged__": 1,
                            "residual": residual,
                            "manifests": manifests,
                        }
                    )
                else:
                    state = ex.state_dict()
                    if state is None:
                        continue
                    blob = pickle.dumps(state)
            except Exception:
                import logging

                logging.getLogger("pathway_tpu").warning(
                    "operator snapshot skipped: node %s (%s) has "
                    "unpicklable state; log compaction disabled",
                    cls,
                    ident,
                )
                # clean up this aborted generation's files so they don't
                # orphan until a later successful snapshot, and record the
                # degraded mode durably so operators can see why the input
                # log keeps growing (ADVICE r2: all-or-nothing snapshot)
                for key in written + new_segments:
                    self.store.remove(key)
                self._segments_present.difference_update(new_segments)
                self.degraded_snapshot = f"{cls}#{ident}"
                meta["snapshot_degraded"] = self.degraded_snapshot
                return None
            for skey, data in seg_blobs:
                self.store.put(skey, data)
                self._segments_present.add(skey)
                new_segments.append(skey)
                bytes_written += len(data)
            key = self._state_key(gen, ident)
            self.store.put(key, blob)
            written.append(key)
            bytes_written += len(blob)
            nodes[str(ident)] = cls
        self.degraded_snapshot = None
        meta.pop("snapshot_degraded", None)
        self._m["segs_written"].inc(len(new_segments))
        self._m["snap_bytes"].observe(float(bytes_written))
        self._m["snap_seconds"].observe(_time.monotonic() - t0)
        # snapshot covers everything up to and including the last processed
        # tick; all flushed chunks hold rows with time <= this
        return {
            "gen": gen,
            "time": self._last_real_time,
            "nodes": nodes,
            "segment_keys": sorted(segment_keys),
        }

    def _gc(self, meta: dict, snap: dict) -> None:
        """After the metadata naming the new generation is durable, delete
        the input chunks the snapshot covers, older state generations, and
        segment files no retained generation references (compaction
        retires dead segments).  Multi-process keeps one extra generation
        (state + the inter-snapshot chunks) so a restart can restore the
        group-min time."""
        keep_segments = set(snap.get("segment_keys", ()))
        if getattr(self.runtime, "host_mesh", None) is not None:
            # the group confirmed this generation (gen-commit barrier):
            # trim the retained list back to ONE superseded generation
            # (the lockstep skew bound), then retire files nothing kept
            # references. Metadata is trimmed FIRST — a crash here
            # orphans files (harmless: _segments_present is primed from
            # metadata, so re-minted ids overwrite them) instead of
            # naming deleted ones.
            retained = list(meta.get("retained_states", ()))
            kept = retained[-1:]
            meta["retained_states"] = kept
            if kept:
                meta["prev_state"] = kept[-1]["state"]
                meta["prev_chunks"] = kept[-1]["chunks"]
            self.store.put(_META_KEY, json.dumps(meta).encode())
            keep_inputs = {
                f"inputs/{pid}/chunk-{i:08d}.pkl"
                for entry in kept
                for pid, ids in entry.get("chunks", {}).items()
                for i in ids
            }
            for key in self.store.list_keys("inputs/"):
                if key not in keep_inputs:
                    self.store.remove(key)
            keep = {f"states/gen-{snap['gen']:06d}/"}
            for entry in kept:
                prev = entry.get("state") or {}
                keep.add(f"states/gen-{int(prev['gen']):06d}/")
                keep_segments.update(prev.get("segment_keys", ()))
            for key in self.store.list_keys("states/"):
                if not any(key.startswith(p) for p in keep):
                    self.store.remove(key)
            self._gc_segments(keep_segments)
            return
        for key in self.store.list_keys("inputs/"):
            self.store.remove(key)
        prefix = f"states/gen-{snap['gen']:06d}/"
        for key in self.store.list_keys("states/"):
            if not key.startswith(prefix):
                self.store.remove(key)
        self._gc_segments(keep_segments)

    def _gc_segments(self, keep: set) -> None:
        retired = 0
        for key in self.store.list_keys("segments/"):
            if key not in keep:
                self.store.remove(key)
                self._segments_present.discard(key)
                retired += 1
        if retired:
            self._m["segs_retired"].inc(retired)

    # --- resume path ----------------------------------------------------------

    def replay(self) -> None:
        import time as _time

        t0 = _time.monotonic()
        try:
            self._replay_inner()
        finally:
            self._m["recovery_seconds"].set(_time.monotonic() - t0)

    def _replay_inner(self) -> None:
        """Restore operator snapshots, then feed only the log TAIL (events
        newer than the snapshot) through the graph at original logical
        times, then restore connector offsets."""
        meta = self._load_meta()
        self._chunk_counts = dict(meta.get("chunks", {}))
        self._live_chunks = {
            pid: list(v) for pid, v in meta.get("live_chunks", {}).items()
        }
        if not self.replay_allowed:
            return
        # multi-process: replay ticks must run in lockstep like live ticks
        # (DCN exchanges pair by (channel, tick) group-wide), and the
        # whole group must restore at ONE agreed time — a process whose
        # snapshot is newer than a peer's would otherwise skip replaying
        # logged rows the peer's state still needs. Snapshot generations
        # are lockstep-aligned with skew <= 1, and commit() retains the
        # previous generation, so the group-min time is always locally
        # restorable (the reference's cross-worker flushed-frontier
        # consensus, src/persistence/state.rs:291).
        hm = getattr(self.runtime, "host_mesh", None)
        state_time = -1  # -1 = no snapshot: replay everything incl. t=0
        if hm is None:
            snap = meta.get("state")
            if snap:
                state_time = self._restore_operators(snap)
        else:
            latest = meta.get("state")
            # candidates newest-first: latest, then every retained
            # superseded generation (legacy metadata: prev_state). The
            # gen-commit barrier's retained list guarantees the group
            # minimum is locally restorable on every rank.
            older = [
                r.get("state")
                for r in reversed(meta.get("retained_states", []))
                if r.get("state")
            ]
            if not older and meta.get("prev_state"):
                older = [meta["prev_state"]]
            latest_time = int(latest.get("time", 0)) if latest else -1
            vals = hm.barrier(("replay-gen", latest_time))
            group_time = min(v[1] for v in vals.values())
            chosen = None
            if group_time >= 0:
                for cand in [latest] + older:
                    if cand and int(cand.get("time", 0)) <= group_time:
                        chosen = cand
                        break
            # the phase-2 audit record: what the group had confirmed
            # restorable before the crash — surfaced so an operator can
            # compare it with what this recovery actually picked
            marker_raw = self.store.get(_GROUP_COMMIT_KEY)
            if marker_raw is not None:
                import logging

                logging.getLogger("pathway_tpu").info(
                    "group recovery: restoring generation at time %s "
                    "(live group agreement %s; last durable gen-commit "
                    "agreement %s)",
                    chosen.get("time") if chosen else None,
                    group_time,
                    json.loads(marker_raw.decode()).get("time"),
                )
            if chosen is not None:
                state_time = self._restore_operators(chosen)
        # Replica Shield: a restarted WRITER restored the index at
        # state_time — ticks at or before it are not in the (fresh)
        # delta ring, only in the snapshot generation; tell the
        # publisher before replay re-publishes the log tail, so replica
        # subscriptions from older ticks resync instead of silently
        # missing the gap
        if state_time >= 0:
            from pathway_tpu.parallel import replicate

            pub = replicate.publisher()
            if pub is not None:
                pub.set_floor(state_time)
        # receiver-side floor: drop exchanged partitions already covered
        # by this process's restored state
        if hm is not None and state_time >= 0:
            for ex in self.runtime.execs.values():
                if hasattr(ex, "replay_floor"):
                    ex.replay_floor = state_time
        # sender-side floor must be the GROUP minimum: rows this process
        # logged may route to a peer restored at an older time (e.g. a
        # structural-mismatch fallback on one process)
        if hm is not None:
            vals = hm.barrier(("replay-floor", state_time))
            group_floor = min(v[1] for v in vals.values())
        else:
            group_floor = state_time
        need_emit = any(
            getattr(ex, "_restore_emit", None)
            for ex in self.runtime.execs.values()
        )
        if hm is not None:
            vals = hm.barrier(("replay-emit", need_emit))
            need_emit = any(v[1] for v in vals.values())
        if need_emit:
            # flush restored-accumulator re-emissions at the run's
            # INITIAL time, before any log-tail replay at later times —
            # otherwise the emission timestamp would be whatever data
            # tick happens to run first
            self._orig_tick(0, None)
        events: list[tuple[int, int, DiffBatch]] = []  # (time, node_id, batch)
        for pid, node in self.inputs.items():
            chunk_ids = self._live_chunks.get(pid)
            if chunk_ids is None:  # pre-compaction metadata: contiguous
                chunk_ids = list(range(meta.get("chunks", {}).get(pid, 0)))
            if hm is not None:
                # retained-generation chunks too: they cover the spans
                # between the retained generations, needed when the
                # group restores an older one
                retained_chunks = [
                    i
                    for r in meta.get("retained_states", [])
                    for i in r.get("chunks", {}).get(pid, [])
                ]
                if not retained_chunks:
                    retained_chunks = list(
                        meta.get("prev_chunks", {}).get(pid, [])
                    )
                chunk_ids = list(
                    dict.fromkeys(retained_chunks + list(chunk_ids))
                )
            for i in chunk_ids:
                raw = self.store.get(f"inputs/{pid}/chunk-{i:08d}.pkl")
                if raw is None:
                    continue
                for t, rows in pickle.loads(raw):
                    if t <= group_floor:
                        continue  # covered by every process's state
                    events.append(
                        (t, node.id, DiffBatch.from_rows(rows, node.column_names))
                    )
        self.replayed_events = len(events)
        events.sort(key=lambda e: e[0])
        i, n = 0, len(events)
        if hm is None:
            while i < n:
                t = events[i][0]
                injected: dict[int, list[DiffBatch]] = {}
                while i < n and events[i][0] == t:
                    injected.setdefault(events[i][1], []).append(events[i][2])
                    i += 1
                self._orig_tick(t, injected)
        else:
            while True:
                local_next = events[i][0] if i < n else END_OF_TIME
                vals = hm.barrier(("replay", local_next))
                t = min(v[1] for v in vals.values())
                if t >= END_OF_TIME:
                    break
                injected = {}
                while i < n and events[i][0] == t:
                    injected.setdefault(events[i][1], []).append(events[i][2])
                    i += 1
                self._orig_tick(t, injected)
        # restore offsets so live sources continue past what was replayed
        # (the metadata names the committed snapshot; legacy stores fall
        # back to the old in-place key)
        for pid, node in () if self.selective else self.inputs.items():
            okey = meta.get("offsets", {}).get(pid, f"offsets/{pid}.pkl")
            raw = self.store.get(okey)
            if raw is None:
                continue
            state = pickle.loads(raw)
            src = node.source
            if isinstance(state, dict) and state.get("__static_done__"):
                if isinstance(src, StaticSource):
                    node.source = _EmptyStatic(node.column_names)
            elif hasattr(src, "seek"):
                src.seek(state)

    def _restore_operators(self, snap: dict) -> int:
        """Load every node's snapshotted state; on any structural mismatch
        (different graph shape/classes than when snapshotted) fall back to
        full-log replay by reporting state_time -1. In selective mode a
        missing/renamed identity just means that operator starts fresh —
        there is no log to fall back to.

        Everything that can fail (blob fetch, unpickle, segment fetch and
        arrangement rebuild) happens BEFORE any exec mutates, so a torn
        snapshot falls back to log replay over pristine fresh state.
        Arrangement-backed states rebuild over ``get_buffer`` views — on
        the filesystem store that is an mmap, so restore cost is
        O(manifest), with column bytes faulting in lazily."""
        gen = int(snap["gen"])
        current = {
            str(ident): (cls, ex, refeed)
            for ident, cls, ex, refeed in self._node_ordinals()
        }
        loaded: list[tuple[Any, bool, dict, dict | None]] = []
        for ident, cls in snap.get("nodes", {}).items():
            if ident not in current or current[ident][0] != cls:
                if self.selective:
                    continue
                return -1
            raw = self.store.get(self._state_key(gen, ident))
            if raw is None:
                if self.selective:
                    continue
                return -1
            state = pickle.loads(raw)
            _cls, ex, refeed = current[ident]
            if isinstance(state, dict) and state.get("__pw_arranged__"):
                try:
                    arrs = {}
                    for name, man in state["manifests"].items():
                        arrs[name] = load_arrangement(
                            man,
                            lambda sid, name=name, epoch=man[
                                "epoch"
                            ], ident=ident: self.store.get_buffer(
                                self._segment_key(ident, name, epoch, sid)
                            ),
                        )
                except Exception:
                    import logging

                    logging.getLogger("pathway_tpu").warning(
                        "segment snapshot for node %s (%s) unreadable; "
                        "falling back to log replay",
                        cls,
                        ident,
                        exc_info=True,
                    )
                    if self.selective:
                        continue
                    return -1
                check = getattr(ex, "check_arranged_state", None)
                if check is not None and not check(
                    state["residual"], arrs
                ):
                    # structural mismatch the class-name check cannot
                    # see (e.g. PATHWAY_ENGINE_SHARDS changed between
                    # runs): surfaced BEFORE any exec mutates, so the
                    # fallback replays the log over pristine state
                    import logging

                    logging.getLogger("pathway_tpu").warning(
                        "snapshot for node %s (%s) does not match the "
                        "current execution layout; falling back to log "
                        "replay",
                        cls,
                        ident,
                    )
                    if self.selective:
                        continue
                    return -1
                loaded.append((ex, refeed, state["residual"], arrs))
            else:
                loaded.append((ex, refeed, state, None))
        for ex, refeed, state, arrs in loaded:
            if arrs is None:
                ex.load_state(state)
            else:
                ex.load_arranged_state(state, arrs)
            if not refeed:
                # this node's logged inputs do NOT re-feed and its
                # downstream consumers were restored too — re-emitting
                # its contents would double-count. DCN/sharded wrappers
                # delegate load_state, so the pending emission sits on
                # their INNER exec.
                for target in (ex, getattr(ex, "inner", None)):
                    if target is not None and getattr(
                        target, "_restore_emit", None
                    ):
                        target._restore_emit = None
        if loaded:
            self.restored_from_snapshot = True
        return int(snap.get("time", 0))


def attach_persistence(runtime: Runtime, config: Any) -> PersistenceDriver:
    driver = PersistenceDriver(runtime, config)
    # graceful degradation (Phoenix Mesh): while recovery replay runs,
    # Surge-Gated endpoints answer from the last hydrated index snapshot
    # instead of queueing behind the replay — operator restore happens
    # up front (mmap), so the stale corpus is available immediately
    from pathway_tpu.serving import degrade

    _REPLAY_REASON = "restoring persisted state (recovery replay)"
    degrade.enter_recovery(_REPLAY_REASON)
    try:
        driver.replay()
    finally:
        degrade.exit_recovery(_REPLAY_REASON)
    runtime.tick = driver.on_tick  # type: ignore[method-assign]
    runtime.persistence_driver = driver  # type: ignore[attr-defined]
    return driver
