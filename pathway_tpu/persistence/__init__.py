"""pw.persistence — checkpoint/resume configuration
(reference: python/pathway/persistence/__init__.py + src/persistence/).

The engine glue (input event logs + state snapshots + resume) lives in
pathway_tpu/persistence/_runtime_glue.py; blob stores in backends.py."""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any


class Backend:
    kind: str = "none"

    @classmethod
    def filesystem(cls, path: str | os.PathLike) -> "FilesystemBackend":
        return FilesystemBackend(str(path))

    @classmethod
    def s3(cls, root_path: str, bucket_settings: Any = None) -> "S3Backend":
        return S3Backend(root_path, bucket_settings)

    @classmethod
    def azure(cls, root_path: str, account: Any = None, **kw) -> "S3Backend":
        return S3Backend(root_path, account)

    @classmethod
    def mock(cls, events: Any = None, name: str = "default") -> "MockBackend":
        return MockBackend(name=name)

    @classmethod
    def memory(cls, name: str = "default") -> "MockBackend":
        return MockBackend(name=name)


@dataclass
class FilesystemBackend(Backend):
    path: str
    kind: str = "filesystem"


@dataclass
class S3Backend(Backend):
    root_path: str
    bucket_settings: Any = None
    kind: str = "s3"


@dataclass
class MockBackend(Backend):
    kind: str = "mock"
    store: dict = field(default_factory=dict)
    name: str = "default"


class PersistenceMode:
    """(reference: src/connectors/mod.rs:108 PersistenceMode)"""

    BATCH = "batch"
    SPEEDRUN = "speedrun"
    PERSISTING = "persisting"
    OPERATOR_PERSISTING = "operator_persisting"
    # only operators with an explicit name persist; inputs are not logged
    # (reference: SELECTIVE_PERSISTING in src/connectors/mod.rs:108)
    SELECTIVE_PERSISTING = "selective_persisting"


class SnapshotAccess:
    """(reference: src/connectors/mod.rs:154 SnapshotAccess) — `record`
    writes the input log without replaying (record/replay debugging),
    `replay` reads it without recording, `full` does both."""

    RECORD = "record"
    REPLAY = "replay"
    FULL = "full"


@dataclass
class Config:
    backend: Backend | None = None
    snapshot_interval_ms: int = 0
    snapshot_access: Any = None
    persistence_mode: Any = None
    continue_after_replay: bool = True
    # operator-state snapshots (reference: operator_snapshot.rs + the
    # OPERATOR_PERSISTING mode): every `snapshot_every`-th data commit dumps
    # all exec states and truncates the covered input log, bounding both
    # restart replay and log growth. False = input-log-only persistence.
    snapshot_operators: bool = True
    snapshot_every: int = 8

    @classmethod
    def simple_config(
        cls,
        backend: Backend,
        snapshot_interval_ms: int = 0,
        **kwargs: Any,
    ) -> "Config":
        return cls(
            backend=backend, snapshot_interval_ms=snapshot_interval_ms, **kwargs
        )


def simple_config(backend: Backend, **kwargs: Any) -> Config:
    return Config.simple_config(backend, **kwargs)
