"""pw.persistence — checkpoint/resume configuration
(reference: python/pathway/persistence/__init__.py + src/persistence/).

The engine glue (input event logs + state snapshots + resume) lives in
pathway_tpu/persistence/engine_glue.py."""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any


class Backend:
    kind: str = "none"

    @classmethod
    def filesystem(cls, path: str | os.PathLike) -> "FilesystemBackend":
        return FilesystemBackend(str(path))

    @classmethod
    def s3(cls, root_path: str, bucket_settings: Any = None) -> "S3Backend":
        return S3Backend(root_path, bucket_settings)

    @classmethod
    def azure(cls, root_path: str, account: Any = None, **kw) -> "S3Backend":
        return S3Backend(root_path, account)

    @classmethod
    def mock(cls, events: Any = None) -> "MockBackend":
        return MockBackend()


@dataclass
class FilesystemBackend(Backend):
    path: str
    kind: str = "filesystem"


@dataclass
class S3Backend(Backend):
    root_path: str
    bucket_settings: Any = None
    kind: str = "s3"


@dataclass
class MockBackend(Backend):
    kind: str = "mock"
    store: dict = field(default_factory=dict)


@dataclass
class Config:
    backend: Backend | None = None
    snapshot_interval_ms: int = 0
    snapshot_access: Any = None
    persistence_mode: Any = None
    continue_after_replay: bool = True

    @classmethod
    def simple_config(
        cls,
        backend: Backend,
        snapshot_interval_ms: int = 0,
        **kwargs: Any,
    ) -> "Config":
        return cls(
            backend=backend, snapshot_interval_ms=snapshot_interval_ms, **kwargs
        )


def simple_config(backend: Backend, **kwargs: Any) -> Config:
    return Config.simple_config(backend, **kwargs)
