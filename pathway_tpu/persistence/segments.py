"""Segment codec — the arrangement's stable on-disk form.

The differential-dataflow design the paper rides says *arranged
collections ARE the checkpoint* (reference: operator snapshots are
chunked dumps of arrangement batches, src/persistence/
operator_snapshot.rs:21-31): an arrangement's immutable sorted segments
(engine/arrangement.py) need only be retained, not re-encoded, for the
operator to be durable.  This module gives each sealed ``_Segment`` a
self-contained byte form and each ``Arrangement`` a tiny JSON manifest:

* ``segment_to_bytes`` — header JSON + 64-byte-aligned raw ndarray
  buffers.  Numeric/string/datetime columns serialize as their exact
  dtype bytes (no pickle); object columns of uniform ndarrays
  (embeddings) as one stacked raw block; anything else falls back to a
  per-column pickle.  The core index arrays (jk, rowkey, diff, age,
  fingerprint) are always raw u64/i64.
* ``segment_from_buffer`` — reconstructs the segment with zero-copy
  ``np.frombuffer`` views over the given buffer.  Hand it an mmap-backed
  memoryview (``BackendStore.get_buffer``) and recovery is O(page cache):
  column bytes fault in lazily as probes touch them.
* ``manifest_of`` / ``load_arrangement`` — the arrangement-level
  save/load pair.  A manifest names segment ids, not bytes; segment ids
  are immutable content addresses (arrangement.py ``_Segment.seg_id``),
  so the persistence glue writes only ids it has never stored — the
  incremental-checkpoint contract (bytes ∝ churn, not state size).
"""

from __future__ import annotations

import json
import pickle
from typing import Callable

import numpy as np

from pathway_tpu.engine.arrangement import Arrangement, _Segment
from pathway_tpu.engine.batch import _obj_column, uniform_element_spec

MAGIC = b"PWSEG01\n"
_ALIGN = 64

_CORE = (  # (attr, dtype) — fixed-layout index arrays of every segment
    ("jks", "<u8"),
    ("keys", "<u8"),
    ("diffs", "<i8"),
    ("ages", "<i8"),
    ("mix_sorted", "<u8"),
)


def _aligned(n: int) -> int:
    return n + (-n % _ALIGN)


def _encode_col(col: np.ndarray) -> tuple[dict, bytes]:
    col = np.asarray(col)
    if col.ndim == 1 and col.dtype != object and not col.dtype.hasobject:
        return (
            {"kind": "raw", "dtype": col.dtype.str},
            np.ascontiguousarray(col).tobytes(),
        )
    spec = uniform_element_spec(col) if col.dtype == object else None
    if spec is not None:
        dtype, shape = spec
        stacked = np.stack(list(col)) if len(col) else np.empty((0, *shape))
        return (
            {
                "kind": "stacked",
                "dtype": np.dtype(dtype).str,
                "shape": list(shape),
            },
            np.ascontiguousarray(stacked, dtype=dtype).tobytes(),
        )
    return (
        {"kind": "pickle"},
        pickle.dumps(col, protocol=pickle.HIGHEST_PROTOCOL),
    )


def segment_to_bytes(seg: _Segment) -> bytes:
    """Serialize one sealed segment; raw for everything numeric."""
    n = len(seg)
    sections: list[bytes] = []
    cursor = 0

    def add(data: bytes) -> tuple[int, int]:
        nonlocal cursor
        off = cursor
        sections.append(data)
        pad = -len(data) % _ALIGN
        if pad:
            sections.append(b"\x00" * pad)
        cursor = off + len(data) + pad
        return off, len(data)

    header: dict = {
        "v": 1,
        "id": int(seg.seg_id),
        "n": int(n),
        "clean": bool(seg.clean),
    }
    core = {}
    for attr, dtype in _CORE:
        arr = np.ascontiguousarray(getattr(seg, attr), dtype=dtype)
        off, nbytes = add(arr.tobytes())
        core[attr] = {"off": off, "nbytes": nbytes}
    header["core"] = core
    cols = []
    for col in seg.cols:
        desc, data = _encode_col(col)
        desc["off"], desc["nbytes"] = add(data)
        cols.append(desc)
    header["cols"] = cols
    hjson = json.dumps(header, separators=(",", ":")).encode()
    head = MAGIC + len(hjson).to_bytes(4, "little") + hjson
    head += b"\x00" * (-len(head) % _ALIGN)
    return head + b"".join(sections)


def _view(buf, base: int, sec: dict, dtype: str, n: int) -> np.ndarray:
    dt = np.dtype(dtype)
    return np.frombuffer(
        buf, dtype=dt, count=sec["nbytes"] // dt.itemsize, offset=base + sec["off"]
    )


def segment_from_buffer(buf) -> _Segment:
    """Reconstruct a segment as zero-copy views over ``buf`` (bytes or an
    mmap-backed memoryview; the arrays keep the buffer alive)."""
    mv = memoryview(buf)
    if bytes(mv[: len(MAGIC)]) != MAGIC:
        raise ValueError("not a PWSEG01 segment blob")
    hlen = int.from_bytes(bytes(mv[len(MAGIC) : len(MAGIC) + 4]), "little")
    hstart = len(MAGIC) + 4
    header = json.loads(bytes(mv[hstart : hstart + hlen]).decode())
    base = _aligned(hstart + hlen)
    n = int(header["n"])
    core = {
        attr: _view(mv, base, header["core"][attr], dtype, n)
        for attr, dtype in _CORE
    }
    cols: list[np.ndarray] = []
    for desc in header["cols"]:
        kind = desc["kind"]
        if kind == "raw":
            cols.append(_view(mv, base, desc, desc["dtype"], n))
        elif kind == "stacked":
            shape = tuple(desc["shape"])
            flat = _view(mv, base, desc, desc["dtype"], n)
            cols.append(_obj_column(list(flat.reshape((n, *shape)))))
        elif kind == "pickle":
            raw = bytes(mv[base + desc["off"] : base + desc["off"] + desc["nbytes"]])
            cols.append(pickle.loads(raw))
        else:  # a future format must fail loud, not half-load
            raise ValueError(f"unknown column kind {kind!r}")
    return _Segment(
        core["jks"],
        core["keys"],
        core["diffs"],
        core["ages"],
        cols,
        core["mix_sorted"],
        bool(header["clean"]),
        int(header["id"]),
    )


def manifest_of(arr: Arrangement) -> dict:
    """Seal staged deltas and describe the arrangement as a small JSON
    document naming segment ids — the only per-snapshot metadata the
    incremental checkpoint needs."""
    arr.seal()
    return {
        "v": 1,
        "epoch": arr.epoch,
        "n_cols": int(arr.n_cols),
        "next_age": int(arr._next_age),
        "next_seg_id": int(arr._next_seg_id),
        "neg_entries": int(arr._neg_entries),
        "segments": [
            {"id": int(s.seg_id), "n": len(s)} for s in arr.segments
        ],
    }


def load_arrangement(
    manifest: dict,
    fetch: Callable[[int], "memoryview | bytes | None"],
    *,
    max_segments: int | None = None,
    compact_ratio: float | None = None,
) -> Arrangement:
    """Rebuild an arrangement from a manifest; ``fetch(seg_id)`` returns
    the segment's buffer (mmap-backed when the store supports it) or
    None, which raises — a missing segment means the snapshot is torn and
    the caller must fall back to log replay."""
    segments: list[_Segment] = []
    for desc in manifest["segments"]:
        buf = fetch(int(desc["id"]))
        if buf is None:
            raise KeyError(f"segment {desc['id']} missing from store")
        seg = segment_from_buffer(buf)
        if seg.seg_id != int(desc["id"]) or len(seg) != int(desc["n"]):
            raise ValueError(
                f"segment {desc['id']} does not match its manifest entry "
                f"(got id={seg.seg_id} n={len(seg)})"
            )
        segments.append(seg)
    return Arrangement.restore(
        int(manifest["n_cols"]),
        segments,
        epoch=str(manifest["epoch"]),
        next_age=int(manifest["next_age"]),
        next_seg_id=int(manifest["next_seg_id"]),
        neg_entries=int(manifest.get("neg_entries", 0)),
        max_segments=max_segments,
        compact_ratio=compact_ratio,
    )
