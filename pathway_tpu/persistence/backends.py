"""Persistence backend stores — blob KV abstraction.

TPU-native equivalent of the reference's `PersistenceBackend` family
(reference: src/persistence/backends/{file,s3,memory,mock}.rs): a tiny
key->bytes store with atomic writes, used by the input event log, offset
snapshots and metadata commits. The filesystem store is the production
backend; the memory store keeps a process-global registry so tests can
"restart" an engine in-process and find their snapshot again.
"""

from __future__ import annotations

import os
import threading


class BackendStore:
    def put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def get(self, key: str) -> bytes | None:
        raise NotImplementedError

    def list_keys(self, prefix: str = "") -> list[str]:
        raise NotImplementedError

    def remove(self, key: str) -> None:
        raise NotImplementedError


class FilesystemStore(BackendStore):
    """Atomic blob store on a local (or NFS/GCS-fuse) directory.

    Writes go to a temp file + rename so a crash mid-write never leaves a
    torn blob (the reference gets the same guarantee from its file backend,
    src/persistence/backends/file.rs)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()

    def _path(self, key: str) -> str:
        return os.path.join(self.root, key)

    def put(self, key: str, data: bytes) -> None:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with self._lock:
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)

    def get(self, key: str) -> bytes | None:
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except OSError:
            return None

    def list_keys(self, prefix: str = "") -> list[str]:
        out = []
        for root, _dirs, files in os.walk(self.root):
            for f in files:
                if f.endswith(".tmp"):
                    continue
                rel = os.path.relpath(os.path.join(root, f), self.root)
                rel = rel.replace(os.sep, "/")
                if rel.startswith(prefix):
                    out.append(rel)
        return sorted(out)

    def remove(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except OSError:
            pass


_MEMORY_REGISTRY: dict[str, dict[str, bytes]] = {}
_MEMORY_LOCK = threading.Lock()


class MemoryStore(BackendStore):
    """Process-global in-memory store (reference: backends/memory.rs).
    Two engines constructed with the same `name` share the blobs — this is
    the test harness for kill/restart cycles without touching disk."""

    def __init__(self, name: str = "default"):
        with _MEMORY_LOCK:
            self._blobs = _MEMORY_REGISTRY.setdefault(name, {})

    def put(self, key: str, data: bytes) -> None:
        with _MEMORY_LOCK:
            self._blobs[key] = data

    def get(self, key: str) -> bytes | None:
        with _MEMORY_LOCK:
            return self._blobs.get(key)

    def list_keys(self, prefix: str = "") -> list[str]:
        with _MEMORY_LOCK:
            return sorted(k for k in self._blobs if k.startswith(prefix))

    def remove(self, key: str) -> None:
        with _MEMORY_LOCK:
            self._blobs.pop(key, None)


def store_for_backend(backend) -> BackendStore:
    """Map a user-facing `pw.persistence.Backend` config onto a store."""
    kind = getattr(backend, "kind", "filesystem")
    if kind == "filesystem":
        return FilesystemStore(backend.path)
    if kind == "memory" or kind == "mock":
        return MemoryStore(getattr(backend, "name", "default"))
    if kind == "s3":
        # No S3 SDK baked into the image: treat the root_path as a mounted
        # object-store path (gcsfuse/s3fs) — same durability contract.
        return FilesystemStore(getattr(backend, "root_path", "."))
    raise ValueError(f"unknown persistence backend kind {kind!r}")
