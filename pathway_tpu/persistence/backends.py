"""Persistence backend stores — blob KV abstraction.

TPU-native equivalent of the reference's `PersistenceBackend` family
(reference: src/persistence/backends/{file,s3,memory,mock}.rs): a tiny
key->bytes store with atomic writes, used by the input event log, offset
snapshots and metadata commits. The filesystem store is the production
backend; the memory store keeps a process-global registry so tests can
"restart" an engine in-process and find their snapshot again.
"""

from __future__ import annotations

import os
import threading


class BackendStore:
    def put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def get(self, key: str) -> bytes | None:
        raise NotImplementedError

    def get_buffer(self, key: str) -> memoryview | None:
        """Read-only buffer view of a blob — stores that can, serve it
        zero-copy (the filesystem store mmaps, so segment recovery is
        O(page faults), not O(read+copy)); the default materializes."""
        data = self.get(key)
        return None if data is None else memoryview(data)

    def remove(self, key: str) -> None:
        raise NotImplementedError

    def list_keys(self, prefix: str = "") -> list[str]:
        raise NotImplementedError


class FilesystemStore(BackendStore):
    """Atomic blob store on a local (or NFS/GCS-fuse) directory.

    Writes go to a temp file + rename so a crash mid-write never leaves a
    torn blob (the reference gets the same guarantee from its file backend,
    src/persistence/backends/file.rs)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()

    def _path(self, key: str) -> str:
        return os.path.join(self.root, key)

    def put(self, key: str, data: bytes) -> None:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with self._lock:
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)

    def get(self, key: str) -> bytes | None:
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except OSError:
            return None

    def get_buffer(self, key: str) -> memoryview | None:
        """mmap the blob read-only: arrays built over this view fault
        pages in lazily, which is what makes segment recovery
        O(mmap + manifest) instead of O(state bytes)."""
        import mmap as _mmap

        try:
            with open(self._path(key), "rb") as f:
                try:
                    mm = _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)
                except ValueError:  # empty file cannot be mapped
                    return memoryview(f.read())
        except OSError:
            return None
        return memoryview(mm)

    def list_keys(self, prefix: str = "") -> list[str]:
        out = []
        for root, _dirs, files in os.walk(self.root):
            for f in files:
                if f.endswith(".tmp"):
                    continue
                rel = os.path.relpath(os.path.join(root, f), self.root)
                rel = rel.replace(os.sep, "/")
                if rel.startswith(prefix):
                    out.append(rel)
        return sorted(out)

    def remove(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except OSError:
            pass


_MEMORY_REGISTRY: dict[str, dict[str, bytes]] = {}
_MEMORY_LOCK = threading.Lock()


class MemoryStore(BackendStore):
    """Process-global in-memory store (reference: backends/memory.rs).
    Two engines constructed with the same `name` share the blobs — this is
    the test harness for kill/restart cycles without touching disk."""

    def __init__(self, name: str = "default"):
        with _MEMORY_LOCK:
            self._blobs = _MEMORY_REGISTRY.setdefault(name, {})

    def put(self, key: str, data: bytes) -> None:
        with _MEMORY_LOCK:
            self._blobs[key] = data

    def get(self, key: str) -> bytes | None:
        with _MEMORY_LOCK:
            return self._blobs.get(key)

    def list_keys(self, prefix: str = "") -> list[str]:
        with _MEMORY_LOCK:
            return sorted(k for k in self._blobs if k.startswith(prefix))

    def remove(self, key: str) -> None:
        with _MEMORY_LOCK:
            self._blobs.pop(key, None)


class FsspecStore(BackendStore):
    """Object-store backend over a real client (reference:
    src/persistence/backends/s3.rs:150 over rust-s3). Any fsspec protocol
    works — ``s3://`` (s3fs), ``gs://`` (gcsfs), ``memory://`` (in-process
    fake object store, how tests exercise this path without credentials).
    Object-store PUTs are atomic per object, giving the same torn-write
    guarantee the filesystem store gets from rename."""

    def __init__(self, url: str, storage_options: dict | None = None):
        import fsspec

        assert "://" in url, f"expected a protocol URL, got {url!r}"
        self.protocol, rest = url.split("://", 1)
        self.fs = fsspec.filesystem(self.protocol, **(storage_options or {}))
        self.root = rest.rstrip("/")

    def _path(self, key: str) -> str:
        return f"{self.root}/{key}"

    def put(self, key: str, data: bytes) -> None:
        path = self._path(key)
        if "/" in key:
            # directory-backed protocols (file://) need parents; a no-op
            # on true object stores
            try:
                self.fs.makedirs(path.rsplit("/", 1)[0], exist_ok=True)
            except OSError:
                pass
        self.fs.pipe_file(path, data)

    def get(self, key: str) -> bytes | None:
        try:
            return self.fs.cat_file(self._path(key))
        except OSError:
            return None

    def list_keys(self, prefix: str = "") -> list[str]:
        # narrow the listing to the deepest directory of the prefix
        base = f"{self.root}/{prefix}"
        directory = base.rsplit("/", 1)[0]
        try:
            found = self.fs.find(directory)
        except OSError:
            return []
        out = []
        lead = f"{self.root}/"
        for p in found:
            p = p.lstrip("/")
            if not p.startswith(lead.lstrip("/")):
                continue
            rel = p[len(lead.lstrip("/")) :]
            if rel.startswith(prefix):
                out.append(rel)
        return sorted(out)

    def remove(self, key: str) -> None:
        try:
            self.fs.rm_file(self._path(key))
        except OSError:
            pass


def store_for_backend(backend) -> BackendStore:
    """Map a user-facing `pw.persistence.Backend` config onto a store."""
    kind = getattr(backend, "kind", "filesystem")
    if kind == "filesystem":
        return FilesystemStore(backend.path)
    if kind == "memory" or kind == "mock":
        return MemoryStore(getattr(backend, "name", "default"))
    if kind == "s3":
        root = getattr(backend, "root_path", ".")
        if "://" in root:
            settings = getattr(backend, "bucket_settings", None)
            if settings is None:
                opts = None
            elif hasattr(settings, "storage_options"):
                opts = settings.storage_options()
            elif isinstance(settings, dict):
                opts = settings
            else:
                # silently dropping explicit credentials would connect
                # with ambient identity and fail far from the cause
                raise TypeError(
                    "bucket_settings must be an AwsS3Settings-like object "
                    "with .storage_options() or a dict of fsspec storage "
                    f"options, got {type(settings).__name__}"
                )
            return FsspecStore(root, opts)
        # bare path: a mounted object store (gcsfuse/s3fs mount)
        return FilesystemStore(root)
    raise ValueError(f"unknown persistence backend kind {kind!r}")
