"""Cached object storage — versioned, download-once blob cache over a
persistence backend (reference: src/persistence/cached_object_storage.rs:
1-377). Object-store connectors use it so (a) an unchanged object is never
downloaded twice within a run and (b) after a restart the exact bytes of
every previously-ingested object version are still available locally,
letting recovery reparse without refetching (and without the source
needing to still exist).

Layout under the backend: ``objects/meta/{version:016d}.json`` — an
append-only event log of Update/Delete per URI — and
``objects/blobs/{version:016d}.blob`` holding the object bytes for Update
events. The latest state is rebuilt from the event log at startup;
``vacuum`` drops superseded versions (the reference's background cleanup
collapsed to an explicit call in the single-driver setting)."""

from __future__ import annotations

import json
from typing import Any, Iterable

from pathway_tpu.persistence.backends import BackendStore, store_for_backend

_META_PREFIX = "objects/meta/"
_BLOB_PREFIX = "objects/blobs/"


class CachedObjectStorage:
    def __init__(self, store: BackendStore | Any):
        if not isinstance(store, BackendStore):
            store = store_for_backend(store)
        self.store = store
        self._version = 0
        # uri -> (version, metadata dict) of the live object
        self._live: dict[str, tuple[int, dict]] = {}
        self._rebuild()

    # --- construction -------------------------------------------------------

    def _rebuild(self) -> None:
        for key in sorted(self.store.list_keys(_META_PREFIX)):
            raw = self.store.get(key)
            if raw is None:
                continue
            try:
                event = json.loads(raw.decode())
            except ValueError:
                continue
            version = int(event["version"])
            self._version = max(self._version, version)
            uri = event["uri"]
            if event["type"] == "update":
                self._live[uri] = (version, event.get("metadata", {}))
            else:
                self._live.pop(uri, None)

    # --- write path ---------------------------------------------------------

    def _next_version(self) -> int:
        self._version += 1
        return self._version

    def upsert(self, uri: str, contents: bytes, metadata: dict | None = None) -> int:
        """Store a new version of `uri`; blob first, metadata event last so
        a crash mid-upsert leaves no dangling live entry."""
        version = self._next_version()
        metadata = dict(metadata or {})
        self.store.put(f"{_BLOB_PREFIX}{version:016d}.blob", contents)
        self.store.put(
            f"{_META_PREFIX}{version:016d}.json",
            json.dumps(
                {"uri": uri, "version": version, "type": "update",
                 "metadata": metadata}
            ).encode(),
        )
        self._live[uri] = (version, metadata)
        return version

    def remove(self, uri: str) -> int:
        version = self._next_version()
        self.store.put(
            f"{_META_PREFIX}{version:016d}.json",
            json.dumps(
                {"uri": uri, "version": version, "type": "delete"}
            ).encode(),
        )
        self._live.pop(uri, None)
        return version

    # --- lookups (latest state) --------------------------------------------

    def contains(self, uri: str) -> bool:
        return uri in self._live

    def get(self, uri: str) -> bytes | None:
        entry = self._live.get(uri)
        if entry is None:
            return None
        return self.store.get(f"{_BLOB_PREFIX}{entry[0]:016d}.blob")

    def metadata(self, uri: str) -> dict | None:
        entry = self._live.get(uri)
        return dict(entry[1]) if entry else None

    def version_of(self, uri: str) -> int | None:
        entry = self._live.get(uri)
        return entry[0] if entry else None

    def actual_version(self) -> int:
        return self._version

    def uris(self) -> Iterable[str]:
        return list(self._live.keys())

    # --- maintenance --------------------------------------------------------

    def vacuum(self) -> int:
        """Delete blobs and events superseded by newer versions (or by a
        delete). Returns the number of removed versions."""
        keep = {v for v, _m in self._live.values()}
        removed = 0
        for key in self.store.list_keys(_META_PREFIX):
            version = int(key[len(_META_PREFIX) :].split(".")[0])
            if version in keep:
                continue
            self.store.remove(key)
            self.store.remove(f"{_BLOB_PREFIX}{version:016d}.blob")
            removed += 1
        return removed
