"""Standby writer — hot takeover of the ingest/delta-publishing role.

Replica Shield (PR 10) left ONE serving SPOF: the writer.  Kill it and
every replica keeps answering, but the read plane goes permanently
stale — nothing publishes deltas, nothing snapshots, nothing ingests.
``StandbyWriter`` closes that gap:

* **Shadow subscription** — the standby subscribes to the primary's
  delta stream exactly like a replica (``DeltaStreamClient`` with the
  reserved ``STANDBY_ID``; its leg carries the ``repl:standby`` wire
  channel so Fault Forge can target it) and PERSISTS its position
  (applied tick + highest incarnation seen) with an atomic
  tmp+rename, so a restarted standby knows where the stream was.

* **Death detection** — the primary is declared dead when the
  subscription stays disconnected for ``grace_s`` continuously
  (every redial failing — the analog of the mesh liveness timeout), or
  immediately when :meth:`notify_failure` is called (wire it to a
  ``HostMesh.add_failure_listener`` / ``FailoverRouter`` listener for
  detection-time takeover).

* **Takeover** — the standby re-opens the PR-7/8 persistence store
  (``resume_point`` reads the newest committed generation + the
  group-commit barrier record), bumps ``PATHWAY_MESH_INCARNATION`` past
  every incarnation it has seen, and respawns the writer role (the
  supervised ``argv`` — the writer process itself restores the
  generation, replays the connector log tail, calls
  ``DeltaStreamServer.set_floor`` and resumes publishing on the SAME
  ``PATHWAY_REPL_PORT``).  Replicas reconnect through the existing
  resync-from-floor path; the bumped incarnation in the ``PWRP2``
  suback fences a zombie primary that comes back from the dead
  (parallel/replicate.py).

An in-process ``on_takeover`` callback replaces the subprocess spawn
for tests and embedded deployments.

``python -m pathway_tpu.parallel.standby -- python writer.py`` runs the
env-configured standby role (the shape the chaos bench spawns):
PATHWAY_REPL_PORT names the primary's delta endpoint; on takeover the
argv is spawned under the Phoenix Mesh supervisor with the bumped
incarnation.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable

from pathway_tpu.parallel.replicate import STANDBY_ID, DeltaStreamClient


def grace_env() -> float:
    """Seconds of continuous primary unreachability before the standby
    takes over (PATHWAY_STANDBY_GRACE_MS, default 5000)."""
    raw = os.environ.get("PATHWAY_STANDBY_GRACE_MS", "5000") or "5000"
    try:
        ms = float(raw)
    except ValueError:
        raise ValueError(
            f"PATHWAY_STANDBY_GRACE_MS={raw!r} is not a number"
        ) from None
    return max(ms, 100.0) / 1000.0


class StandbyWriter:
    """Shadow the primary's delta stream; take over when it dies.

    ``on_takeover(standby) -> None`` runs the takeover (default: spawn
    ``argv`` under a 1-rank GroupSupervisor with
    ``PATHWAY_MESH_INCARNATION`` = ``next_incarnation()``).  ``argv``
    is the writer role's command line; ``env`` extends its
    environment.  ``store_root`` (optional) lets the standby log the
    persistence resume point it is handing the new writer.
    """

    def __init__(
        self,
        writer_host: str = "127.0.0.1",
        writer_port: int | None = None,
        *,
        endpoints: list[tuple[str, int]] | None = None,
        argv: list[str] | None = None,
        env: dict[str, str] | None = None,
        store_root: str | None = None,
        position_path: str | None = None,
        on_takeover: Callable[["StandbyWriter"], None] | None = None,
        grace_s: float | None = None,
        poll_s: float = 0.2,
    ):
        if endpoints is None:
            if writer_port is None:
                raise ValueError(
                    "StandbyWriter needs writer_port or endpoints"
                )
            endpoints = [(writer_host, int(writer_port))]
        self.endpoints = endpoints
        self.argv = list(argv) if argv else None
        self.env = dict(env or {})
        self.store_root = store_root
        self.position_path = position_path
        self.on_takeover = on_takeover
        self.grace_s = grace_env() if grace_s is None else float(grace_s)
        self.poll_s = poll_s
        self.applied_tick = -1
        self.seen_incarnation = int(
            os.environ.get("PATHWAY_MESH_INCARNATION", "0") or 0
        )
        self.took_over = False
        self.takeover_count = 0
        self.takeover_incarnation: int | None = None
        self._position_written_at = -1.0e9
        self.events: list[tuple[float, str, str]] = []
        self._restore_position()
        self._closed = False
        self._failure = threading.Event()
        self._took_over_ev = threading.Event()
        self._lock = threading.Lock()
        self._client: DeltaStreamClient | None = None
        self._monitor: threading.Thread | None = None
        self._sup: Any = None  # GroupSupervisor after a spawn takeover
        self._sup_thread: threading.Thread | None = None

    # --- position persistence ---------------------------------------------

    def _restore_position(self) -> None:
        if not self.position_path or not os.path.exists(self.position_path):
            return
        try:
            with open(self.position_path) as f:
                pos = json.load(f)
            self.applied_tick = int(pos.get("applied_tick", -1))
            self.seen_incarnation = max(
                self.seen_incarnation, int(pos.get("incarnation", 0))
            )
        except (OSError, ValueError):
            pass  # a torn position file only costs a deeper resubscribe

    def _persist_position(self, force: bool = False) -> None:
        """Throttled (0.5 s) atomic write: the position's only consumer
        is a restarted standby, which tolerates a stale value (it just
        resubscribes a little deeper) — a write per applied tick would
        be pure filesystem churn on the shadow's apply path.  Takeover
        forces the write (the fenced incarnation must be durable)."""
        if not self.position_path:
            return
        now = time.monotonic()
        if not force and now - self._position_written_at < 0.5:
            return
        self._position_written_at = now
        tmp = self.position_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(
                    {
                        "applied_tick": self.applied_tick,
                        "incarnation": self.seen_incarnation,
                    },
                    f,
                )
            os.replace(tmp, self.position_path)
        except OSError:
            pass

    def next_incarnation(self) -> int:
        """The incarnation the takeover writer must publish under: one
        past everything this standby (or its persisted position) has
        seen, so the PWRP2 fencing token outranks any zombie.  Stable
        once a takeover is in flight — the on_takeover callback may
        call it again."""
        if self.takeover_incarnation is not None:
            return self.takeover_incarnation
        return self.seen_incarnation + 1

    # --- lifecycle ---------------------------------------------------------

    def _event(self, kind: str, detail: str) -> None:
        self.events.append((time.monotonic(), kind, detail))
        # mirror into the Fleet Lens incident journal: standby-start /
        # failure-notified / takeover are exactly the records peers use
        # to reconstruct a SIGKILLed primary's death (persist=True — a
        # takeover record must survive the standby dying right after)
        from pathway_tpu.observability.journal import record as journal_record

        journal_record(
            f"standby-{kind}" if not kind.startswith("standby") else kind,
            detail,
            tick=self.applied_tick if self.applied_tick >= 0 else None,
            incarnation=self.seen_incarnation,
            persist=kind in ("takeover", "failure-notified"),
        )

    def start(self) -> "StandbyWriter":
        self._client = DeltaStreamClient(
            self.endpoints[0][0],
            self.endpoints[0][1],
            STANDBY_ID,
            from_tick=self.applied_tick,
            on_deltas=self._on_deltas,
            endpoints=self.endpoints,
            connect_timeout=3600.0,
        )
        self._client.start()
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True, name="pw-standby"
        )
        self._monitor.start()
        self._event("standby-start", f"shadowing {self.endpoints[0]}")
        return self

    def stop(self) -> None:
        self._closed = True
        self._failure.set()
        self._persist_position(force=True)  # flush the throttle
        if self._client is not None:
            self._client.close()
        if self._sup is not None:
            self._sup.stop()
        if self._sup_thread is not None:
            self._sup_thread.join(timeout=30)

    def _on_deltas(self, tick: int, batches: list) -> None:
        self.applied_tick = tick
        c = self._client
        if c is not None:
            self.seen_incarnation = max(
                self.seen_incarnation, c.writer_incarnation
            )
        self._persist_position()

    def notify_failure(self, peer: Any = None, reason: str = "") -> None:
        """External death signal (mesh failure listener / router
        ejection callback): skip the disconnect grace window and take
        over now."""
        if not self._closed:
            self._event("failure-notified", f"{peer}: {reason}")
            self._failure.set()

    # --- death detection + takeover ----------------------------------------

    def _monitor_loop(self) -> None:
        # the grace clock only runs AFTER the primary has been reached
        # at least once this life (or a persisted position proves a
        # past life): a standby booted before/alongside its primary
        # must not usurp a merely slow boot — the bumped incarnation
        # would fence the legitimate writer forever.  An explicit
        # notify_failure() (mesh/router listener) bypasses the gate.
        ever_connected = self.applied_tick >= 0
        disconnected_since: float | None = None
        while not self._closed:
            if self._failure.wait(self.poll_s):
                if self._closed:
                    return
                self._takeover("external failure notification")
                return
            c = self._client
            if c is None:
                continue
            if c.connected:
                ever_connected = True
                disconnected_since = None
                self.seen_incarnation = max(
                    self.seen_incarnation, c.writer_incarnation
                )
                continue
            if not ever_connected:
                continue
            now = time.monotonic()
            if disconnected_since is None:
                disconnected_since = now
            elif now - disconnected_since >= self.grace_s:
                self._takeover(
                    f"primary unreachable for {now - disconnected_since:.1f}s"
                )
                return

    def _takeover(self, reason: str) -> None:
        with self._lock:
            if self.took_over or self._closed:
                return
            self.took_over = True
        from pathway_tpu.observability.tracing import get_tracer

        with get_tracer().span(
            "standby.takeover", root=True, reason=reason
        ):
            self._takeover_locked(reason)

    def _takeover_locked(self, reason: str) -> None:
        self.takeover_count += 1
        inc = self.seen_incarnation + 1
        self.takeover_incarnation = inc
        self.seen_incarnation = inc
        self._persist_position(force=True)
        if self._client is not None:
            self._client.close()
        detail = f"{reason}; resuming as incarnation {inc}"
        if self.store_root is not None:
            try:
                from pathway_tpu.persistence._runtime_glue import resume_point
                from pathway_tpu.persistence.backends import FilesystemStore

                rp = resume_point(FilesystemStore(self.store_root))
                detail += (
                    f"; store resume point: generation time "
                    f"{rp['state_time']}, group-commit barrier "
                    f"{rp['group_commit_time']}, log tail to "
                    f"{rp['last_time']}"
                )
            except Exception as exc:
                detail += f"; resume-point read failed: {exc}"
        self._event("takeover", detail)
        import logging

        logging.getLogger("pathway_tpu").warning(
            "standby writer: taking over (%s)", detail
        )
        if self.on_takeover is not None:
            self.on_takeover(self)
        elif self.argv:
            self._spawn_writer(inc)
        self._took_over_ev.set()

    def _spawn_writer(self, incarnation: int) -> None:
        """Default takeover: respawn the writer role under a 1-rank
        Phoenix Mesh supervisor starting at the fenced incarnation.  The
        writer's own boot restores the newest committed generation,
        replays the connector log from the group-commit barrier, floors
        the delta ring, and resumes publishing."""
        from pathway_tpu.parallel.supervisor import GroupSupervisor

        env = dict(self.env)
        self._sup = GroupSupervisor(
            self.argv,
            1,
            env=env,
            initial_incarnation=incarnation,
        )
        self._sup_thread = threading.Thread(
            target=self._sup.run, daemon=True, name="pw-standby-writer"
        )
        self._sup_thread.start()

    def wait_takeover(self, timeout: float | None = None) -> bool:
        return self._took_over_ev.wait(timeout)


def main(argv: list[str] | None = None) -> int:
    import argparse
    import signal
    import sys

    parser = argparse.ArgumentParser(
        prog="python -m pathway_tpu.parallel.standby",
        description="shadow a replication writer's delta stream and "
        "respawn the writer role (the trailing argv) on primary death",
    )
    parser.add_argument(
        "--writer-host",
        default=os.environ.get("PATHWAY_REPL_WRITER_HOST", "127.0.0.1"),
    )
    parser.add_argument(
        "--writer-port",
        type=int,
        default=int(os.environ.get("PATHWAY_REPL_PORT", "0") or 0),
    )
    parser.add_argument(
        "--store-root",
        default=os.environ.get("PATHWAY_REPLICA_STORE") or None,
    )
    parser.add_argument("--position-file", default=None)
    args, extra = parser.parse_known_args(argv)
    if extra and extra[0] == "--":
        extra = extra[1:]
    if not args.writer_port:
        print("no writer port (set PATHWAY_REPL_PORT)", file=sys.stderr)
        return 2
    if not extra:
        print("nothing to take over with (pass -- <writer argv>)",
              file=sys.stderr)
        return 2
    standby = StandbyWriter(
        args.writer_host,
        args.writer_port,
        argv=extra,
        store_root=args.store_root,
        position_path=args.position_file,
    ).start()
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_a: stop.set())
    signal.signal(signal.SIGINT, lambda *_a: stop.set())
    print("STANDBY-READY", flush=True)
    while not stop.is_set():
        if standby.took_over and standby._sup_thread is not None:
            # after a takeover the standby process IS the writer's
            # supervisor: stay alive for its lifetime
            stop.wait(0.5)
        else:
            stop.wait(0.2)
    standby.stop()
    for ts, kind, detail in standby.events:
        print(f"[standby] {kind}: {detail}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
