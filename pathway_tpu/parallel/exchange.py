"""Ragged all-to-all row exchange over the mesh — the engine's Exchange pact.

The reference routes every record to `key.shard() % n_workers` through timely
exchange channels (reference: src/engine/dataflow/operators.rs:128,432 and
the TCP comm backend, SURVEY §5.8). The TPU-native equivalent is a true
`lax.all_to_all` over ICI: each shard scatters its rows into per-destination
send buckets and one collective rotates the buckets so every shard ends up
holding exactly the rows destined to it.

Unlike an all-gather+mask (round-1 placeholder), per-device memory and ICI
traffic are O(n_shards × bucket_capacity) — proportional to what the shard
actually receives, not to the global table.

Rows are arbitrary typed columns; they travel as exact int32 bit-patterns
(`pack_columns`/`unpack_columns`), so f64/i64/u64 survive bit-for-bit.
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import numpy as np

_WORDS = {  # np dtype kind/itemsize -> number of int32 words
    ("f", 8): 2,
    ("i", 8): 2,
    ("u", 8): 2,
    ("f", 4): 1,
    ("i", 4): 1,
    ("u", 4): 1,
    ("b", 1): 1,
}


def packable(arr: np.ndarray) -> bool:
    return arr.ndim == 1 and (arr.dtype.kind, arr.dtype.itemsize) in _WORDS


def pack_columns(
    arrays: Sequence[np.ndarray],
) -> tuple[np.ndarray, list[np.dtype]]:
    """Bit-cast typed columns into one [N, W] int32 word matrix (exact)."""
    n = len(arrays[0])
    spec = [a.dtype for a in arrays]
    words = []
    for a in arrays:
        w = _WORDS[(a.dtype.kind, a.dtype.itemsize)]
        if a.dtype.kind == "b":
            col = a.astype(np.int32).reshape(n, 1)
        else:
            col = (
                np.ascontiguousarray(a)
                .view(np.int32)
                .reshape(n, w)
            )
        words.append(col)
    return np.concatenate(words, axis=1) if words else np.zeros(
        (n, 0), np.int32
    ), spec


def unpack_columns(
    matrix: np.ndarray, spec: Sequence[np.dtype]
) -> list[np.ndarray]:
    """Inverse of pack_columns."""
    out = []
    ofs = 0
    for dt in spec:
        w = _WORDS[(dt.kind, dt.itemsize)]
        chunk = np.ascontiguousarray(matrix[:, ofs : ofs + w])
        if dt.kind == "b":
            out.append(chunk.reshape(-1).astype(bool))
        else:
            out.append(chunk.view(dt).reshape(-1))
        ofs += w
    return out


def _next_pow2(x: int) -> int:
    p = 1
    while p < x:
        p *= 2
    return p


def _impl(n_shards: int, capacity: int, mesh: Any, axis: str):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from pathway_tpu.parallel.collectives import _shard_map_compat

    shard_map, check_kw = _shard_map_compat()

    def local(words, dst):
        # words: [per, W] i32; dst: [per] i32 (-1 = padding row)
        per, width = words.shape
        dstc = jnp.where(dst >= 0, dst, n_shards)  # padding sorts last
        order = jnp.argsort(dstc)  # stable
        swords = words[order]
        sdst = dstc[order]
        counts = jnp.bincount(sdst, length=n_shards + 1)
        starts = jnp.concatenate(
            [jnp.zeros(1, counts.dtype), jnp.cumsum(counts)[:-1]]
        )
        rank = jnp.arange(per) - starts[sdst]
        ok = (sdst < n_shards) & (rank < capacity)
        slot = jnp.where(ok, sdst * capacity + rank, n_shards * capacity)
        # scatter rows + a validity word into the send buffer (last slot is
        # the overflow/padding dump, sliced off before the collective)
        buf = jnp.zeros((n_shards * capacity + 1, width + 1), jnp.int32)
        payload = jnp.concatenate(
            [swords, ok.astype(jnp.int32)[:, None]], axis=1
        )
        buf = buf.at[slot].set(payload, mode="drop")
        send = buf[:-1].reshape(n_shards, capacity, width + 1)
        recv = jax.lax.all_to_all(
            send, axis, split_axis=0, concat_axis=0, tiled=False
        )
        return recv.reshape(n_shards * capacity, width + 1)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis, None), P(axis)),
        out_specs=P(axis, None),
        **check_kw,
    )


@functools.lru_cache(maxsize=64)
def _jitted_impl(n_shards: int, capacity: int, mesh: Any, axis: str):
    import jax

    return jax.jit(_impl(n_shards, capacity, mesh, axis))


def ragged_all_to_all(
    words: np.ndarray,  # [N, W] int32 packed rows
    dest: np.ndarray,  # [N] int32 destination shard in [0, n_shards)
    mesh: Any,
    axis: str = "data",
    capacity: int | None = None,
) -> list[np.ndarray]:
    """Exchange rows to their destination shards through one device
    all-to-all. Returns, per destination shard, the [n_s, W] int32 word
    matrix of rows it received (order: by source shard, then source order).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_shards = mesh.shape[axis]
    n, width = words.shape
    per = _next_pow2(max(1, -(-n // n_shards)))
    total = per * n_shards
    if total > n:
        words = np.concatenate(
            [words, np.zeros((total - n, width), np.int32)]
        )
        dest = np.concatenate(
            [dest, np.full(total - n, -1, np.int32)]
        )
    src = np.arange(total) // per
    cnt = np.zeros((n_shards, n_shards), np.int64)
    valid = dest >= 0
    np.add.at(cnt, (src[valid], dest[valid]), 1)
    need = int(cnt.max())
    if capacity is None:
        capacity = _next_pow2(max(8, need))
    elif capacity < need:
        raise ValueError(
            f"capacity={capacity} would drop rows: a source shard sends "
            f"{need} rows to one destination"
        )
    capacity = min(capacity, per)

    dw = jax.device_put(
        jax.numpy.asarray(words), NamedSharding(mesh, P(axis, None))
    )
    dd = jax.device_put(
        jax.numpy.asarray(dest.astype(np.int32)),
        NamedSharding(mesh, P(axis)),
    )
    fn = _jitted_impl(n_shards, int(capacity), mesh, axis)
    out = np.asarray(fn(dw, dd))  # [n_shards * n_shards*capacity, W+1]
    out = out.reshape(n_shards, n_shards * capacity, width + 1)
    result = []
    for s in range(n_shards):
        block = out[s]
        rows = block[block[:, -1] == 1]
        result.append(np.ascontiguousarray(rows[:, :-1]))
    return result


def exchange_rows(
    arrays: Sequence[np.ndarray],
    dest: np.ndarray,
    mesh: Any,
    axis: str = "data",
) -> list[list[np.ndarray]]:
    """High-level Exchange: route typed columns to destination shards.
    Returns per-shard lists of typed column arrays (exact bit patterns)."""
    words, spec = pack_columns(list(arrays))
    blocks = ragged_all_to_all(
        words, dest.astype(np.int32), mesh, axis
    )
    return [unpack_columns(b, spec) for b in blocks]
