from pathway_tpu.parallel.mesh import (
    get_mesh,
    make_mesh,
    set_default_mesh,
)
from pathway_tpu.parallel.collectives import (
    exchange_by_shard,
    replicated,
    sharded_rows,
)

__all__ = [
    "make_mesh",
    "get_mesh",
    "set_default_mesh",
    "exchange_by_shard",
    "sharded_rows",
    "replicated",
]
