from pathway_tpu.parallel.mesh import (
    get_mesh,
    make_mesh,
    set_default_mesh,
)
from pathway_tpu.parallel.collectives import (
    exchange_by_shard,
    replicated,
    sharded_rows,
)

def exchange_topology() -> dict:
    """Static facts about the exchange fabric this process would execute
    with: engine key-shards over the device mesh (ICI rung) and peer
    processes on the host mesh (DCN rung). Consumed by the Graph Doctor's
    graph-stats rule; cheap enough to call at graph-build time."""
    from pathway_tpu.parallel.host_exchange import dcn_active, process_env
    from pathway_tpu.parallel.mesh import get_engine_mesh

    n_procs, _pid, _port, _host = process_env()
    em = get_engine_mesh()
    shards = em[0].shape[em[1]] if em is not None else 1
    dcn = n_procs if dcn_active() else 1
    return {
        "engine_shards": shards,
        "dcn_processes": dcn,
        "sharding_active": shards > 1 or dcn > 1,
    }


__all__ = [
    "make_mesh",
    "get_mesh",
    "set_default_mesh",
    "exchange_by_shard",
    "sharded_rows",
    "replicated",
    "exchange_topology",
]
