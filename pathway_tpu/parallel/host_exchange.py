"""Cross-process host-row exchange — the DCN rung of the comm backend.

The reference moves *all* dataflow records between worker processes over
timely's TCP mesh (reference:
external/timely-dataflow/communication/src/networking.rs:16-33 — one
framed socket per process pair, handshake magic + peer id;
src/engine/dataflow/config.rs:88-121 — PATHWAY_PROCESSES/PROCESS_ID/
FIRST_PORT env contract). The TPU-native split keeps *device* data on XLA
collectives (ICI) and gives *host* keyed rows this mesh: every process
pair holds a framed TCP connection, DiffBatch partitions travel as
typed columnar frames (parallel/wire.py — delta-varint keys, packed
diffs, raw/optionally-quantized value columns; PATHWAY_DCN_WIRE=pickle
restores the PWHX5 whole-frame pickle), and a value-exchange barrier
doubles as the lockstep tick scheduler (the frontier consensus of
timely's progress tracking).

Overlap: ``send`` enqueues the frame onto a bounded per-peer outbox
drained by one sender thread per peer, which does the encode + MAC +
``sendall`` off the caller's thread — so encoding and TCP of one
channel's partitions overlap the next channel's partition/compute, and
the old serialize-under-lock critical section is gone. Per-peer frame
order (and therefore the MAC sequence) is the enqueue order; a full
outbox back-pressures the producer instead of buffering unboundedly.
A send failure fail-stops exactly like a dead reader: the peer is
marked dead and the next gather/barrier raises HostMeshError.

Failure model (Phoenix Mesh): peer death is DETECTED, not merely
stumbled into. Every connection carries periodic heartbeat frames
(PATHWAY_MESH_HEARTBEAT_MS); a liveness monitor marks a peer dead when
nothing — data, barrier or heartbeat — has arrived within
PATHWAY_MESH_LIVENESS_TIMEOUT_MS (socket EOF and send failures mark it
dead immediately). Registered ``FailureListener`` callbacks fire the
moment a peer is declared dead, so the runtime (and the serving
degradation controller) learn about the failure instead of discovering
it inside a gather; any pending gather/barrier then raises
HostMeshError naming the dead peer and the recorded cause. The process
exits nonzero, and the group supervisor (parallel/supervisor.py)
restarts the WHOLE group, which restores the latest group-committed
snapshot generation (persistence/_runtime_glue.py) — the reference's
recovery model (whole-cluster restart from the persisted frontier,
src/persistence/state.rs:291), now with bounded detection latency and a
bounded restart budget (PATHWAY_MESH_MAX_RESTARTS).

Authentication: frames carry pickled payloads, which execute code on
load, so the mesh authenticates under a per-job shared secret
(PATHWAY_DCN_SECRET — the CLI `spawn` generates one per job; manual
launches must export it on every process). The hello is a
challenge-response (acceptor sends a random nonce, dialer answers with
an HMAC over it — a captured hello cannot be replayed to frame a peer
as dead; the acceptor then returns an authenticated OK so a secret
mismatch fails fast at dial time), and every frame MAC covers
(src, dst, sequence number, body),
so frames cannot be forged, reflected to a different peer, or replayed
out of order. Unauthenticated bytes are dropped before they ever reach
pickle.loads. The reference's timely mesh is unauthenticated but
deserializes data-only bincode; pickle needs the stronger gate.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import queue
import socket
import struct
import threading
import time
from typing import Any

from pathway_tpu.observability.tracing import (
    get_tracer,
    pending_traceparent,
    propagation_traceparent,
)
from pathway_tpu.parallel import wire

_HELLO_MAGIC = b"PWHX7"  # protocol version tag (networking.rs handshake
# analog); v7 adds per-peer heartbeat control frames ("hb") and the
# failure-listener liveness contract (Phoenix Mesh) — a v6 peer would
# treat heartbeats as unknown frames, so the version bump fails fast via
# the established PWVN reject; v6 switched frame bodies to the tagged
# columnar wire codec (parallel/wire.py — a leading 'C'/'P' byte
# self-describes each frame, so codec and pickle frames interoperate
# inside one connection); v5 appended the W3C traceparent slot that
# stitches traces across processes (Trace Weaver)
_MAC_LEN = 32  # HMAC-SHA256
_NONCE_LEN = 32
_OK_TAG = b"PWOK"  # acceptor's authenticated handshake acknowledgment
# explicit (necessarily unauthenticated — we don't share a key with a
# mismatched dialer) rejection sentinel: lets the dialer fail fast with
# an auth diagnosis instead of retrying a close it can't interpret. A
# forged reject is at worst a startup DoS an on-path attacker could
# already cause with a TCP reset.
_REJECT = b"PWNO" + b"\x00" * (_MAC_LEN - 4)
# version-mismatch sentinel: a peer that shares the PWHX prefix but
# speaks another protocol version gets told so explicitly — the dialer
# fails fast with a clear diagnosis instead of retrying a silent close
# until the connect deadline. Carries the acceptor's magic so the error
# can name both versions. Same threat model note as _REJECT: forging it
# is at worst a startup DoS an on-path attacker already has via RST.
_VREJECT_TAG = b"PWVN"


def _version_reject() -> bytes:
    out = _VREJECT_TAG + _HELLO_MAGIC
    return out + b"\x00" * (_MAC_LEN - len(out))


def _frame_mac(key: bytes, src: int, dst: int, seq: int, body: bytes) -> bytes:
    ctx = struct.pack("<iiQ", src, dst, seq)
    return hmac.new(key, ctx + body, "sha256").digest()


def _job_key() -> bytes:
    secret = os.environ.get("PATHWAY_DCN_SECRET", "")
    if not secret:
        raise HostMeshError(
            "PATHWAY_DCN_SECRET is not set. The host mesh moves pickled "
            "frames between processes and refuses to run unauthenticated; "
            "launch the job with `pathway-tpu spawn` (which generates a "
            "per-job secret) or export the same random PATHWAY_DCN_SECRET "
            "on every process."
        )
    return hashlib.sha256(("pathway-dcn:" + secret).encode()).digest()


class HostMeshError(RuntimeError):
    pass


def process_env() -> tuple[int, int, int, str]:
    """(n_processes, process_id, base_port, host) from the reference env
    contract."""
    n = int(os.environ.get("PATHWAY_PROCESSES", "1") or 1)
    pid = int(os.environ.get("PATHWAY_PROCESS_ID", "0") or 0)
    port = int(os.environ.get("PATHWAY_DCN_PORT", "10700") or 10700)
    host = os.environ.get("PATHWAY_DCN_HOST", "127.0.0.1")
    return n, pid, port, host


class HostMesh:
    """Full TCP mesh between N engine processes.

    Each process listens on base_port+pid and dials every peer; the dialing
    side sends a hello frame carrying its process id, so each ordered pair
    (src -> dst) has exactly one connection used for src's sends. Frames
    are length-prefixed tagged bodies (parallel/wire.py — columnar codec
    for DiffBatch payloads, pickle for everything else) logically shaped:

      ("data", src, channel, tick, payload, tp)  — DiffBatch partitions
      ("bar",  src, round, value, tp)            — barrier value exchange

    Sends are asynchronous: ``send``/``barrier`` enqueue onto the
    destination's bounded outbox and the per-peer sender thread performs
    encode + MAC + sendall, so wire work overlaps the caller's compute.
    The payload's ownership transfers to the mesh at enqueue — callers
    must not mutate a sent batch.

    `tp` is the sender's W3C traceparent (or None): cross-host context
    propagation for the Trace Weaver. ``barrier()`` records the group's
    traceparents in ``last_barrier_tps`` — the lockstep tick scheduler
    reads it to agree on ONE tick trace group-wide (engine/runtime.py);
    ``gather()`` records them in ``last_gather_tps`` for the DCN
    exchange spans.
    """

    def __init__(
        self,
        n: int,
        pid: int,
        base_port: int,
        host: str = "127.0.0.1",
        connect_timeout: float = 60.0,
    ):
        self.n = n
        self.pid = pid
        self.base_port = base_port
        self.host = host
        self._key = _job_key()
        # wire-format knobs, resolved once per mesh: PATHWAY_DCN_WIRE
        # picks the data-frame encoding (codec|pickle), PATHWAY_DCN_QUANT
        # opts value columns into the lossy tier (bf16|int8; keys, diffs
        # and non-float columns stay lossless regardless — wire.py never
        # quantizes them)
        self.wire_format = os.environ.get("PATHWAY_DCN_WIRE", "codec")
        if self.wire_format not in ("codec", "pickle"):
            raise HostMeshError(
                f"PATHWAY_DCN_WIRE={self.wire_format!r}: expected "
                "'codec' or 'pickle'"
            )
        self.wire_quant = os.environ.get("PATHWAY_DCN_QUANT", "") or None
        if self.wire_quant not in (None, "bf16", "int8"):
            raise HostMeshError(
                f"PATHWAY_DCN_QUANT={self.wire_quant!r}: expected "
                "'bf16', 'int8', or unset (lossless)"
            )
        # Flight Recorder: DCN traffic accounting. Peer cardinality is the
        # process-group size (small); every process also exposes its own
        # id via the `process` label on pathway_build_info-adjacent scrape
        # configs, so multi-host dashboards aggregate by (job, process).
        from pathway_tpu.observability import REGISTRY

        self._m_sent_bytes = REGISTRY.counter(
            "pathway_host_exchange_sent_bytes_total",
            "framed bytes sent over the host mesh, by destination peer",
            labelnames=("peer",),
        )
        self._m_sent_msgs = REGISTRY.counter(
            "pathway_host_exchange_sent_messages_total",
            "frames sent over the host mesh, by destination peer",
            labelnames=("peer",),
        )
        self._m_recv_bytes = REGISTRY.counter(
            "pathway_host_exchange_recv_bytes_total",
            "framed bytes received over the host mesh, by source peer",
            labelnames=("peer",),
        )
        self._m_recv_msgs = REGISTRY.counter(
            "pathway_host_exchange_recv_messages_total",
            "frames received over the host mesh, by source peer",
            labelnames=("peer",),
        )
        self._m_barrier_seconds = REGISTRY.histogram(
            "pathway_host_exchange_barrier_seconds",
            "barrier round-trip: send-to-all until all peers' values "
            "arrive (the lockstep tick scheduler's cadence)",
        )
        self._m_gather_seconds = REGISTRY.histogram(
            "pathway_host_exchange_gather_seconds",
            "wait for one payload from every peer on a data channel",
        )
        self._m_encode_seconds = REGISTRY.histogram(
            "pathway_host_exchange_encode_seconds",
            "wire-encode time per frame, by the format actually used "
            "(barrier/scalar frames ride the pickle path even under "
            "PATHWAY_DCN_WIRE=codec)",
            labelnames=("wire",),
        )
        self._m_decode_seconds = REGISTRY.histogram(
            "pathway_host_exchange_decode_seconds",
            "wire-decode time per received frame, by format",
            labelnames=("wire",),
        )
        self._m_ratio = REGISTRY.gauge(
            "pathway_host_exchange_compression_ratio",
            "dense in-memory bytes / wire bytes of the most recent codec "
            "data frame, by exchange channel",
            labelnames=("channel",),
        )
        self._m_outbox_depth = REGISTRY.gauge(
            "pathway_host_exchange_outbox_depth",
            "frames waiting on the per-peer sender outbox (bounded by "
            "PATHWAY_DCN_OUTBOX; a full outbox back-pressures the tick)",
            labelnames=("peer",),
        )
        self._cv = threading.Condition()
        # (channel, tick) -> {src: payload}
        self._data: dict[tuple[str, int], dict[int, Any]] = {}
        # round -> {src: value}
        self._bars: dict[int, dict[int, Any]] = {}
        # received traceparents, keyed like their payloads; gather moves
        # its key's entry into _gathered_tps for take_gather_tps (keyed,
        # not a shared "last" slot — concurrent gathers on different
        # channels must not clobber each other's remote traces)
        self._data_tps: dict[tuple[str, int], dict[int, str]] = {}
        self._bar_tps: dict[int, dict[int, str]] = {}
        self._gathered_tps: dict[tuple[str, int], dict[int, str]] = {}
        # {pid: traceparent|None} of the last completed barrier (barriers
        # are lockstep on one thread, so a single slot is race-free)
        self.last_barrier_tps: dict[int, str | None] = {}
        self._round = 0
        self._dead: set[int] = set()
        # peer pid -> human-readable cause recorded when the peer was
        # declared dead (EOF, send failure, liveness timeout) — surfaced
        # in every subsequent HostMeshError so the supervisor log names
        # the root cause, not just the gather that tripped over it
        self._dead_reason: dict[int, str] = {}
        # Phoenix Mesh: failure listeners fire (peer, reason) the moment
        # a peer is declared dead — the runtime and the serving
        # degradation controller subscribe so recovery starts at
        # detection time, not at the next gather
        self._failure_listeners: list = []
        # liveness: last monotonic instant ANY frame (data/bar/hb)
        # arrived from each peer; heartbeats keep this fresh on idle
        # connections so the monitor can tell "slow tick" from "dead or
        # wedged peer"
        _hb_ms = float(
            os.environ.get("PATHWAY_MESH_HEARTBEAT_MS", "1000") or 1000
        )
        # floor at 50 ms: a zero/tiny interval would busy-spin the
        # heartbeat thread and flood every outbox (disable monitoring
        # with PATHWAY_MESH_LIVENESS_TIMEOUT_MS=0, not by zeroing the
        # send interval)
        self.heartbeat_s = max(_hb_ms, 50.0) / 1000.0
        # generous default: a long GIL-holding jit compile on the peer
        # can starve its heartbeat thread for seconds — the timeout must
        # catch dead/wedged peers, not slow ones (0 disables monitoring;
        # socket EOF and send failures still detect clean deaths fast)
        self.liveness_timeout_s = (
            float(
                os.environ.get("PATHWAY_MESH_LIVENESS_TIMEOUT_MS", "30000")
                or 30000
            )
            / 1000.0
        )
        self._last_heard: dict[int, float] = {}
        # peer pid -> its PWHX magic, recorded when a peer running a
        # DIFFERENT protocol version dials us with a valid job-secret
        # MAC (a genuinely old build cannot understand our PWVN reject,
        # but its authenticated hello proves the skew — our own dial
        # loop for that peer aborts with the version diagnosis instead
        # of retrying into the connect deadline)
        self._version_skew: dict[int, bytes] = {}
        self._out: dict[int, socket.socket] = {}
        self._closed = False
        # receive-side decode pool (wide fan-in long tail): each peer
        # already has its own reader thread, but that thread decodes a
        # frame before it can recv the NEXT one — on wide fan-ins the
        # per-link decode serializes behind the gather wait.  A small
        # shared pool takes (MAC-verified) bodies off the readers so
        # recv and decode overlap across peers.  Safe to run unordered:
        # every delivery slot is keyed (channel, tick, src) and written
        # once.  PATHWAY_DCN_DECODE_POOL: "" = auto (pool of
        # min(4, n-1) threads once the fan-in is ≥ 3 peers), 0 = inline
        # decode (the pre-pool behavior), N = N threads.
        pool_raw = os.environ.get("PATHWAY_DCN_DECODE_POOL", "") or ""
        if pool_raw:
            try:
                pool_n = int(pool_raw)
            except ValueError:
                raise HostMeshError(
                    f"PATHWAY_DCN_DECODE_POOL={pool_raw!r} is not an int"
                ) from None
        else:
            pool_n = min(4, n - 1) if n - 1 >= 3 else 0
        self._decode_pool = None
        self._decode_slots: threading.Semaphore | None = None
        if pool_n > 0:
            from concurrent.futures import ThreadPoolExecutor

            self._decode_pool = ThreadPoolExecutor(
                max_workers=pool_n,
                thread_name_prefix=f"pw-dcn-decode-{pid}",
            )
            # BOUNDED pending-decode backlog: the inline path's natural
            # backpressure (reader busy decoding → kernel socket buffer
            # fills → sender blocks) must survive the pool, or
            # undecoded bodies accumulate without limit under a gather
            # storm — readers block here instead, like every other
            # bounded queue in this subsystem
            self._decode_slots = threading.Semaphore(pool_n * 8)
        # per-peer overlapped delivery: bounded outbox + one sender
        # thread per peer (owns that connection's MAC sequence counter)
        depth = int(os.environ.get("PATHWAY_DCN_OUTBOX", "32") or 32)
        self._outbox: dict[int, queue.Queue] = {}
        self._senders: dict[int, threading.Thread] = {}
        self._send_failed: dict[int, BaseException] = {}

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, base_port + pid))
        self._listener.listen(n)
        threading.Thread(target=self._accept_loop, daemon=True).start()

        deadline = time.monotonic() + connect_timeout
        for peer in range(n):
            if peer == pid:
                continue
            self._out[peer] = self._dial(peer, deadline)
            q: queue.Queue = queue.Queue(maxsize=max(1, depth))
            self._outbox[peer] = q
            self._m_outbox_depth.labels(str(peer)).set_function(q.qsize)
            th = threading.Thread(
                target=self._sender_loop,
                args=(peer,),
                daemon=True,
                name=f"pw-dcn-send-{pid}to{peer}",
            )
            self._senders[peer] = th
            th.start()
        # the liveness clock starts once the full mesh is dialed — a
        # peer that was reachable at startup but never speaks again is
        # exactly what the monitor exists to catch
        now = time.monotonic()
        for peer in range(n):
            if peer != pid:
                self._last_heard[peer] = now
        # the heartbeat SENDER always runs (peers with monitoring on
        # must keep hearing us even when our own timeout is 0 =
        # monitoring disabled); only the timeout CHECK is conditional
        threading.Thread(
            target=self._heartbeat_loop,
            daemon=True,
            name=f"pw-dcn-heartbeat-{pid}",
        ).start()

    # --- wiring -----------------------------------------------------------

    def _dial(self, peer: int, deadline: float) -> socket.socket:
        """Dial one peer until the MONOTONIC deadline (wall-clock jumps
        must neither expire nor extend connection budgets) with jittered
        exponential backoff between attempts — a whole group restarting
        at once must not hammer a still-booting peer in lockstep."""
        import random as _random

        last_err: Exception | None = None
        attempt = 0
        while time.monotonic() < deadline:
            skew = self._version_skew.get(peer)
            if skew is not None:
                raise HostMeshError(
                    f"process {self.pid}: protocol version mismatch — "
                    f"peer {peer} speaks "
                    f"{skew.decode('ascii', 'replace')}, this process "
                    f"speaks {_HELLO_MAGIC.decode('ascii')} (detected "
                    "from the peer's authenticated hello); run every "
                    "process of the job from the same build"
                )
            s: socket.socket | None = None
            try:
                s = socket.create_connection(
                    (self.host, self.base_port + peer), timeout=5.0
                )
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                s.settimeout(10.0)
                # challenge-response: answer the acceptor's random nonce so
                # a captured hello cannot be replayed. The hello names BOTH
                # endpoints — the acceptor checks dst == its own pid, so a
                # rogue listener cannot relay our answer to a third peer
                # (HMAC-oracle connection forwarding).
                nonce = self._read_exact(s, _NONCE_LEN)
                if nonce is None:
                    raise OSError("peer closed during handshake")
                hello = _HELLO_MAGIC + struct.pack("<ii", self.pid, peer)
                s.sendall(
                    hello + hmac.new(self._key, hello + nonce, "sha256").digest()
                )
                # wait for the acceptor's authenticated OK (MAC over its
                # own nonce + our hello): a PATHWAY_DCN_SECRET mismatch
                # fails HERE with a clear auth error instead of surfacing
                # later as a confusing EPIPE on the first large send
                ok = self._read_exact(s, _MAC_LEN)
                if ok is None:
                    # clean close mid-handshake (peer tearing down, or an
                    # older-protocol acceptor dropping the unknown magic):
                    # a retryable startup race, NOT an auth verdict
                    raise OSError("peer closed during handshake")
                if ok == _REJECT:
                    s.close()
                    raise HostMeshError(
                        f"process {self.pid}: peer {peer} rejected the "
                        "handshake — authentication failed (is "
                        "PATHWAY_DCN_SECRET identical on every process?)"
                    )
                if ok[: len(_VREJECT_TAG)] == _VREJECT_TAG:
                    peer_magic = (
                        ok[len(_VREJECT_TAG) : len(_VREJECT_TAG) + 5]
                        .rstrip(b"\x00")
                        .decode("ascii", "replace")
                    )
                    s.close()
                    raise HostMeshError(
                        f"process {self.pid}: protocol version mismatch "
                        f"— peer {peer} speaks {peer_magic}, this process "
                        f"speaks {_HELLO_MAGIC.decode('ascii')}; run every "
                        "process of the job from the same build"
                    )
                expected = hmac.new(
                    self._key, _OK_TAG + nonce + hello, "sha256"
                ).digest()
                if not hmac.compare_digest(ok, expected):
                    # a garbled (not explicitly rejected) response: treat
                    # like a transport fault and retry within the deadline
                    raise OSError("unexpected handshake response")
                s.settimeout(None)
                return s
            except OSError as e:
                last_err = e
                if s is not None:
                    try:
                        s.close()
                    except OSError:
                        pass
                attempt += 1
                backoff = min(2.0, 0.05 * (2**min(attempt, 6)))
                delay = backoff * (0.5 + _random.random())
                delay = min(delay, max(0.0, deadline - time.monotonic()))
                if delay > 0:
                    time.sleep(delay)
        raise HostMeshError(
            f"process {self.pid}: could not reach peer {peer} at "
            f"{self.host}:{self.base_port + peer} ({last_err})"
        )

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(
                target=self._reader, args=(conn,), daemon=True
            ).start()

    def _read_exact(self, conn: socket.socket, count: int) -> bytes | None:
        buf = b""
        while len(buf) < count:
            chunk = conn.recv(count - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    def _reader(self, conn: socket.socket) -> None:
        src = -1
        dec_codec = self._m_decode_seconds.labels("codec")
        dec_pickle = self._m_decode_seconds.labels("pickle")
        try:
            nonce = os.urandom(_NONCE_LEN)
            conn.settimeout(30.0)  # handshake must complete promptly
            conn.sendall(nonce)
            hello = self._read_exact(conn, len(_HELLO_MAGIC) + 8 + _MAC_LEN)
            if hello is None:
                conn.close()
                return
            magic = hello[: len(_HELLO_MAGIC)]
            if magic != _HELLO_MAGIC:
                if magic[: len(_VREJECT_TAG)] == _HELLO_MAGIC[
                    : len(_VREJECT_TAG)
                ]:
                    # a PWHX peer speaking another protocol version:
                    # tell it explicitly so a PWVN-aware build fails
                    # fast with a version diagnosis instead of retrying
                    # until its deadline
                    try:
                        conn.sendall(_version_reject())
                    except OSError:
                        pass
                    # a genuinely OLD build cannot parse PWVN — but if
                    # its hello authenticates under the job secret (the
                    # MAC scheme predates the version split), that
                    # PROVES a same-job peer on another version: record
                    # the skew so our own dial loop for that peer
                    # aborts with the clear diagnosis. Unauthenticated
                    # probers must not be able to plant skew (that
                    # would let any off-path connect kill the job).
                    claimed, mac = hello[:-_MAC_LEN], hello[-_MAC_LEN:]
                    if hmac.compare_digest(
                        mac,
                        hmac.new(
                            self._key, claimed + nonce, "sha256"
                        ).digest(),
                    ):
                        skew_src, skew_dst = struct.unpack(
                            "<ii", claimed[len(_HELLO_MAGIC) :]
                        )
                        if skew_dst == self.pid and 0 <= skew_src < self.n:
                            self._version_skew[skew_src] = magic
                conn.close()
                return
            claimed, mac = hello[:-_MAC_LEN], hello[-_MAC_LEN:]
            if not hmac.compare_digest(
                mac, hmac.new(self._key, claimed + nonce, "sha256").digest()
            ):
                try:
                    conn.sendall(_REJECT)
                except OSError:
                    pass
                conn.close()
                return
            hello_src, dst = struct.unpack(
                "<ii", hello[len(_HELLO_MAGIC) : -_MAC_LEN]
            )
            if dst != self.pid:
                # answer relayed from a different handshake; close WITHOUT
                # assigning src — the genuine peer must not be framed dead
                conn.close()
                return
            # authenticated OK: proves to the dialer that WE hold the job
            # key too (mutual auth) and that its hello was accepted
            conn.sendall(
                hmac.new(
                    self._key, _OK_TAG + nonce + claimed, "sha256"
                ).digest()
            )
            src = hello_src
            conn.settimeout(None)
            recv_seq = 0
            while True:
                head = self._read_exact(conn, 4 + _MAC_LEN)
                if head is None:
                    break
                (length,) = struct.unpack("<I", head[:4])
                body = self._read_exact(conn, length)
                if body is None:
                    break
                if not hmac.compare_digest(
                    head[4:], _frame_mac(self._key, src, self.pid, recv_seq, body)
                ):
                    break  # forged/reflected/replayed frame: drop the link
                recv_seq += 1
                self._last_heard[src] = time.monotonic()
                self._m_recv_bytes.labels(str(src)).inc(len(head) + len(body))
                self._m_recv_msgs.labels(str(src)).inc()
                pool = self._decode_pool
                if pool is not None:
                    # overlap: the reader goes straight back to recv
                    # while a pool worker decodes + delivers.  Unordered
                    # delivery is safe — every slot is keyed
                    # (channel, tick, src) and written once — and the
                    # MAC sequence was already verified in order above.
                    # The slot acquire bounds the pending backlog
                    # (released by the worker).
                    self._decode_slots.acquire()
                    pool.submit(
                        self._decode_deliver,
                        conn,
                        body,
                        dec_codec,
                        dec_pickle,
                    )
                else:
                    self._decode_deliver(conn, body, dec_codec, dec_pickle)
        except Exception:
            # transport faults AND decode failures (wire.WireError, a
            # struct/pickle error from a codec bug or a version skew
            # the handshake missed) take the same clean fail-stop path:
            # drop the link, mark the peer dead below — never kill the
            # reader thread with an unhandled-exception traceback
            pass
        finally:
            conn.close()
            if src >= 0:
                self._mark_dead(
                    src, "connection closed (peer EOF or corrupt frame)"
                )

    def _decode_deliver(
        self, conn: socket.socket, body: bytes, dec_codec, dec_pickle
    ) -> None:
        """Decode one MAC-verified frame body and deliver it under the
        condition variable.  Runs inline (reader thread) or on the
        decode pool; a decode failure on the pool path closes the link
        so the reader fail-stops exactly like an inline failure."""
        try:
            t0 = time.perf_counter()
            frame = wire.decode_frame(body)
            (
                dec_codec
                if body[:1] == wire.FRAME_CODEC
                else dec_pickle
            ).observe(time.perf_counter() - t0)
            kind = frame[0]
            if kind == "hb":
                return  # liveness already refreshed by the reader
            with self._cv:
                if kind == "data":
                    _k, fsrc, channel, tick, payload, tp = frame
                    self._data.setdefault((channel, tick), {})[
                        fsrc
                    ] = payload
                    if tp is not None:
                        self._data_tps.setdefault(
                            (channel, tick), {}
                        )[fsrc] = tp
                elif kind == "bar":
                    _k, fsrc, rnd, value, tp = frame
                    self._bars.setdefault(rnd, {})[fsrc] = value
                    if tp is not None:
                        self._bar_tps.setdefault(rnd, {})[fsrc] = tp
                self._cv.notify_all()
        except Exception:
            if self._decode_pool is None:
                raise  # inline path: the reader's fail-stop handler
            # pool path: tear the link so the reader fail-stops.
            # shutdown() BEFORE close(): the reader is blocked in
            # recv() on this socket, and a bare close() neither wakes
            # it (the in-flight syscall pins the description) nor is
            # safe against the freed fd being reused by a new accept
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        finally:
            if self._decode_pool is not None:
                self._decode_slots.release()

    # --- liveness (Phoenix Mesh) ------------------------------------------

    def add_failure_listener(self, fn) -> None:
        """Register ``fn(peer: int, reason: str)``, fired once per peer
        the moment it is declared dead (EOF, send failure, or liveness
        timeout). Fired from mesh internal threads — listeners must be
        quick and must not call back into the mesh."""
        with self._cv:
            self._failure_listeners.append(fn)
            already = [
                (p, self._dead_reason.get(p, "unknown")) for p in self._dead
            ]
        # a listener registered after a failure still learns about it
        for peer, reason in already:
            try:
                fn(peer, reason)
            except Exception:
                pass

    def _mark_dead(self, peer: int, reason: str) -> None:
        with self._cv:
            if peer in self._dead:
                return
            self._dead.add(peer)
            self._dead_reason.setdefault(peer, reason)
            # a peer going away while WE are tearing down is the normal
            # end of a clean run, not a failure: keep the dead-set
            # bookkeeping (stray gathers must still error) but skip the
            # alarm and the recovery listeners
            listeners = (
                [] if self._closed else list(self._failure_listeners)
            )
            self._cv.notify_all()
        if self._closed:
            return
        import logging

        logging.getLogger("pathway_tpu").warning(
            "host mesh: process %d declared peer %d dead (%s)",
            self.pid,
            peer,
            reason,
        )
        for fn in listeners:
            try:
                fn(peer, reason)
            except Exception:
                logging.getLogger("pathway_tpu").exception(
                    "host mesh failure listener raised"
                )

    def _heartbeat_loop(self) -> None:
        """Send a heartbeat frame to every live peer each interval and
        declare peers dead when nothing has arrived within the liveness
        timeout. Heartbeats ride the normal outbox (so they share the
        MAC sequence) but never block: a full outbox means data frames
        are flowing, which is liveness enough."""
        while not self._closed:
            time.sleep(self.heartbeat_s)
            if self._closed:
                return
            now = time.monotonic()
            for peer, q in self._outbox.items():
                if peer in self._dead:
                    continue
                try:
                    q.put_nowait(("hb", self.pid))
                except queue.Full:
                    pass
                if self.liveness_timeout_s <= 0:
                    continue  # monitoring disabled; keep sending
                heard = self._last_heard.get(peer)
                if (
                    heard is not None
                    and now - heard > self.liveness_timeout_s
                ):
                    self._mark_dead(
                        peer,
                        f"liveness timeout: no frames for "
                        f"{now - heard:.1f}s "
                        f"(> {self.liveness_timeout_s:.1f}s)",
                    )

    # --- send/recv --------------------------------------------------------

    _STOP = object()  # outbox sentinel: sender thread exits

    def _enqueue_frame(self, dst: int, frame: tuple) -> None:
        """Hand a frame to dst's sender thread. Bounded: a full outbox
        blocks (back-pressure against a slow peer) but keeps polling the
        failure flags so a dead peer cannot wedge the producer."""
        q = self._outbox[dst]
        while True:
            if self._closed:
                raise HostMeshError(
                    f"process {self.pid}: mesh is closed"
                )
            err = self._send_failed.get(dst)
            if err is not None:
                raise HostMeshError(
                    f"process {self.pid}: send to peer {dst} failed "
                    f"({err})"
                )
            try:
                q.put(frame, timeout=0.2)
                return
            except queue.Full:
                continue

    def _sender_loop(self, dst: int) -> None:
        """Drain dst's outbox: encode + MAC + sendall, off the caller's
        thread, so wire work overlaps the next channel's partitioning
        and compute. Owns the connection's MAC sequence counter (frames
        leave in enqueue order, so the receiver's recv_seq matches)."""
        from pathway_tpu.testing import faults

        q = self._outbox[dst]
        sock = self._out[dst]
        seq = 0
        plan = faults.active()
        # bind label children once: the per-frame path pays attribute
        # loads, not registry lock + dict lookups
        enc_codec = self._m_encode_seconds.labels("codec")
        enc_pickle = self._m_encode_seconds.labels("pickle")
        sent_bytes = self._m_sent_bytes.labels(str(dst))
        sent_msgs = self._m_sent_msgs.labels(str(dst))
        while True:
            frame = q.get()
            if frame is self._STOP:
                return
            try:
                repeats = 1
                if plan is not None:
                    kind = frame[0]
                    channel = frame[2] if kind == "data" else kind
                    action = plan.on_wire_send(str(channel))
                    if action is not None:
                        if action[0] == "drop":
                            continue
                        if action[0] == "dup":
                            repeats = 2
                        elif action[0] == "delay":
                            time.sleep(action[1])
                t0 = time.perf_counter()
                body, stats = wire.encode_frame(
                    frame, self.wire_format, self.wire_quant
                )
                (enc_codec if stats is not None else enc_pickle).observe(
                    time.perf_counter() - t0
                )
                if stats is not None and stats["raw_bytes"]:
                    self._m_ratio.labels(frame[2]).set(
                        stats["raw_bytes"] / max(len(body) - 1, 1)
                    )
                if frame[0] == "data":
                    # Tick Scope wire tap: per-channel encoded bytes —
                    # the sender thread is off the tick hot loop, so the
                    # tap's small lock is free concurrency-wise
                    wire.tap_frame(str(frame[2]), len(body), stats)
                for _ in range(repeats):
                    mac = _frame_mac(self._key, self.pid, dst, seq, body)
                    seq += 1
                    msg = struct.pack("<I", len(body)) + mac + body
                    sock.sendall(msg)
                    sent_bytes.inc(len(msg))
                    sent_msgs.inc()
            except Exception as e:  # OSError or an encode bug: fail-stop
                self._send_failed[dst] = e
                self._mark_dead(dst, f"send failed: {e}")
                # unblock producers stuck on the (now doomed) outbox
                try:
                    while True:
                        q.get_nowait()
                except queue.Empty:
                    pass
                return

    def _dead_detail(self, pids) -> str:
        notes = []
        for p in sorted(pids):
            if p in self._send_failed:
                notes.append(f"peer {p} send failed: {self._send_failed[p]}")
            elif p in self._dead_reason:
                notes.append(f"peer {p}: {self._dead_reason[p]}")
        return (" [" + "; ".join(notes) + "]") if notes else ""

    @staticmethod
    def _default_timeout(timeout: float | None) -> float:
        """gather/barrier wait budget: explicit argument, else
        PATHWAY_DCN_TIMEOUT seconds (default 300). Chaos tests shrink it
        so a dropped frame surfaces in seconds, not minutes."""
        if timeout is not None:
            return timeout
        return float(os.environ.get("PATHWAY_DCN_TIMEOUT", "300") or 300)

    def send(self, dst: int, channel: str, tick: int, payload: Any) -> None:
        # disabled tracing must not cost a contextvar read + pending-lock
        # acquisition per frame on the mesh hot path
        tp = propagation_traceparent() if get_tracer().enabled else None
        self._enqueue_frame(
            dst, ("data", self.pid, channel, tick, payload, tp)
        )

    def gather(
        self, channel: str, tick: int, timeout: float | None = None
    ) -> dict[int, Any]:
        """Wait for one payload from every other process on (channel, tick)."""
        want = self.n - 1
        t0 = time.perf_counter()
        deadline = time.monotonic() + self._default_timeout(timeout)
        key = (channel, tick)
        with self._cv:
            while True:
                got = self._data.get(key, {})
                if len(got) >= want:
                    self._m_gather_seconds.observe(
                        time.perf_counter() - t0
                    )
                    tps = self._data_tps.pop(key, None)
                    if tps:
                        self._gathered_tps[key] = tps
                        # bound the stash: a caller that never takes its
                        # entry must not leak memory over a long run
                        while len(self._gathered_tps) > 1024:
                            self._gathered_tps.pop(
                                next(iter(self._gathered_tps))
                            )
                    return self._data.pop(key)
                if self._dead:
                    missing = set(range(self.n)) - {self.pid} - set(got)
                    if missing & self._dead:
                        raise HostMeshError(
                            f"process {self.pid}: peer(s) "
                            f"{sorted(missing & self._dead)} died before "
                            f"delivering {channel}@{tick}"
                            + self._dead_detail(missing & self._dead)
                        )
                left = deadline - time.monotonic()
                if left <= 0:
                    raise HostMeshError(
                        f"process {self.pid}: timeout waiting for "
                        f"{channel}@{tick} (have {sorted(got)})"
                    )
                self._cv.wait(timeout=min(left, 1.0))

    def barrier(
        self, value: Any, timeout: float | None = None
    ) -> dict[int, Any]:
        """Exchange `value` with every process; returns {pid: value} for all
        N processes (including self). Must be called in lockstep — the
        internal round counter is the channel. ``last_barrier_tps`` holds
        every participant's traceparent afterwards (None for processes
        with no active trace).

        Barriers carry the PENDING-request traceparent only, never the
        ambient span: the barrier is the lockstep tick scheduler, and the
        trace the next tick should serve is the oldest in-flight REST
        request. The ambient context on the run-loop thread is the
        whole-run ``pathway.run`` span — adopting it would collapse every
        tick of every process into one giant run-long trace and starve
        request attribution."""
        rnd = self._round
        self._round += 1
        t0 = time.perf_counter()
        own_tp = pending_traceparent() if get_tracer().enabled else None
        for peer in range(self.n):
            if peer != self.pid:
                self._enqueue_frame(
                    peer, ("bar", self.pid, rnd, value, own_tp)
                )
        want = self.n - 1
        deadline = time.monotonic() + self._default_timeout(timeout)
        with self._cv:
            while True:
                got = self._bars.get(rnd, {})
                if len(got) >= want:
                    out = self._bars.pop(rnd)
                    out[self.pid] = value
                    tps = self._bar_tps.pop(rnd, {})
                    tps[self.pid] = own_tp
                    self.last_barrier_tps = tps
                    self._m_barrier_seconds.observe(
                        time.perf_counter() - t0
                    )
                    return out
                if self._dead:
                    missing = set(range(self.n)) - {self.pid} - set(got)
                    if missing & self._dead:
                        raise HostMeshError(
                            f"process {self.pid}: peer(s) "
                            f"{sorted(missing & self._dead)} died at "
                            f"barrier {rnd}"
                            + self._dead_detail(missing & self._dead)
                        )
                left = deadline - time.monotonic()
                if left <= 0:
                    raise HostMeshError(
                        f"process {self.pid}: timeout at barrier {rnd}"
                    )
                self._cv.wait(timeout=min(left, 1.0))

    def take_gather_tps(self, channel: str, tick: int) -> dict[int, str]:
        """Remove and return the remote traceparents that arrived with the
        (channel, tick) payloads of a completed gather. Keyed per gather,
        so concurrent exchanges on different channels stay isolated."""
        with self._cv:
            return self._gathered_tps.pop((channel, tick), {})

    def group_traceparent(self) -> str | None:
        """The group's agreed trace context for the round the last barrier
        scheduled: the lowest-pid non-None traceparent (deterministic —
        every process sees the same set, so every process picks the same
        one and the whole group's tick spans join one trace)."""
        tps = self.last_barrier_tps
        for pid in sorted(tps):
            if tps[pid] is not None:
                return tps[pid]
        return None

    def close(self) -> None:
        self._closed = True
        # FLUSH-then-stop each outbox: the sentinel queues BEHIND any
        # pending frames so the sender delivers them first — a barrier
        # frame still in flight must reach the peer or its next barrier
        # sees a spurious dead-peer EOF. Producers blocked on a full
        # outbox unblock via the closed flag (their next 0.2 s poll
        # raises), freeing a slot; a sender that already fail-stopped
        # has undeliverable frames, so skip the sentinel and just join
        # (the thread is gone). Bounded retries keep close() from
        # wedging on a hung peer; the socket close below aborts any
        # still-blocked sendall.
        for dst, q in self._outbox.items():
            for _ in range(50):
                if self._send_failed.get(dst) is not None:
                    break
                try:
                    q.put(self._STOP, timeout=0.2)
                    break
                except queue.Full:
                    continue
        for th in self._senders.values():
            th.join(timeout=2.0)
        if self._decode_pool is not None:
            self._decode_pool.shutdown(wait=False)
        try:
            self._listener.close()
        except OSError:
            pass
        for s in self._out.values():
            try:
                s.close()
            except OSError:
                pass


_mesh: HostMesh | None = None
_mesh_lock = threading.Lock()


def dcn_active() -> bool:
    """True when this process is part of a multi-process engine group."""
    n, _pid, _port, _host = process_env()
    return n > 1 and os.environ.get("PATHWAY_DCN", "1") != "0"


def _flush_mesh_at_exit() -> None:
    """atexit hook: flush-close the mesh singleton before the
    interpreter kills its daemon sender threads.

    Nothing else ever closes the singleton, and the PR-6 overlapped
    sender means a rank can COMPLETE its last barrier/gather (it only
    needs the peers' frames) while its own final frame still sits in an
    outbox queue — process exit then kills the sender mid-queue, the
    frame is never delivered, and the slower peer blocks until the
    socket EOF declares this rank dead (the load-flaky
    test_two_process_wordcount_wire_formats failure: under contention
    the sender thread loses the race with interpreter teardown).
    ``close()`` queues the stop sentinel BEHIND pending frames and
    joins the senders, so every frame a completed tick produced is on
    the wire before the sockets go down.  Injected deaths (os._exit /
    SIGKILL) bypass atexit, so Fault Forge kills stay abrupt."""
    with _mesh_lock:
        m = _mesh
    if m is not None and not m._closed:
        try:
            m.close()
        except Exception:
            pass  # exit path: never mask the process's real outcome


def get_host_mesh() -> HostMesh:
    """Process-wide mesh singleton (daemon threads live for the process)."""
    global _mesh
    with _mesh_lock:
        if _mesh is None:
            n, pid, port, host = process_env()
            if n <= 1:
                raise HostMeshError("PATHWAY_PROCESSES must be > 1")
            _mesh = HostMesh(n, pid, port, host)
            import atexit

            atexit.register(_flush_mesh_at_exit)
        return _mesh
