"""Replica Shield wire tier — one writer streaming consolidated per-tick
index deltas to N read replicas over the PWHX7-family framed protocol.

The replicated read plane (ROADMAP "Replicated serving plane") splits the
serving topology into three roles: ONE writer (the engine process that
owns the index and the persistence store), N READ REPLICAS
(serving/replica.py — hydrate from the newest committed snapshot
generation, then apply this stream), and a failover ROUTER
(serving/router.py).  This module is the writer↔replica wire:

* ``DeltaStreamServer`` runs inside the writer.  The engine's
  ``ExternalIndexExec`` publishes its per-tick consolidated corpus
  deltas (``publish``); the server appends them to a bounded
  retained-delta ring and fans them out to every subscribed replica
  over per-subscriber bounded outboxes drained by sender threads — the
  same overlap/fail-stop shape as the host mesh's per-peer outboxes
  (parallel/host_exchange.py).  Frames reuse the typed columnar codec
  (parallel/wire.py): a delta frame IS a mesh data frame
  ``("data", 0, "repl:<node>", tick, [DiffBatch], tp)``, so key/diff
  packing and the embedding-column stacking apply unchanged.

* ``DeltaStreamClient`` runs inside each replica.  It dials the writer
  (jittered backoff, HMAC challenge-response under the same
  PATHWAY_DCN_SECRET job key as the mesh), subscribes from its
  snapshot's tick, replays the ring tail, then applies live frames.
  When the requested tick has already fallen off the ring the server
  answers ``resync`` and the replica re-hydrates from the (by now
  newer) snapshot generation instead — the bounded-ring contract of
  the tentpole: replay when cheap, full re-hydrate when not.

Shard Harbor (sharded corpus ownership): with
``PATHWAY_SERVING_SHARDS`` = S > 1 the writer splits every
consolidated per-tick delta batch by the DCN jk-hash partition
(engine/sharded.py ``shard_of`` — the same low-16-bit key routing the
device mesh and ``_DcnRouter`` use) and fans EACH SHARD'S stream only
to that shard's owners: a replica subscribing with ``shard=s`` (hello
field) receives only keys whose hash routes to s, so it hydrates and
holds ~1/S of the corpus.  Every subscriber still receives every
tick's (possibly empty) marker, so freshness tracking is
shard-independent.  A subscription whose expected shard count
disagrees with the writer's is refused at suback time (the torn
shard-assignment-map guard; the boot-time twin lives in
serving/router.py ``validate_shard_map``).

Standby takeover + incarnation fencing: the suback carries the
writer's ``PATHWAY_MESH_INCARNATION``.  A client remembers the highest
incarnation it has ever seen and REJECTS any writer presenting a lower
one (``fenced_count``) — after a standby takeover (parallel/standby.py
bumps the incarnation and resumes publishing on the writer endpoint), a
zombie primary that comes back can never feed replicas stale frames.
Clients accept a list of endpoints (primary first, standby next) and
rotate to the next endpoint on dial failure or fencing.

Freshness: every frame carries the writer's newest published tick, and
idle ticks still emit (empty) tick markers, so a replica always knows
whether it is caught up; heartbeats keep that knowledge fresh on idle
streams and double as the liveness signal for a dead/partitioned
writer.  The replica-side staleness clock
(``pathway_replica_staleness_seconds``) restarts whenever the replica
confirms ``applied_tick == newest_tick``.

Fault Forge: the sender loop runs every data frame through the same
``on_wire_send(channel)`` hook as the mesh, so
``drop/dup/delay=ch:repl...`` directives target the delta stream with
the familiar deterministic counters.

Authentication matches the mesh's threat model: delta frames carry
pickled control frames and codec batches, so every connection performs
the nonce challenge-response under the per-job shared secret and every
frame MAC covers (src, dst, seq, body).  A replica cannot be framed
dead by a forged frame, and unauthenticated bytes never reach
``pickle.loads``.
"""

from __future__ import annotations

import hmac
import os
import pickle
import queue
import socket
import struct
import threading
import time
from collections import deque
from typing import Any, Callable

from pathway_tpu.observability.journal import record as _journal_record
from pathway_tpu.observability.tracing import get_tracer
from pathway_tpu.parallel import wire
from pathway_tpu.parallel.host_exchange import (
    _MAC_LEN,
    _NONCE_LEN,
    _REJECT,
    _frame_mac,
    _job_key,
)

_REPL_MAGIC = b"PWRP2"  # replication protocol (sits beside the mesh's
# PWHX7: a replica is NOT a mesh rank — it never joins barriers — so the
# subscription stream gets its own handshake magic and version lane).
# v2 widens the hello with the subscriber's shard + expected shard count
# (Shard Harbor) and the suback with the writer's shard count +
# incarnation fencing token — a v1 peer's hello is a different length,
# so version skew fails the handshake instead of mis-parsing.
_OK_TAG = b"PWRO"
_HELLO_STRUCT = "<iqii"  # replica_id, from_tick, shard, expected shards

REPL_CHANNEL = "repl:idx"  # delta frames' wire channel (Fault Forge
# directives match it by prefix: drop/dup/delay=ch:repl)
STANDBY_CHANNEL = "repl:standby"  # the writer→standby leg: a standby
# subscriber's frames are re-tagged so Fault Forge directives can
# target JUST this leg (drop/dup/delay=ch:repl:standby) without
# touching the replica fan-out
STANDBY_ID = -2  # reserved replica_id for standby-writer subscriptions
OBSERVER_CHANNEL = "repl:observe"  # non-replica full-corpus observers
# (the router result cache's invalidation feed) — a distinct channel so
# Fault Forge can delay/drop the invalidation stream without touching
# the replica fan-out or the standby leg
OBSERVER_ID = -3  # reserved replica_id for observer subscriptions:
# negative ids may subscribe to the FULL corpus of a sharded writer
# (they never sit behind the router, so the torn-map guard passes)


def shards_env() -> int:
    """Serving-plane shard count, PATHWAY_SERVING_SHARDS (default 1 =
    every replica owns the full corpus — the pre-Shard-Harbor
    topology)."""
    raw = os.environ.get("PATHWAY_SERVING_SHARDS", "1") or "1"
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"PATHWAY_SERVING_SHARDS={raw!r} is not an int"
        ) from None
    if n < 1:
        raise ValueError(f"PATHWAY_SERVING_SHARDS={raw!r} must be >= 1")
    return n


def corpus_shard_of(keys, n_shards: int):
    """Shard assignment for corpus row keys — the DCN router's jk-hash
    partition (engine/sharded.py ``shard_of``: low 16 bits of the key
    mod the shard count), reused so the serving plane, the device mesh
    and the host mesh all agree on ownership."""
    import numpy as np

    from pathway_tpu.engine.sharded import shard_of

    return shard_of(np.asarray(keys, dtype=np.uint64), n_shards)


def ring_ticks_env() -> int:
    """Bounded retained-delta ring depth (ticks), PATHWAY_REPL_RING
    (default 1024).  A replica whose subscription tick fell off the ring
    full-re-hydrates from the newest snapshot generation instead."""
    raw = os.environ.get("PATHWAY_REPL_RING", "1024") or "1024"
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"PATHWAY_REPL_RING={raw!r} is not an int"
        ) from None
    return max(n, 1)


class ReplicationError(RuntimeError):
    pass


def _shutdown_close(conn: socket.socket) -> None:
    """shutdown() BEFORE close(): a plain close() while another thread
    is blocked in recv() on the same socket keeps the file description
    alive (the in-flight syscall holds it), so no FIN ever reaches the
    peer and both sides hang; shutdown() tears the connection down at
    the description level, waking every blocked reader."""
    try:
        conn.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        conn.close()
    except OSError:
        pass


def _read_exact(conn: socket.socket, count: int) -> bytes | None:
    buf = b""
    while len(buf) < count:
        try:
            chunk = conn.recv(count - len(buf))
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return buf


class _Subscriber:
    """One connected replica: bounded outbox + sender thread owning the
    connection's MAC sequence (frames leave in enqueue order)."""

    __slots__ = (
        "conn",
        "replica_id",
        "outbox",
        "backlog",
        "thread",
        "dead",
        "from_tick",
        "shard",
    )

    def __init__(self, conn: socket.socket, replica_id: int, depth: int):
        self.conn = conn
        self.replica_id = replica_id
        self.outbox: queue.Queue = queue.Queue(maxsize=depth)
        # ring-replay frames (suback first), sent by the sender thread
        # BEFORE it starts draining the outbox: the backlog can exceed
        # the outbox bound (up to ring_ticks entries), so it must never
        # go through put_nowait — a deep rejoin used to crash the
        # handshake thread with queue.Full and livelock the replica
        self.backlog: list[tuple] = []
        self.thread: threading.Thread | None = None
        self.dead = False
        self.from_tick = -1
        self.shard = -1  # -1 = full corpus (unsharded replica / standby)


class DeltaStreamServer:
    """Writer-side delta publisher: bounded retained ring + fan-out.

    ``publish(tick, batches)`` is called from the engine thread once per
    tick (idle ticks publish an empty marker so replicas track
    freshness); subscribers receive every published tick newer than
    their subscription point.  A subscriber that cannot keep up (full
    outbox) is dropped — it reconnects and replays the ring, or
    re-hydrates if it fell past the ring floor.  Never blocks the
    engine tick."""

    def __init__(
        self,
        port: int,
        host: str = "127.0.0.1",
        ring_ticks: int | None = None,
        outbox_depth: int = 256,
        n_shards: int | None = None,
        incarnation: int | None = None,
    ):
        self.host = host
        self.port = port
        self._key = _job_key()
        self.ring_ticks = (
            ring_ticks_env() if ring_ticks is None else max(int(ring_ticks), 1)
        )
        self.n_shards = (
            shards_env() if n_shards is None else max(int(n_shards), 1)
        )
        self.incarnation = (
            int(os.environ.get("PATHWAY_MESH_INCARNATION", "0") or 0)
            if incarnation is None
            else int(incarnation)
        )
        self._outbox_depth = max(int(outbox_depth), 8)
        self._lock = threading.Lock()
        # (tick, per_shard) newest-last — per_shard is one list of
        # DiffBatch per shard (length n_shards; the unsharded plane is
        # the 1-shard special case), split ONCE at publish so fan-out
        # and ring replay pay no per-subscriber partitioning.  floor =
        # newest tick whose deltas are UNAVAILABLE (evicted from the
        # ring, or covered only by the snapshot generation a restarted
        # writer restored from — set_floor) — a subscription from below
        # the floor must full-re-hydrate.  A fresh writer's floor stays
        # -1: no ticks existed before its first publish, so the ring IS
        # complete history and a from_tick=-1 subscriber replays it
        # instead of resyncing.
        self._ring: deque[tuple[int, list[list]]] = deque()
        self._floor = -1
        self._newest = -1
        self._ticks_published = 0  # deterministic counter the Fault
        # Forge kill=writer directive fires on (distinct ticks, so a
        # second index node merging into the same lockstep tick does
        # not advance it)
        self._subs: list[_Subscriber] = []
        self._closed = False
        from pathway_tpu.testing import faults

        self._fault_plan = faults.active()
        hb_ms = float(
            os.environ.get("PATHWAY_REPL_HEARTBEAT_MS", "1000") or 1000
        )
        self.heartbeat_s = max(hb_ms, 50.0) / 1000.0
        from pathway_tpu.observability import REGISTRY

        self._m_published = REGISTRY.counter(
            "pathway_repl_ticks_published_total",
            "delta-stream ticks published by the writer (empty markers "
            "included)",
        )
        self._m_delta_rows = REGISTRY.counter(
            "pathway_repl_delta_rows_total",
            "consolidated corpus delta rows streamed to replicas",
        )
        self._m_resyncs = REGISTRY.counter(
            "pathway_repl_resyncs_total",
            "subscriptions answered with a resync (requested tick fell "
            "off the bounded retained-delta ring)",
        )
        self._m_subs = REGISTRY.gauge(
            "pathway_repl_subscribers",
            "replicas currently subscribed to the delta stream",
        )
        self._m_subs.set_function(lambda: len(self._subs))
        self._m_dropped = REGISTRY.counter(
            "pathway_repl_subscribers_dropped_total",
            "replica subscriptions dropped (EOF, send failure, or a "
            "full outbox — the replica reconnects and replays)",
        )

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self.port = self._listener.getsockname()[1]  # resolve port 0
        self._listener.listen(16)
        threading.Thread(
            target=self._accept_loop, daemon=True, name="pw-repl-accept"
        ).start()
        threading.Thread(
            target=self._heartbeat_loop, daemon=True, name="pw-repl-hb"
        ).start()

    # --- writer-side API --------------------------------------------------

    def _split_shards(
        self, batches: list, n_shards: int | None = None
    ) -> list[list]:
        """Partition one tick's batches by corpus-key shard ownership
        (jk-hash, engine/sharded.py shard_of).  1-shard planes skip the
        hash entirely."""
        n = self.n_shards if n_shards is None else n_shards
        if n == 1:
            return [list(batches)]
        per: list[list] = [[] for _ in range(n)]
        for b in batches:
            if not len(b):
                continue
            dest = corpus_shard_of(b.keys, n)
            for s in range(n):
                m = dest == s
                if m.any():
                    per[s].append(b.mask(m))
        return per

    def reshard(self, n_new: int) -> dict:
        """Shard Flux: republish under a new shard map, live.

        Phase 1 (freeze) happens under the publisher lock: the retained
        ring's per-tick splits are re-partitioned by the NEW jk-hash
        map (so a new member's ring replay serves exactly its new key
        range), the shard count flips, and the incarnation bumps —
        one atomic commit from the stream's point of view.  Phase 2:
        every live subscriber is dropped; on redial the suback carries
        the new ``n_shards`` + incarnation, so the established torn-map
        guard becomes the TRANSITION guard — members still holding the
        old map fence themselves (``config_error``, serving stale,
        never mis-partitioned) until they adopt the new assignment
        (restart with the new env, or
        ``ReplicaServer.adopt_shard_map``), while negative-id
        subscribers (standby/observers, full-corpus) reconnect
        unaffected.  Returns {old, new, incarnation}."""
        with self._lock:
            n_new = max(int(n_new), 1)
            old = self.n_shards
            if n_new == old:
                return {
                    "old": old,
                    "new": n_new,
                    "incarnation": self.incarnation,
                }
            self._ring = deque(
                (
                    tick,
                    self._split_shards(
                        [b for part in per_shard for b in part], n_new
                    ),
                )
                for tick, per_shard in self._ring
            )
            self.n_shards = n_new
            self.incarnation += 1
            subs = list(self._subs)
        for sub in subs:
            self._drop(
                sub,
                f"shard map resharded {old} -> {n_new} (redial under "
                "the new map)",
            )
        import logging

        logging.getLogger("pathway_tpu").info(
            "delta stream: resharded %d -> %d shard(s) under "
            "incarnation %d",
            old,
            n_new,
            self.incarnation,
        )
        _journal_record(
            "writer-reshard",
            f"shard map {old} -> {n_new}",
            tick=self._newest,
            incarnation=self.incarnation,
            persist=True,
            old_shards=old,
            new_shards=n_new,
            subscribers_dropped=len(subs),
        )
        return {"old": old, "new": n_new, "incarnation": self.incarnation}

    @staticmethod
    def _shard_batches(per_shard: list[list], shard: int) -> list:
        """The batches a subscriber owning ``shard`` receives (-1 = the
        full corpus: standby writers and unsharded replicas)."""
        if shard < 0:
            return [b for part in per_shard for b in part]
        if shard >= len(per_shard):
            return []  # mismatched map: suback fencing rejects the
            # subscription; deliver markers only meanwhile
        return list(per_shard[shard])

    def publish(self, tick: int, batches: list) -> None:
        """Append one tick's consolidated deltas (possibly empty) to the
        ring and fan out per shard.  Engine-thread hot path:
        O(subscribers) queue puts, no I/O (sender threads own the
        sockets)."""
        with get_tracer().span("repl.publish", tick=tick):
            self._publish(tick, batches)

    def _publish(self, tick: int, batches: list) -> None:
        per_shard = self._split_shards(batches)
        fresh_tick = False
        with self._lock:
            if self._closed:
                return
            if tick <= self._newest:
                # a second index node publishing the same lockstep tick:
                # merge into the existing ring entry so replay stays
                # one-entry-per-tick
                for i in range(len(self._ring) - 1, -1, -1):
                    if self._ring[i][0] == tick:
                        for s, part in enumerate(per_shard):
                            self._ring[i][1][s].extend(part)
                        break
            else:
                fresh_tick = True
                self._ring.append((tick, per_shard))
                self._newest = tick
                self._ticks_published += 1
                while len(self._ring) > self.ring_ticks:
                    evicted, _b = self._ring.popleft()
                    self._floor = max(self._floor, evicted)
            subs = list(self._subs)
            n_published = self._ticks_published
        self._m_published.inc()
        rows = sum(len(b) for b in batches)
        if rows:
            self._m_delta_rows.inc(rows)
        for sub in subs:
            self._offer(
                sub,
                (
                    "data",
                    0,
                    REPL_CHANNEL,
                    tick,
                    self._shard_batches(per_shard, sub.shard),
                    None,
                ),
            )
        if fresh_tick and self._fault_plan is not None:
            # kill=writer: fires AFTER the tick fanned out, so the
            # replicas' last applied tick is deterministic too
            self._fault_plan.on_writer_tick(n_published)

    def newest_tick(self) -> int:
        return self._newest

    def set_floor(self, tick: int) -> None:
        """A restarted writer restored operator state at ``tick``: every
        delta at or before it exists only inside that snapshot
        generation, so subscriptions from below must full-re-hydrate.
        Called by the persistence glue before replay re-publishes the
        log tail (monotone — the floor never moves back)."""
        with self._lock:
            self._floor = max(self._floor, int(tick))

    def _offer(self, sub: _Subscriber, frame: tuple) -> None:
        """Non-blocking enqueue: a replica that cannot drain its outbox
        is dropped (it will reconnect and ring-replay) — the writer's
        tick cadence is never hostage to a slow replica."""
        if sub.dead:
            return
        try:
            sub.outbox.put_nowait(frame)
        except queue.Full:
            self._drop(sub, "outbox full (replica too slow)")

    def _drop(self, sub: _Subscriber, reason: str) -> None:
        with self._lock:
            if sub.dead:
                return
            sub.dead = True
            if sub in self._subs:
                self._subs.remove(sub)
        self._m_dropped.inc()
        if not self._closed:
            import logging

            logging.getLogger("pathway_tpu").warning(
                "delta stream: dropped replica %d subscription (%s)",
                sub.replica_id,
                reason,
            )
            _journal_record(
                "sub-dropped",
                reason,
                tick=self._newest,
                incarnation=self.incarnation,
                replica_id=sub.replica_id,
                shard=sub.shard,
            )
        try:
            sub.outbox.put_nowait(None)  # sender exit sentinel
        except queue.Full:
            pass
        _shutdown_close(sub.conn)

    # --- wiring -----------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(
                target=self._handshake, args=(conn,), daemon=True
            ).start()

    def _handshake(self, conn: socket.socket) -> None:
        """Nonce challenge-response (mesh-grade), then the replica's
        subscription frame, then register + backlog replay."""
        try:
            nonce = os.urandom(_NONCE_LEN)
            conn.settimeout(30.0)
            conn.sendall(nonce)
            hello = _read_exact(
                conn,
                len(_REPL_MAGIC) + struct.calcsize(_HELLO_STRUCT) + _MAC_LEN,
            )
            if hello is None or hello[: len(_REPL_MAGIC)] != _REPL_MAGIC:
                conn.close()
                return
            claimed, mac = hello[:-_MAC_LEN], hello[-_MAC_LEN:]
            if not hmac.compare_digest(
                mac, hmac.new(self._key, claimed + nonce, "sha256").digest()
            ):
                try:
                    conn.sendall(_REJECT)
                except OSError:
                    pass
                conn.close()
                return
            replica_id, from_tick, shard, _want_shards = struct.unpack(
                _HELLO_STRUCT, claimed[len(_REPL_MAGIC) :]
            )
            conn.sendall(
                hmac.new(
                    self._key, _OK_TAG + nonce + claimed, "sha256"
                ).digest()
            )
            conn.settimeout(None)
        except OSError:
            try:
                conn.close()
            except OSError:
                pass
            return
        sub = _Subscriber(conn, replica_id, self._outbox_depth)
        sub.from_tick = from_tick
        sub.shard = int(shard)
        with self._lock:
            if self._closed:
                conn.close()
                return
            resync = from_tick < self._floor
            # the boundary tick (== from_tick) replays too: a second
            # index node publishing the same lockstep tick merges into
            # the existing ring entry, and per-tick consolidated deltas
            # are idempotent state ops (last-op-per-key), so re-applying
            # the boundary is safe and never loses the merged tail.
            # A resync subscription gets the FULL ring: a replica that
            # re-hydrates redials anyway (bounded waste), and one that
            # CANNOT hydrate (no store — e.g. behind a takeover writer
            # that republished its corpus as its first tick) accepts
            # the gap and converges on everything the ring still holds
            backlog = (
                list(self._ring)
                if resync
                else [e for e in self._ring if e[0] >= from_tick]
            )
            # registered (and backlog captured) under the lock: a publish
            # racing this subscription lands in the outbox, which the
            # sender drains only AFTER the backlog, so the replica sees
            # ticks in order
            self._subs.append(sub)
            # suback carries the writer's shard count (the client
            # fences a torn shard-assignment map) and incarnation (the
            # zombie-primary fencing token)
            sub.backlog.append(
                (
                    "suback",
                    self._newest,
                    self._floor,
                    bool(resync),
                    self.n_shards,
                    self.incarnation,
                )
            )
            if resync:
                self._m_resyncs.inc()
            for tick, per_shard in backlog:
                sub.backlog.append(
                    (
                        "data",
                        0,
                        REPL_CHANNEL,
                        tick,
                        self._shard_batches(per_shard, sub.shard),
                        None,
                    )
                )
        sub.thread = threading.Thread(
            target=self._sender_loop,
            args=(sub,),
            daemon=True,
            name=f"pw-repl-send-{replica_id}",
        )
        sub.thread.start()
        # reader side only watches for EOF (the replica never sends data
        # frames after the subscription) so a vanished replica is
        # unsubscribed promptly instead of on the next full outbox
        threading.Thread(
            target=self._watch_eof, args=(sub,), daemon=True
        ).start()

    def _watch_eof(self, sub: _Subscriber) -> None:
        _read_exact(sub.conn, 1)  # returns on EOF/error
        self._drop(sub, "replica closed the subscription")

    def _sender_loop(self, sub: _Subscriber) -> None:
        from pathway_tpu.testing import faults

        plan = faults.active()
        seq = 0
        # the writer→standby leg carries its own channel tag so Fault
        # Forge can target takeover determinism without touching the
        # replica fan-out (drop/dup/delay=ch:repl:standby)
        standby = sub.replica_id < 0
        backlog = sub.backlog
        sub.backlog = []
        while True:
            if backlog:
                frame = backlog.pop(0)
            else:
                frame = sub.outbox.get()
            if frame is None or sub.dead:
                return
            try:
                repeats = 1
                if frame[0] == "data" and standby:
                    frame = (
                        frame[0],
                        frame[1],
                        STANDBY_CHANNEL
                        if sub.replica_id == STANDBY_ID
                        else OBSERVER_CHANNEL,
                        *frame[3:],
                    )
                if plan is not None and frame[0] == "data":
                    action = plan.on_wire_send(str(frame[2]))
                    if action is not None:
                        if action[0] == "drop":
                            continue
                        if action[0] == "dup":
                            repeats = 2
                        elif action[0] == "delay":
                            time.sleep(action[1])
                body, _stats = wire.encode_frame(frame, "codec", None)
                for _ in range(repeats):
                    mac = _frame_mac(self._key, 0, sub.replica_id, seq, body)
                    seq += 1
                    sub.conn.sendall(
                        struct.pack("<I", len(body)) + mac + body
                    )
            except Exception as e:  # OSError or encode bug: fail-stop
                self._drop(sub, f"send failed: {e}")
                return

    def _heartbeat_loop(self) -> None:
        while not self._closed:
            time.sleep(self.heartbeat_s)
            if self._closed:
                return
            with self._lock:
                subs = list(self._subs)
                newest = self._newest
            for sub in subs:
                self._offer(sub, ("hb", newest))

    def close(self) -> None:
        with self._lock:
            self._closed = True
            subs = list(self._subs)
            self._subs.clear()
        try:
            self._listener.close()
        except OSError:
            pass
        for sub in subs:
            sub.dead = True
            try:
                sub.outbox.put_nowait(None)
            except queue.Full:
                pass
            _shutdown_close(sub.conn)


# --- process-global writer publisher ---------------------------------------
# Armed by PATHWAY_REPL_PORT on the writer process: ExternalIndexExec
# publishes its per-tick corpus deltas here (engine/index_node.py), and
# replicas subscribe at <host>:<port>.  None everywhere else — the hook
# costs one cached is-None check per tick.

_publisher: DeltaStreamServer | None = None
_publisher_resolved = False
_publisher_lock = threading.Lock()


def publisher() -> DeltaStreamServer | None:
    """The writer's process-global delta-stream server (lazily bound
    from PATHWAY_REPL_PORT; PATHWAY_REPL_HOST overrides the bind host),
    or None when this process is not a replication writer."""
    global _publisher, _publisher_resolved
    if _publisher_resolved:
        return _publisher
    with _publisher_lock:
        if not _publisher_resolved:
            raw = os.environ.get("PATHWAY_REPL_PORT", "")
            if raw:
                try:
                    port = int(raw)
                except ValueError:
                    raise ReplicationError(
                        f"PATHWAY_REPL_PORT={raw!r} is not an int"
                    ) from None
                _publisher = DeltaStreamServer(
                    port,
                    host=os.environ.get(
                        "PATHWAY_REPL_HOST", "127.0.0.1"
                    ),
                )
            _publisher_resolved = True
    return _publisher


def reset_publisher() -> None:
    """Test hook: close and forget the process-global publisher."""
    global _publisher, _publisher_resolved
    with _publisher_lock:
        if _publisher is not None:
            _publisher.close()
        _publisher = None
        _publisher_resolved = False


class DeltaStreamClient:
    """Replica-side subscriber: dial, subscribe from a tick, replay the
    ring tail, apply live frames; reconnect (from the last applied tick)
    on writer death; full-re-hydrate on resync.

    Callbacks (all invoked on the client's reader thread):

    * ``on_deltas(tick, batches)`` — apply one tick's consolidated
      deltas (batches may be empty: a freshness marker).
    * ``on_resync() -> int`` — the requested tick fell off the writer's
      ring: re-hydrate from the newest snapshot generation and return
      the new subscription tick.
    * ``on_applied(tick, n_applied)`` — after each applied tick (the
      Fault Forge's replica-kill hook rides here).
    """

    def __init__(
        self,
        host: str,
        port: int,
        replica_id: int,
        from_tick: int,
        on_deltas: Callable[[int, list], None],
        on_resync: Callable[[], int] | None = None,
        on_applied: Callable[[int, int], None] | None = None,
        connect_timeout: float = 60.0,
        *,
        shard: int = -1,
        expect_shards: int = 0,
        endpoints: list[tuple[str, int]] | None = None,
    ):
        self.host = host
        self.port = port
        # endpoints: (host, port) list tried in order — the primary
        # writer first, the standby's takeover endpoint next.  The
        # single-endpoint form (host/port args) is the common same-port
        # takeover deployment.
        self.endpoints = (
            [(host, int(port))] if not endpoints else list(endpoints)
        )
        self._ep_idx = 0
        self.replica_id = int(replica_id)
        self.from_tick = int(from_tick)
        # shard ownership (Shard Harbor): subscribe to one shard's
        # stream (-1 = the full corpus); expect_shards (when > 0) fences
        # a writer whose shard count disagrees — a torn assignment map
        # must never half-apply
        self.shard = int(shard)
        self.expect_shards = int(expect_shards)
        self.on_deltas = on_deltas
        self.on_resync = on_resync
        self.on_applied = on_applied
        self.connect_timeout = connect_timeout
        self._key = _job_key()
        self._closed = False
        self._conn: socket.socket | None = None
        self.applied_tick = int(from_tick)
        self.applied_count = 0  # ticks applied since process start (the
        # deterministic counter kill=replica:N,tick:T fires on)
        self.newest_known = -1
        self.resyncs = 0
        self.connected = False
        # incarnation fencing: the highest writer incarnation ever seen
        # on this stream — any writer presenting a LOWER one is a
        # zombie primary (the standby already took over) and its
        # subscription is rejected before a single frame applies
        self.writer_incarnation = -1
        self.fenced_count = 0
        self.config_error: str | None = None  # sticky shard-map
        # mismatch diagnosis (kept across redials for health reporting)
        # caught_up: applied_tick has reached the stream head at least
        # once since the current subscription — the freshness bound a
        # replica must clear before the router re-admits it
        self.caught_up = False
        self._fresh_at: float | None = None
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None

    # --- freshness --------------------------------------------------------

    def staleness_seconds(self) -> float | None:
        """Seconds since this replica last confirmed it was caught up
        with the writer's newest published tick (None until the first
        catch-up).  A connected, caught-up replica reads 0.0 — the
        clock only runs while the replica is behind the stream head or
        cut off from the writer.  Mirrors serving/degrade.py's
        staleness clock."""
        with self._lock:
            if (
                self.connected
                and self.caught_up
                and self.newest_known <= self.applied_tick
            ):
                return 0.0
            if self._fresh_at is None:
                return None
            return max(0.0, time.monotonic() - self._fresh_at)

    def _note_progress(self) -> None:
        became_fresh = False
        with self._lock:
            if self.newest_known <= self.applied_tick:
                became_fresh = not self.caught_up
                self.caught_up = True
                self._fresh_at = time.monotonic()
        if became_fresh:
            # the takeover/reshard window's END edge in /fleet/events:
            # this subscription reached the stream head
            _journal_record(
                "caught-up",
                f"replica {self.replica_id} reached the stream head",
                tick=self.applied_tick,
                incarnation=max(self.writer_incarnation, 0),
                replica_id=self.replica_id,
            )

    # --- lifecycle --------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run,
            daemon=True,
            name=f"pw-repl-client-{self.replica_id}",
        )
        self._thread.start()

    def close(self) -> None:
        self._closed = True
        conn = self._conn
        if conn is not None:
            _shutdown_close(conn)

    def _dial(self) -> socket.socket | None:
        import random as _random

        deadline = time.monotonic() + self.connect_timeout
        attempt = 0
        while not self._closed and time.monotonic() < deadline:
            s: socket.socket | None = None
            ep = self.endpoints[self._ep_idx % len(self.endpoints)]
            try:
                s = socket.create_connection(ep, timeout=5.0)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                s.settimeout(10.0)
                nonce = _read_exact(s, _NONCE_LEN)
                if nonce is None:
                    raise OSError("writer closed during handshake")
                hello = _REPL_MAGIC + struct.pack(
                    _HELLO_STRUCT,
                    self.replica_id,
                    self.from_tick,
                    self.shard,
                    self.expect_shards,
                )
                s.sendall(
                    hello
                    + hmac.new(self._key, hello + nonce, "sha256").digest()
                )
                ok = _read_exact(s, _MAC_LEN)
                if ok is None:
                    raise OSError("writer closed during handshake")
                if ok == _REJECT:
                    s.close()
                    raise ReplicationError(
                        f"replica {self.replica_id}: writer rejected the "
                        "subscription — authentication failed (is "
                        "PATHWAY_DCN_SECRET identical on the writer and "
                        "every replica?)"
                    )
                expected = hmac.new(
                    self._key, _OK_TAG + nonce + hello, "sha256"
                ).digest()
                if not hmac.compare_digest(ok, expected):
                    raise OSError("unexpected handshake response")
                s.settimeout(None)
                return s
            except OSError:
                if s is not None:
                    try:
                        s.close()
                    except OSError:
                        pass
                # rotate to the next endpoint (standby takeover address)
                # before backing off — a dead primary must not eat the
                # whole connect budget when the standby is already up
                self._ep_idx += 1
                attempt += 1
                backoff = min(2.0, 0.05 * (2 ** min(attempt, 6)))
                time.sleep(backoff * (0.5 + _random.random()))
        return None

    def _probe_incarnation(self, ep: tuple[str, int]) -> int | None:
        """Handshake + read the suback + close: what incarnation does
        this endpoint's writer publish under?  None when unreachable or
        not speaking PWRP2."""
        s: socket.socket | None = None
        try:
            s = socket.create_connection(ep, timeout=2.0)
            s.settimeout(5.0)
            nonce = _read_exact(s, _NONCE_LEN)
            if nonce is None:
                return None
            hello = _REPL_MAGIC + struct.pack(
                _HELLO_STRUCT,
                self.replica_id,
                self.applied_tick,
                self.shard,
                self.expect_shards,
            )
            s.sendall(
                hello + hmac.new(self._key, hello + nonce, "sha256").digest()
            )
            ok = _read_exact(s, _MAC_LEN)
            if ok is None or ok == _REJECT:
                return None
            if not hmac.compare_digest(
                ok,
                hmac.new(self._key, _OK_TAG + nonce + hello, "sha256").digest(),
            ):
                return None
            head = _read_exact(s, 4 + _MAC_LEN)
            if head is None:
                return None
            (length,) = struct.unpack("<I", head[:4])
            body = _read_exact(s, length)
            if body is None:
                return None
            if not hmac.compare_digest(
                head[4:],
                _frame_mac(self._key, 0, self.replica_id, 0, body),
            ):
                return None
            frame = wire.decode_frame(body)
            if frame[0] != "suback":
                return None
            return int(frame[5])
        except Exception:
            return None
        finally:
            if s is not None:
                _shutdown_close(s)

    def _probe_endpoints(self) -> None:
        """Multi-endpoint fencing bootstrap: learn EVERY endpoint's
        incarnation before subscribing and start with the highest — a
        restarted replica (empty in-memory high-water) must not
        re-adopt a zombie primary just because the zombie's endpoint
        dials first."""
        best_idx, best_inc = None, -1
        for i, ep in enumerate(self.endpoints):
            inc = self._probe_incarnation(ep)
            if inc is not None and inc > best_inc:
                best_idx, best_inc = i, inc
        if best_idx is not None:
            with self._lock:
                self.writer_incarnation = max(
                    self.writer_incarnation, best_inc
                )
            self._ep_idx = best_idx

    def _run(self) -> None:
        while not self._closed:
            if len(self.endpoints) > 1 and self.writer_incarnation < 0:
                # fencing bootstrap ONLY: once a high-water is known,
                # suback-time fencing rejects zombies by itself — a
                # probe per routine redial would cost every endpoint a
                # wasted authenticated subscription (suback + ring
                # backlog) each time
                self._probe_endpoints()
            conn = self._dial()
            if conn is None:
                if self._closed:
                    return
                # writer unreachable within the budget: keep trying —
                # the replica serves (increasingly stale) reads
                # meanwhile, and the router's staleness bound decides
                # admission
                continue
            self._conn = conn
            with self._lock:
                self.connected = True
                self.caught_up = False
            try:
                self._read_stream(conn)
            finally:
                with self._lock:
                    self.connected = False
                try:
                    conn.close()
                except OSError:
                    pass
                self._conn = None
                # reconnect from whatever we applied last
                self.from_tick = self.applied_tick
                if not self._closed:
                    # the takeover window's START edge in /fleet/events:
                    # replicas see the writer's death as stream EOF
                    # within milliseconds of the kill
                    _journal_record(
                        "stream-disconnect",
                        f"replica {self.replica_id} lost the delta stream",
                        tick=self.applied_tick,
                        incarnation=max(self.writer_incarnation, 0),
                        replica_id=self.replica_id,
                    )

    def _read_stream(self, conn: socket.socket) -> None:
        recv_seq = 0
        while not self._closed:
            head = _read_exact(conn, 4 + _MAC_LEN)
            if head is None:
                return
            (length,) = struct.unpack("<I", head[:4])
            body = _read_exact(conn, length)
            if body is None:
                return
            if not hmac.compare_digest(
                head[4:],
                _frame_mac(self._key, 0, self.replica_id, recv_seq, body),
            ):
                return  # forged/replayed frame: drop the link, redial
            recv_seq += 1
            try:
                frame = wire.decode_frame(body)
            except Exception:
                return  # corrupt frame: fail-stop this link, redial
            kind = frame[0]
            if kind == "hb":
                with self._lock:
                    self.newest_known = max(self.newest_known, frame[1])
                self._note_progress()
            elif kind == "suback":
                _k, newest, _floor, resync, srv_shards, srv_inc = frame
                if srv_inc < self.writer_incarnation:
                    # zombie primary: a standby with a HIGHER incarnation
                    # already took over this stream — reject the whole
                    # subscription (no frame from this writer may apply)
                    # and rotate to the next endpoint
                    self.fenced_count += 1
                    import logging

                    logging.getLogger("pathway_tpu").warning(
                        "replica %d: fenced zombie writer (incarnation "
                        "%d < %d) at %s",
                        self.replica_id,
                        srv_inc,
                        self.writer_incarnation,
                        self.endpoints[self._ep_idx % len(self.endpoints)],
                    )
                    # persist=True: this record is how a SIGKILLed zombie
                    # is reconstructed from its peers' journals
                    _journal_record(
                        "zombie-fenced",
                        f"writer incarnation {srv_inc} < "
                        f"{self.writer_incarnation}",
                        tick=self.applied_tick,
                        incarnation=self.writer_incarnation,
                        persist=True,
                        replica_id=self.replica_id,
                        zombie_incarnation=srv_inc,
                    )
                    self._ep_idx += 1
                    time.sleep(0.2)  # a persistent zombie must not
                    # hot-loop dial->fence->dial
                    return
                with self._lock:
                    prev_inc = self.writer_incarnation
                    self.writer_incarnation = max(
                        self.writer_incarnation, srv_inc
                    )
                if srv_inc > max(prev_inc, 0):
                    _journal_record(
                        "incarnation-seen",
                        f"writer incarnation {prev_inc} -> {srv_inc}",
                        tick=self.applied_tick,
                        incarnation=srv_inc,
                        replica_id=self.replica_id,
                        previous=prev_inc,
                    )
                torn = (
                    self.expect_shards and srv_shards != self.expect_shards
                ) or (
                    # an UNSHARDED replica (no expectation at all)
                    # against a sharded writer is torn too: it would
                    # receive the FULL corpus while the router treats
                    # it as one shard's owner — merged top-k would
                    # carry duplicates and the 1/S memory win silently
                    # vanishes.  Full-corpus subscriptions to a sharded
                    # writer are reserved for negative ids (standby /
                    # observers), which never sit behind the router.
                    not self.expect_shards
                    and self.shard < 0
                    and self.replica_id >= 0
                    and srv_shards > 1
                ) or (
                    # a shard index the writer does not split to would
                    # receive an empty stream yet report caught-up
                    self.shard >= 0
                    and srv_shards > 0
                    and self.shard >= srv_shards
                )
                if torn:
                    # torn shard assignment: this replica's map and the
                    # writer's split disagree — applying would
                    # mis-partition the corpus silently
                    self.config_error = (
                        f"writer splits the corpus into {srv_shards} "
                        f"shard(s) but this replica expected "
                        f"{self.expect_shards or 1} (torn shard "
                        "assignment map — fix PATHWAY_SERVING_SHARDS/"
                        "PATHWAY_REPLICA_SHARD and restart)"
                    )
                    import logging

                    logging.getLogger("pathway_tpu").error(
                        "replica %d: %s", self.replica_id, self.config_error
                    )
                    _journal_record(
                        "config-error",
                        self.config_error,
                        tick=self.applied_tick,
                        incarnation=max(self.writer_incarnation, 0),
                        replica_id=self.replica_id,
                        writer_shards=srv_shards,
                        expected_shards=self.expect_shards,
                    )
                    time.sleep(0.5)
                    return
                self.config_error = None
                with self._lock:
                    self.newest_known = max(self.newest_known, newest)
                if resync:
                    self.resyncs += 1
                    if self.on_resync is None:
                        # no hydrate path (store-less replica — e.g.
                        # behind a takeover writer that republished its
                        # corpus as its first tick): accept the gap
                        # and converge on the FULL ring the server
                        # replays for resync subscriptions —
                        # consolidated per-tick deltas are idempotent,
                        # and frames older than applied_tick skip below
                        self.from_tick = self.applied_tick
                        continue
                    new_tick = int(self.on_resync())
                    if new_tick > self.from_tick:
                        # re-hydrated to a newer generation: the index
                        # was replaced under us, so advance past it
                        self.from_tick = new_tick
                        self.applied_tick = max(
                            self.applied_tick, new_tick
                        )
                        if new_tick >= _floor:
                            return  # redial: normal ring-tail replay
                            # from the fresh generation
                    # the store has no generation reaching the ring
                    # floor yet (writer restarted without a fresh
                    # snapshot, or the newest generation is torn):
                    # NEVER silently accept a gap a snapshot will
                    # cover — wait for the writer to commit one
                    # instead of hot-looping dial->resync->dial
                    time.sleep(0.5)
                    return
                self._note_progress()
            elif kind == "data":
                _k, _src, _channel, tick, batches, _tp = frame
                if tick < self.applied_tick:
                    continue  # writer-restart overlap: already applied
                # tick == applied_tick is NOT skipped: a second index
                # node publishing the same lockstep tick, and the
                # boundary tick of a reconnect replay, both arrive as
                # equal-tick frames — consolidated per-tick deltas are
                # idempotent state ops, so re-applying is safe and
                # skipping would lose the merged tail
                try:
                    with get_tracer().span(
                        "repl.apply",
                        tick=tick,
                        replica_id=self.replica_id,
                        batches=len(batches),
                    ):
                        self.on_deltas(tick, batches)
                except Exception:
                    # an apply failure must not kill the reader thread
                    # (the replica would zombie: alive, serving ever-
                    # staler reads, never reconnecting).  Fail-stop the
                    # link like a corrupt frame: redial replays from
                    # applied_tick, re-attempting this tick.
                    import logging

                    logging.getLogger("pathway_tpu").exception(
                        "replica %d: applying delta tick %d failed; "
                        "dropping the subscription to retry",
                        self.replica_id,
                        tick,
                    )
                    time.sleep(0.5)  # a deterministic failure must
                    # not hot-loop dial->apply->fail
                    return
                self.applied_tick = tick
                self.applied_count += 1
                with self._lock:
                    self.newest_known = max(self.newest_known, tick)
                self._note_progress()
                if self.on_applied is not None:
                    self.on_applied(tick, self.applied_count)


def consolidate_rows(rows: list[tuple[int, int, tuple]]) -> list:
    """Collapse one tick's raw corpus updates to per-key FINAL ops (the
    "consolidated per-tick deltas" of the tentpole): the last op per key
    wins, upsert-after-remove collapses to the upsert, and emission
    order is the order keys were last touched — so a replica applying
    the result converges to the same corpus as applying the raw stream.

    Returns ``pickle``-free row tuples ready for DiffBatch.from_rows.
    """
    final: dict[int, tuple[int, int, tuple]] = {}
    for row in rows:
        k = row[0]
        final.pop(k, None)  # re-insert to keep last-touch order
        final[k] = row
    return list(final.values())
