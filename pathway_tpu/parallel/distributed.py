"""Multi-process execution — the DCN/multi-host rung of the comm backend.

The reference scales past one process by spawning PATHWAY_PROCESSES OS
processes connected by timely's TCP mesh (reference:
src/engine/dataflow/config.rs:88-121, env contract
PATHWAY_PROCESSES/PATHWAY_PROCESS_ID/PATHWAY_FIRST_PORT;
external/timely-dataflow/communication/src/networking.rs:16-33). The
TPU-native equivalent is one JAX process per host joined through
``jax.distributed``: after initialization every process sees the global
device set, meshes span hosts, and XLA collectives ride ICI within a slice
and DCN across slices — no hand-rolled socket protocol.

What runs multi-process: device-resident data parallelism — corpus
sharding for the KNN/retrieval path (`sharded_topk_global`), embed batch
sharding — on the jax.distributed device group (this module), and host-
side keyed engine state spanning processes over the TCP host mesh
(parallel/host_exchange.py + engine/dcn.py): groupby/join state is
key-sharded across the process group with lockstep barrier-scheduled
ticks and group-consistent persistence. The device group is joined when
PATHWAY_JAX_DISTRIBUTED=1; the host mesh joins whenever
PATHWAY_PROCESSES > 1.
"""

from __future__ import annotations

import os
from typing import Any

import numpy as np

_initialized = False


def process_env() -> tuple[int, int, str]:
    """(num_processes, process_id, coordinator) from the reference env
    contract; coordinator defaults to localhost at PATHWAY_FIRST_PORT."""
    n = int(os.environ.get("PATHWAY_PROCESSES", "1") or 1)
    pid = int(os.environ.get("PATHWAY_PROCESS_ID", "0") or 0)
    first_port = int(os.environ.get("PATHWAY_FIRST_PORT", "10000") or 10000)
    coord = os.environ.get(
        "JAX_COORDINATOR_ADDRESS", f"127.0.0.1:{first_port}"
    )
    return n, pid, coord


def maybe_initialize() -> bool:
    """Join the process group when PATHWAY_PROCESSES > 1 (idempotent).
    Returns True when running multi-process. On the CPU backend the gloo
    collectives implementation is selected so cross-process collectives
    work in tests and the driver's dryrun."""
    global _initialized
    n, pid, coord = process_env()
    if n <= 1:
        return False
    if _initialized:
        return True
    import jax

    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass  # unavailable on this jax version: TPU backends don't need it
    try:
        jax.distributed.initialize(
            coordinator_address=coord, num_processes=n, process_id=pid
        )
    except RuntimeError as e:
        # most common cause: user code ran a JAX computation during graph
        # construction, initializing backends before pw.run() could join
        # the process group
        raise RuntimeError(
            f"PATHWAY_PROCESSES={n} but the JAX process group could not be "
            "joined. jax.distributed.initialize must run before any JAX "
            "computation — avoid touching JAX arrays while declaring the "
            "graph, or call pathway_tpu.parallel.distributed."
            "maybe_initialize() at the top of your script "
            f"(original error: {e})"
        ) from e
    _initialized = True
    return True


def is_multiprocess() -> bool:
    import jax

    return _initialized or jax.process_count() > 1


def global_mesh(axis: str = "data"):
    """Mesh over the GLOBAL device set (all processes)."""
    import jax
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()), (axis,))


def from_process_local(local: np.ndarray, mesh: Any, axis: str = "data"):
    """Assemble a globally-sharded array from each process's local rows
    (the multi-host replacement for device_put-with-sharding, which
    requires the full array on every host)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.make_array_from_process_local_data(
        NamedSharding(mesh, P(axis, *([None] * (local.ndim - 1)))),
        jnp.asarray(local),
    )


def replicated(value: np.ndarray, mesh: Any):
    """A fully-replicated global array (every process passes equal data)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.make_array_from_process_local_data(
        NamedSharding(mesh, P()), jnp.asarray(value)
    )


def to_host(x) -> np.ndarray:
    """Fetch a replicated result on this process (np.asarray would demand
    every shard be addressable, which is false multi-process)."""
    return np.asarray(x.addressable_data(0))


def sharded_topk_global(
    queries: np.ndarray,  # [B, D] f32 — identical on every process
    corpus_local: np.ndarray,  # [n_local, D] this process's corpus rows
    valid_local: np.ndarray,  # [n_local] bool
    k: int,
    *,
    mesh: Any = None,
    axis: str = "data",
    metric: str = "cosine",
    bf16: bool = True,
) -> tuple[np.ndarray, np.ndarray]:
    """Multi-host KNN (BASELINE: 1M docs sharded across a pod): each
    process contributes its corpus shard; queries are replicated; local
    top-k results merge through an all-gather over ICI/DCN
    (ops/knn.sharded_topk's TPU-KNN recipe on a global mesh). Every shard
    must hold the same row count (pad + mask). Returns host (scores, ids)
    with ids indexing the GLOBAL corpus (process-major order)."""
    import jax

    from pathway_tpu.ops.knn import _sharded_topk_impl

    if mesh is None:
        mesh = global_mesh(axis)
    n_shards = mesh.shape[axis]
    n_local = corpus_local.shape[0]
    assert n_local % max(1, (n_shards // jax.process_count())) == 0
    corpus = from_process_local(corpus_local.astype(np.float32), mesh, axis)
    valid = from_process_local(np.asarray(valid_local, bool), mesh, axis)
    n_global = corpus.shape[0]
    from pathway_tpu.ops.knn import shard_base_indices

    base = shard_base_indices(n_global, n_shards)
    local_rows = n_global // jax.process_count()
    start = jax.process_index() * local_rows
    base_idx = from_process_local(
        base[start : start + local_rows], mesh, axis
    )
    q = replicated(np.asarray(queries, np.float32), mesh)
    sc, ix = _sharded_topk_impl(
        q, corpus, valid, base_idx, k, metric, bf16, mesh, axis
    )
    return to_host(sc), to_host(ix)
