"""Collective patterns over the mesh — the ICI-native replacements for the
reference's timely channel pacts (reference: §2.2 of SURVEY —
timely `Exchange` pact → all_to_all; `Broadcast` → all_gather;
progress frontier exchange → psum; vendored
external/timely-dataflow/communication replaced by XLA collectives)."""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P


def sharded_rows(mesh: Any, axis: str = "data") -> NamedSharding:
    """Sharding for [N, ...] row-major tables: rows split over `axis`."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Any) -> NamedSharding:
    return NamedSharding(mesh, P())


@functools.partial(jax.jit, static_argnames=("mesh", "axis"))
def _exchange_impl(values, dest_shard, mesh, axis):
    """Route rows to the mesh shard given per-row in `dest_shard`
    (the Exchange pact: key.shard() % n_workers,
    reference src/engine/dataflow/operators.rs:128). Dense formulation:
    every device masks + all-gathers, then keeps its rows — exact semantics
    of a ragged all-to-all with static shapes (XLA optimizes the gather
    over ICI)."""
    from jax import shard_map

    n_shards = mesh.shape[axis]

    def local(vals, dest):
        # vals: [n_local, d]; dest: [n_local]
        me = jax.lax.axis_index(axis)
        all_vals = jax.lax.all_gather(vals, axis, axis=0, tiled=True)
        all_dest = jax.lax.all_gather(dest, axis, axis=0, tiled=True)
        keep = all_dest == me
        # static shape: every device holds the full set, masked rows zeroed
        out = jnp.where(keep[:, None], all_vals, 0)
        return out, keep

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis, None), P(axis)),
        out_specs=(P(), P()),
        check_vma=False,
    )(values, dest_shard)


def exchange_by_shard(values, dest_shard, mesh, axis: str = "data"):
    """All-to-all exchange of rows by destination shard id. Returns
    (gathered_values, keep_mask) replicated per device — each shard's rows
    are the masked subset."""
    return _exchange_impl(values, dest_shard, mesh, axis)


@functools.partial(jax.jit, static_argnames=("mesh", "axis"))
def frontier_allreduce(local_time, mesh, axis: str = "data"):
    """Global frontier = min over shards' local clocks — the tiny all-reduce
    per tick replacing timely's progress-update broadcast
    (reference: timely progress tracking, SURVEY §5.8)."""
    from jax import shard_map

    def local(t):
        return jax.lax.pmin(t, axis)

    return shard_map(
        local, mesh=mesh, in_specs=(P(axis),), out_specs=P(axis),
        check_vma=False,
    )(local_time)
