"""Collective patterns over the mesh — the ICI-native replacements for the
reference's timely channel pacts (reference: §2.2 of SURVEY —
timely `Exchange` pact → all_to_all; `Broadcast` → all_gather;
progress frontier exchange → psum; vendored
external/timely-dataflow/communication replaced by XLA collectives)."""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P


def sharded_rows(mesh: Any, axis: str = "data") -> NamedSharding:
    """Sharding for [N, ...] row-major tables: rows split over `axis`."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Any) -> NamedSharding:
    return NamedSharding(mesh, P())


def exchange_by_shard(values, dest_shard, mesh, axis: str = "data"):
    """Route rows to the mesh shard given per-row in `dest_shard`
    (the Exchange pact: key.shard() % n_workers, reference
    src/engine/dataflow/operators.rs:128) through a real ragged
    `lax.all_to_all` (parallel/exchange.py) — per-device memory is
    O(n_shards × bucket), not O(total rows) like the round-1
    all-gather+mask placeholder.

    Returns (per_shard_values, per_shard_counts): a [n_shards, cap, d]
    array whose block s holds the rows shard s received, and the valid row
    count per block."""
    import numpy as np

    from pathway_tpu.parallel.exchange import ragged_all_to_all

    n_shards = mesh.shape[axis]
    vals = np.ascontiguousarray(values)
    if vals.dtype.itemsize % 4:
        raise TypeError(
            f"exchange_by_shard needs a 4/8-byte element dtype, got "
            f"{vals.dtype}"
        )
    d = vals.shape[1]
    # rows travel as exact int32 bit patterns — no value cast for any dtype
    words = vals.view(np.int32).reshape(vals.shape[0], -1)
    blocks = ragged_all_to_all(
        words, np.asarray(dest_shard, dtype=np.int32), mesh, axis
    )
    cap = max((len(b) for b in blocks), default=0)
    out = np.zeros((n_shards, cap, d), dtype=vals.dtype)
    counts = np.zeros(n_shards, dtype=np.int64)
    for s, b in enumerate(blocks):
        counts[s] = len(b)
        if len(b):
            out[s, : len(b)] = b.view(vals.dtype).reshape(len(b), d)
    return out, counts


def _shard_map_compat():
    """(shard_map, replication-check kwarg) across the API move: new
    jax exposes `jax.shard_map` with `check_vma`; 0.4.x ships it under
    `jax.experimental.shard_map` with `check_rep`."""
    try:
        from jax import shard_map  # jax >= 0.6

        return shard_map, {"check_vma": False}
    except ImportError:
        from jax.experimental.shard_map import shard_map

        return shard_map, {"check_rep": False}


@functools.partial(jax.jit, static_argnames=("mesh", "axis"))
def frontier_allreduce(local_time, mesh, axis: str = "data"):
    """Global frontier = min over shards' local clocks — the tiny all-reduce
    per tick replacing timely's progress-update broadcast
    (reference: timely progress tracking, SURVEY §5.8)."""
    shard_map, check_kw = _shard_map_compat()

    def local(t):
        return jax.lax.pmin(t, axis)

    return shard_map(
        local, mesh=mesh, in_specs=(P(axis),), out_specs=P(axis),
        **check_kw,
    )(local_time)
