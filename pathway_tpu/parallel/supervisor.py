"""Phoenix Mesh group supervisor — whole-group restart on rank failure.

The mesh is fail-stop per incarnation: a dead peer surfaces as
HostMeshError on every survivor (heartbeat liveness, reader EOF, or a
send failure — parallel/host_exchange.py), every rank exits nonzero, and
recovery = restart the WHOLE group from the latest group-committed
snapshot generation (persistence/_runtime_glue.py), exactly the
reference's recovery model (whole-cluster restart from the persisted
frontier, src/persistence/state.rs:291).  This module is the missing
restart half: it spawns the N ranks, watches them, tears the group down
when any rank dies, and respawns everything under a bounded restart
budget with jittered backoff.

Each incarnation gets ``PATHWAY_MESH_INCARNATION=<n>`` in its
environment: Fault Forge directives (testing/faults.py) default to
incarnation 0, so an injected death is not re-injected into the
restarted group — chaos tests assert the SECOND incarnation converges on
the uninterrupted run's output.

Usage::

    python -m pathway_tpu.parallel.supervisor -n 2 -- python job.py
    pathway-tpu spawn -n 2 --supervise -- python job.py

or programmatically (tests, bench.py chaos_recovery)::

    sup = GroupSupervisor(["python", "job.py"], n=2, env=extra_env)
    rc = sup.run()
    sup.events  # [(monotonic_ts, "rank-died"|"group-restart"|..., detail)]
"""

from __future__ import annotations

import os
import random
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Mapping


def max_restarts_env() -> int:
    """Bounded restart budget before giving up with today's fail-stop
    diagnostics (PATHWAY_MESH_MAX_RESTARTS, default 2)."""
    return int(os.environ.get("PATHWAY_MESH_MAX_RESTARTS", "2") or 2)


class GroupSupervisor:
    """Spawn-and-respawn an N-rank process group.

    ``argv`` is the per-rank command line; each rank runs it with
    PATHWAY_PROCESSES / PATHWAY_PROCESS_ID / PATHWAY_MESH_INCARNATION
    set (plus ``env`` overrides, applied to every rank; ``rank_env``
    may add per-rank variables).  A group where every rank exits 0 is
    done; any nonzero (or signaled) rank kills the survivors and — if
    the restart budget allows — respawns the whole group.
    """

    def __init__(
        self,
        argv: list[str],
        n: int,
        *,
        env: Mapping[str, str] | None = None,
        rank_env: Callable[[int], Mapping[str, str] | None] | None = None,
        max_restarts: int | None = None,
        grace_s: float = 5.0,
        backoff_s: float = 0.25,
        poll_s: float = 0.05,
        log_dir: str | None = None,
        initial_incarnation: int = 0,
    ):
        self.argv = list(argv)
        self.n = int(n)
        self.env = dict(env or {})
        self.rank_env = rank_env
        # a standby-writer takeover spawns the writer role starting at
        # the FENCED incarnation (one past everything the plane has
        # seen) so the PWRP2 handshake token outranks any zombie; the
        # restart budget still counts from zero
        self.initial_incarnation = int(initial_incarnation)
        self.max_restarts = (
            max_restarts_env() if max_restarts is None else int(max_restarts)
        )
        self.grace_s = grace_s
        self.backoff_s = backoff_s
        self.poll_s = poll_s
        self.log_dir = log_dir
        self.restarts_used = 0
        self.events: list[tuple[float, str, str]] = []
        self.last_codes: list[int | None] = []
        self._rng = random.Random(0xF0E1)
        self._stop = threading.Event()
        # Shard Flux: a pending live resize — (new rank count, reshard
        # callback) consumed by the run loop at the next poll
        self._resize: tuple[int, Callable[[], Any] | None] | None = None
        self._resize_ev = threading.Event()

    def resize(self, m: int, *, reshard: Callable[[], Any] | None = None):
        """Live elastic resize (Shard Flux): ask a running :meth:`run`
        loop to grow/shrink the group to ``m`` ranks WITHOUT the
        log-replay fallback.  The loop terminates the current group at
        its next poll (phase-1 freeze: DCN groups commit durably every
        lockstep tick, so the cut is a group-committed state), runs the
        ``reshard`` callback (the transfer phase — typically
        ``elastic.mesh.reshard_stores`` moving each arrangement's moved
        key ranges to their new owners' stores), then respawns ``m``
        ranks under a BUMPED incarnation (phase-2 commit: zombies of
        the old topology present a stale incarnation and are fenced by
        the existing checks).  A reshard callback that RAISES rolls the
        resize back: the old rank count respawns and the old committed
        state still rules — bounded pause either way.  The respawn does
        not consume the restart budget."""
        self._resize = (int(m), reshard)
        self._resize_ev.set()

    def _apply_resize(self, incarnation: int) -> int:
        """Run the transfer phase + commit the new size; returns the
        next incarnation (always bumped — even a rollback restarts the
        group, and stale ranks must be fenced)."""
        m, reshard = self._resize
        self._resize = None
        self._resize_ev.clear()
        old_n = self.n
        try:
            if reshard is not None:
                reshard()
            self.n = int(m)
            self._event("group-resize", f"{old_n} -> {self.n} ranks")
        except Exception as e:
            # rollback: the old ownership map was never superseded —
            # respawn at the old size and surface the cause
            self._event(
                "resize-rollback",
                f"reshard {old_n} -> {m} failed ({e}); staying at "
                f"{old_n} ranks",
            )
        return incarnation + 1

    def stop(self) -> None:
        """Ask a running :meth:`run` loop (e.g. on another thread — the
        replica supervisors in the chaos bench) to SIGTERM the current
        group and return.  A group whose ranks serve until terminated
        (read replicas) has no natural all-exited-0 end, so the owner
        drives shutdown explicitly."""
        self._stop.set()

    def _event(self, kind: str, detail: str) -> None:
        self.events.append((time.monotonic(), kind, detail))
        # mirror into the Fleet Lens incident journal — rank-died /
        # group-restart / group-resize are the supervisor's side of the
        # fleet timeline (rank-died persists: it is a peer's record of a
        # SIGKILLed member)
        from pathway_tpu.observability.journal import record as journal_record

        journal_record(
            f"group-{kind}" if not kind.startswith(("group", "rank")) else kind,
            detail,
            persist=kind in ("rank-died", "gave-up", "resize-rollback"),
        )

    def _spawn_group(self, incarnation: int) -> list[subprocess.Popen]:
        from pathway_tpu.internals.monitoring_server import BASE_PORT

        procs: list[subprocess.Popen] = []
        for pid in range(self.n):
            env = dict(os.environ)
            env.update(self.env)
            env["PATHWAY_PROCESSES"] = str(self.n)
            env["PATHWAY_PROCESS_ID"] = str(pid)
            env["PATHWAY_MESH_INCARNATION"] = str(incarnation)
            # Fleet Lens: every rank knows the whole group's monitoring
            # ports, so ANY rank's /fleet/* federates the group (an
            # explicit member map wins)
            env.setdefault(
                "PATHWAY_FLEET_MEMBERS",
                ",".join(
                    f"rank-{i}=http://127.0.0.1:{BASE_PORT + i}"
                    for i in range(self.n)
                ),
            )
            if self.rank_env is not None:
                env.update(self.rank_env(pid) or {})
            stdout = None
            if self.log_dir is not None:
                os.makedirs(self.log_dir, exist_ok=True)
                stdout = open(
                    os.path.join(
                        self.log_dir, f"rank{pid}-inc{incarnation}.log"
                    ),
                    "ab",
                )
            procs.append(
                subprocess.Popen(
                    self.argv,
                    env=env,
                    stdout=stdout,
                    stderr=subprocess.STDOUT if stdout is not None else None,
                )
            )
            if stdout is not None:
                stdout.close()  # the child holds its own fd now
        self._event("group-start", f"incarnation {incarnation}")
        return procs

    def _terminate(self, procs: list[subprocess.Popen]) -> None:
        """SIGTERM the survivors, escalate to SIGKILL after the grace
        period — a wedged rank must not block the restart."""
        for p in procs:
            if p.poll() is None:
                try:
                    p.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        deadline = time.monotonic() + self.grace_s
        for p in procs:
            while p.poll() is None and time.monotonic() < deadline:
                time.sleep(self.poll_s)
            if p.poll() is None:
                try:
                    p.kill()
                except OSError:
                    pass
                p.wait()

    def run(self) -> int:
        incarnation = self.initial_incarnation
        while True:
            procs = self._spawn_group(incarnation)
            failed: int | None = None
            resized = False
            while True:
                if self._stop.is_set():
                    self._terminate(procs)
                    self.last_codes = [p.returncode for p in procs]
                    self._event(
                        "group-stopped", f"incarnation {incarnation}"
                    )
                    return 0
                if self._resize_ev.is_set():
                    # phase-1 freeze: stop the group at this poll (each
                    # lockstep tick is durably committed, so the cut is
                    # a group-committed state), move state, respawn at
                    # the new size under a bumped incarnation
                    self._terminate(procs)
                    self.last_codes = [p.returncode for p in procs]
                    incarnation = self._apply_resize(incarnation)
                    resized = True
                    break
                codes = [p.poll() for p in procs]
                bad = [
                    (i, c) for i, c in enumerate(codes) if c not in (None, 0)
                ]
                if bad:
                    failed = bad[0][0]
                    self._event(
                        "rank-died",
                        f"rank {bad[0][0]} exited {bad[0][1]} "
                        f"(incarnation {incarnation})",
                    )
                    break
                if all(c == 0 for c in codes):
                    self.last_codes = codes
                    self._event("group-done", f"incarnation {incarnation}")
                    return 0
                time.sleep(self.poll_s)
            if resized:
                continue  # respawn at the new size, budget untouched
            self._terminate(procs)
            self.last_codes = [p.returncode for p in procs]
            if self.restarts_used >= self.max_restarts:
                self._event(
                    "gave-up",
                    f"restart budget exhausted "
                    f"({self.restarts_used}/{self.max_restarts}); rank "
                    f"{failed} last exit "
                    f"{self.last_codes[failed] if failed is not None else '?'}",
                )
                # propagate the code of the rank that CAUSED the
                # give-up — a survivor we ourselves SIGTERMed would
                # otherwise mask it with -15
                if (
                    failed is not None
                    and self.last_codes[failed] not in (0, None)
                ):
                    return self.last_codes[failed]
                return next(
                    (c for c in self.last_codes if c not in (0, None)), 1
                )
            self.restarts_used += 1
            incarnation += 1
            delay = min(5.0, self.backoff_s * (2 ** (self.restarts_used - 1)))
            delay *= 0.5 + self._rng.random()
            self._event(
                "group-restart",
                f"restart {self.restarts_used}/{self.max_restarts} in "
                f"{delay:.2f}s (incarnation {incarnation})",
            )
            time.sleep(delay)


def main(argv: list[str] | None = None) -> int:
    import argparse
    import secrets

    parser = argparse.ArgumentParser(
        prog="python -m pathway_tpu.parallel.supervisor",
        description="run an N-rank DCN group under the Phoenix Mesh "
        "restart supervisor",
    )
    parser.add_argument("--processes", "-n", type=int, default=2)
    parser.add_argument("--max-restarts", type=int, default=None)
    parser.add_argument("--log-dir", default=None)
    args, extra = parser.parse_known_args(argv)
    if extra and extra[0] == "--":
        extra = extra[1:]
    if not extra:
        print("nothing to run", file=sys.stderr)
        return 2
    env = {}
    if "PATHWAY_DCN_SECRET" not in os.environ:
        env["PATHWAY_DCN_SECRET"] = secrets.token_hex(32)
    sup = GroupSupervisor(
        extra,
        args.processes,
        env=env,
        max_restarts=args.max_restarts,
        log_dir=args.log_dir,
    )
    rc = sup.run()
    for ts, kind, detail in sup.events:
        print(f"[supervisor +{ts - sup.events[0][0]:8.3f}s] {kind}: {detail}")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
