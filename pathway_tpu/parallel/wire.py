"""Typed columnar wire codec for the DCN host mesh (PWHX6 frames).

The PWHX5 mesh shipped every cross-process hop as a raw ``pickle.dumps``
of full-width ``DiffBatch`` columns, so DCN bytes scaled with column
width and the whole frame serialized under the per-peer send lock.
This module replaces that with a typed, self-describing columnar
encoding in the spirit of EQuARX's block-quantized collectives
(PAPERS.md, https://arxiv.org/pdf/2506.17615):

- row keys (sorted-ish uint64) as zigzag-delta + LEB128 varint, with a
  raw fallback when the delta stream would be larger;
- diff weights (overwhelmingly +/-1) as a constant, a sign bitmap, or
  zigzag varints;
- numeric value columns as raw little-endian bytes, with an **opt-in**
  lossy tier (``PATHWAY_DCN_QUANT=bf16|int8``) for float columns —
  keys, diffs and every non-float column stay lossless always;
- object columns whose elements are uniform ndarrays (embedding rows,
  the TPU-KNN gather payload, https://arxiv.org/pdf/2206.14286) as one
  stacked raw block (bf16/int8-quantizable like flat float columns);
- any other object column falls back to a per-column pickle.

Every batch carries a self-describing header (row count, per-column
name + encoding tag + dtype), so decode needs no out-of-band schema.
Frame bodies start with a one-byte tag — ``P`` (whole-frame pickle,
the PWHX5 format and the ``PATHWAY_DCN_WIRE=pickle`` escape hatch) or
``C`` (codec data frame) — so both formats interoperate inside one
PWHX6 connection and barrier/scalar frames simply stay pickled.

All encoders/decoders are numpy-vectorized (no per-row Python on the
hot path); the varint codec uses a matrix-shift encode and a
reduceat-based decode.
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, Sequence

import numpy as np

from pathway_tpu.engine.batch import DiffBatch, uniform_element_spec

FRAME_PICKLE = b"P"  # body[1:] is pickle.dumps(frame)
FRAME_CODEC = b"C"  # body[1:] is the columnar encoding below
_CODEC_VERSION = 1

# keys section tags
_K_DELTA = 0  # zigzag(delta) varints
_K_RAW = 1  # raw little-endian uint64

# diffs section tags
_D_CONST = 0  # all rows share one value: a single int64
_D_SIGN = 1  # all rows in {+1, -1}: packbits(diff < 0)
_D_VARINT = 2  # zigzag varints

# column section tags
_C_RAW = 0  # dtype str + raw little-endian bytes
_C_PKL = 1  # pickle of the object ndarray
_C_STACK = 2  # uniform ndarray elements stacked into one raw block
_C_QUANT = 3  # quantized flat float column (bf16/int8 sub-tag)
_C_STACK_QUANT = 4  # quantized stacked ndarray elements
_C_INTV = 5  # integer column as (zigzag) varints — lossless, chosen
# only when actually smaller than raw (counts/ids hug zero)

_Q_BF16 = 0
_Q_INT8 = 1

_INT8_BLOCK = 1024  # per-block absmax scale granularity


class WireError(ValueError):
    """Malformed or unsupported wire bytes (authenticated frames only
    reach this decoder, so in practice this means a codec bug or a
    version skew the handshake failed to catch)."""


# --- varint / zigzag primitives (vectorized) -------------------------------

_SHIFTS = (np.arange(10, dtype=np.uint64) * np.uint64(7))[None, :]
_COLS = np.arange(10)[None, :]


def uvarint_encode(
    values: np.ndarray, max_bytes: int | None = None
) -> bytes | None:
    """LEB128-encode a uint64 array without a per-value Python loop:
    build a width-capped (n, wmax) byte matrix with vector shifts, then
    keep each value's leading ``nbytes`` entries via a boolean mask.
    With ``max_bytes`` set, returns None as soon as the encoded size
    would exceed it — callers use that to fall back to a raw encoding
    without paying for a doomed matrix build."""
    v = np.ascontiguousarray(values, dtype=np.uint64)
    n = v.shape[0]
    if n == 0:
        return b""
    vmax = int(v.max())
    if vmax < 128:  # dense fast path: sorted-key deltas, small counts
        if max_bytes is not None and n > max_bytes:
            return None
        return v.astype(np.uint8).tobytes()
    wmax = 1
    while wmax < 10 and vmax >= (1 << (7 * wmax)):
        wmax += 1
    nb = np.ones(n, dtype=np.int64)
    for j in range(1, wmax):
        nb += v >= (np.uint64(1) << np.uint64(7 * j))
    if max_bytes is not None and int(nb.sum()) > max_bytes:
        return None
    mat = ((v[:, None] >> _SHIFTS[:, :wmax]) & np.uint64(0x7F)).astype(
        np.uint8
    )
    mat |= (_COLS[:, :wmax] < (nb - 1)[:, None]).astype(np.uint8) << 7
    return mat[_COLS[:, :wmax] < nb[:, None]].tobytes()


def uvarint_decode(raw: np.ndarray, count: int) -> np.ndarray:
    """Decode ``count`` LEB128 values from a uint8 array holding exactly
    the varint section. Group boundaries come from the continuation
    bits; each group sums its shifted 7-bit payloads via reduceat."""
    if count == 0:
        if raw.size:
            raise WireError("varint section has trailing bytes")
        return np.empty(0, dtype=np.uint64)
    if raw.size == count:  # all single-byte (dense fast path)
        if (raw & 0x80).any():
            raise WireError("varint section does not hold the declared count")
        return raw.astype(np.uint64)
    term = (raw & 0x80) == 0
    ends = np.flatnonzero(term)
    if ends.size != count or ends[-1] != raw.size - 1:
        raise WireError("varint section does not hold the declared count")
    starts = np.empty(count, dtype=np.int64)
    starts[0] = 0
    starts[1:] = ends[:-1] + 1
    gid = np.cumsum(term) - term
    offsets = np.arange(raw.size, dtype=np.int64) - starts[gid]
    if offsets.size and int(offsets.max()) > 9:
        raise WireError("varint value longer than 10 bytes")
    payload = (raw & 0x7F).astype(np.uint64) << (
        offsets.astype(np.uint64) * np.uint64(7)
    )
    return np.add.reduceat(payload, starts)


# width-packing modes (1-byte prefix on every packed-uint section)
_PK_VARINT = 0
_PK_U8 = 1
_PK_U16 = 2
_PK_U32 = 3
_PK_U64 = 4
_PK_WIDTH = {_PK_U8: "<u1", _PK_U16: "<u2", _PK_U32: "<u4", _PK_U64: "<u8"}


def pack_uints(
    values: np.ndarray, max_bytes: int | None = None
) -> bytes | None:
    """Lossless uint64 sequence packing: one ``.max()`` picks the
    narrowest fixed byte width (a single astype — far cheaper than the
    varint matrix), falling back to LEB128 varints only for the skewed
    big-value case where per-value widths actually pay. A 1-byte mode
    prefix keeps the section self-describing. With ``max_bytes`` set,
    returns None instead of exceeding it (raw-fallback probe)."""
    v = np.ascontiguousarray(values, dtype=np.uint64)
    n = v.shape[0]
    if n == 0:
        return b"\x00"
    vmax = int(v.max())
    if vmax < 1 << 8:
        mode, width = _PK_U8, 1
    elif vmax < 1 << 16:
        mode, width = _PK_U16, 2
    elif vmax < 1 << 32:
        mode, width = _PK_U32, 4
    else:
        # mostly-small values with a huge outlier: varints; otherwise u64
        enc = uvarint_encode(
            v, max_bytes=None if max_bytes is None else max_bytes - 1
        )
        if enc is not None and len(enc) < 8 * n:
            return struct.pack("<B", _PK_VARINT) + enc
        mode, width = _PK_U64, 8
    if max_bytes is not None and 1 + width * n > max_bytes:
        return None
    return struct.pack("<B", mode) + v.astype(_PK_WIDTH[mode]).tobytes()


def unpack_uints(raw: np.ndarray, count: int) -> np.ndarray:
    """Inverse of :func:`pack_uints`; ``raw`` is the uint8 view of the
    whole section (mode prefix included)."""
    if raw.size < 1:
        raise WireError("empty packed-uint section")
    mode = int(raw[0])
    body = raw[1:]
    if count == 0:
        if body.size:
            raise WireError("packed-uint section has trailing bytes")
        return np.empty(0, dtype=np.uint64)
    if mode == _PK_VARINT:
        return uvarint_decode(body, count)
    dtype = _PK_WIDTH.get(mode)
    if dtype is None:
        raise WireError(f"unknown packed-uint mode {mode}")
    vals = np.frombuffer(body.tobytes(), dtype=dtype)
    if vals.shape[0] != count:
        raise WireError("packed-uint section length mismatch")
    return vals.astype(np.uint64)


def zigzag(x: np.ndarray) -> np.ndarray:
    x = np.ascontiguousarray(x, dtype=np.int64)
    return (x.view(np.uint64) << np.uint64(1)) ^ (
        x >> np.int64(63)
    ).view(np.uint64)


def unzigzag(z: np.ndarray) -> np.ndarray:
    z = np.ascontiguousarray(z, dtype=np.uint64)
    return ((z >> np.uint64(1)) ^ (np.uint64(0) - (z & np.uint64(1)))).view(
        np.int64
    )


# --- section encoders ------------------------------------------------------


def _encode_keys(keys: np.ndarray) -> tuple[int, bytes]:
    k = np.ascontiguousarray(keys, dtype=np.uint64)
    n = k.shape[0]
    if n == 0:
        return _K_DELTA, b""
    deltas = np.empty(n, dtype=np.uint64)
    deltas[0] = k[0]
    deltas[1:] = k[1:] - k[:-1]  # mod-2^64 wrap keeps decode exact
    enc = pack_uints(zigzag(deltas.view(np.int64)), max_bytes=8 * n - 1)
    if enc is None:  # adversarially unsorted keys: raw is smaller
        return _K_RAW, k.tobytes()
    return _K_DELTA, enc


def _decode_keys(tag: int, raw: bytes, n: int) -> np.ndarray:
    if n == 0:
        if raw:
            raise WireError("key section has trailing bytes")
        return np.empty(0, dtype=np.uint64)
    if tag == _K_RAW:
        if len(raw) != 8 * n:
            raise WireError("raw key section length mismatch")
        return np.frombuffer(raw, dtype="<u8").astype(np.uint64)
    if tag != _K_DELTA:
        raise WireError(f"unknown key encoding {tag}")
    deltas = unzigzag(
        unpack_uints(np.frombuffer(raw, dtype=np.uint8), n)
    ).view(np.uint64)
    return np.cumsum(deltas, dtype=np.uint64)


def _encode_diffs(diffs: np.ndarray) -> tuple[int, bytes]:
    d = np.ascontiguousarray(diffs, dtype=np.int64)
    n = d.shape[0]
    if n == 0:
        return _D_VARINT, b""
    if bool((d == d[0]).all()):
        return _D_CONST, struct.pack("<q", int(d[0]))
    if bool((np.abs(d) == 1).all()):
        return _D_SIGN, np.packbits(d < 0).tobytes()
    return _D_VARINT, pack_uints(zigzag(d))


def _decode_diffs(tag: int, raw: bytes, n: int) -> np.ndarray:
    if n == 0:
        if raw:
            raise WireError("diff section has trailing bytes")
        return np.empty(0, dtype=np.int64)
    if tag == _D_CONST:
        (value,) = struct.unpack("<q", raw)
        return np.full(n, value, dtype=np.int64)
    if tag == _D_SIGN:
        bits = np.unpackbits(np.frombuffer(raw, dtype=np.uint8), count=n)
        return (1 - 2 * bits.astype(np.int64)).astype(np.int64)
    if tag == _D_VARINT:
        return unzigzag(
            unpack_uints(np.frombuffer(raw, dtype=np.uint8), n)
        ).copy()
    raise WireError(f"unknown diff encoding {tag}")


# --- float quantization (opt-in lossy tier) --------------------------------


def _bf16_pack(f32: np.ndarray) -> np.ndarray:
    """Round-to-nearest-even truncation float32 -> bf16 (uint16). NaNs
    keep their exponent and a set mantissa bit so the carry-add cannot
    walk a NaN payload into an infinity or flip its sign."""
    u = np.ascontiguousarray(f32, dtype=np.float32).view(np.uint32)
    rnd = (u >> np.uint32(16)) & np.uint32(1)
    packed = ((u + np.uint32(0x7FFF) + rnd) >> np.uint32(16)).astype(
        np.uint16
    )
    nan = np.isnan(f32)
    if nan.any():
        packed = np.where(
            nan, ((u >> np.uint32(16)) | np.uint32(0x40)).astype(np.uint16),
            packed,
        )
    return packed


def _quantize(arr: np.ndarray, quant: str) -> bytes | None:
    """Quantize a float array (any shape); None means "stay lossless"
    (unknown mode, or int8 asked for non-finite data)."""
    flat = np.ascontiguousarray(arr).reshape(-1)
    if quant == "bf16":
        packed = _bf16_pack(flat.astype(np.float32, copy=False))
        return struct.pack("<B", _Q_BF16) + _dtype_header(arr.dtype) + (
            packed.tobytes()
        )
    if quant == "int8":
        if not bool(np.isfinite(flat).all()):
            return None  # inf/nan cannot ride an absmax scale
        f32 = flat.astype(np.float32, copy=False)
        n = f32.shape[0]
        nblocks = max(1, -(-n // _INT8_BLOCK))
        padded = np.zeros(nblocks * _INT8_BLOCK, dtype=np.float32)
        padded[:n] = f32
        blocks = padded.reshape(nblocks, _INT8_BLOCK)
        scales = np.abs(blocks).max(axis=1) / np.float32(127.0)
        scales[scales == 0] = 1.0
        q = np.clip(
            np.rint(blocks / scales[:, None]), -127, 127
        ).astype(np.int8)
        return (
            struct.pack("<B", _Q_INT8)
            + _dtype_header(arr.dtype)
            + struct.pack("<QI", n, nblocks)
            + scales.astype("<f4").tobytes()
            + q.reshape(-1)[:n].tobytes()
        )
    return None


def _dequantize(raw: memoryview) -> np.ndarray:
    """Inverse of :func:`_quantize`; returns a flat array in the
    original dtype."""
    (qkind,) = struct.unpack_from("<B", raw, 0)
    dtype, off = _read_dtype(raw, 1)
    if qkind == _Q_BF16:
        u16 = np.frombuffer(raw[off:], dtype="<u2").astype(np.uint32)
        return (u16 << np.uint32(16)).view(np.float32).astype(dtype)
    if qkind == _Q_INT8:
        n, nblocks = struct.unpack_from("<QI", raw, off)
        off += 12
        scales = np.frombuffer(
            raw[off : off + 4 * nblocks], dtype="<f4"
        ).astype(np.float32)
        off += 4 * nblocks
        q = np.frombuffer(raw[off:], dtype=np.int8)
        if q.shape[0] != n:
            raise WireError("int8 section length mismatch")
        padded = np.zeros(nblocks * _INT8_BLOCK, dtype=np.float32)
        padded[:n] = q.astype(np.float32)
        out = (padded.reshape(nblocks, _INT8_BLOCK) * scales[:, None]).reshape(
            -1
        )[:n]
        return out.astype(dtype)
    raise WireError(f"unknown quantization kind {qkind}")


def _quantizable(dtype: np.dtype) -> bool:
    return dtype.kind == "f" and dtype.itemsize >= 4


# --- column encoders -------------------------------------------------------


def _dtype_header(dtype: np.dtype) -> bytes:
    ds = np.dtype(dtype).str.encode("ascii")
    return struct.pack("<H", len(ds)) + ds


def _read_dtype(raw: memoryview, off: int) -> tuple[np.dtype, int]:
    (dlen,) = struct.unpack_from("<H", raw, off)
    off += 2
    dtype = np.dtype(bytes(raw[off : off + dlen]).decode("ascii"))
    return dtype, off + dlen


def _shape_header(shape: tuple[int, ...]) -> bytes:
    return struct.pack("<B", len(shape)) + b"".join(
        struct.pack("<I", dim) for dim in shape
    )


def _read_shape(raw: memoryview, off: int) -> tuple[tuple[int, ...], int]:
    (ndim,) = struct.unpack_from("<B", raw, off)
    off += 1
    shape = struct.unpack_from(f"<{ndim}I", raw, off) if ndim else ()
    return tuple(shape), off + 4 * ndim


def _encode_column(col: np.ndarray, quant: str | None) -> tuple[int, bytes]:
    if col.dtype != object:
        arr = np.ascontiguousarray(col)
        if quant and _quantizable(arr.dtype):
            qp = _quantize(arr, quant)
            if qp is not None:
                return _C_QUANT, qp
        if arr.dtype.kind in "iu" and arr.dtype.itemsize >= 2:
            z = (
                zigzag(arr.astype(np.int64, copy=False))
                if arr.dtype.kind == "i"
                else arr.astype(np.uint64, copy=False)
            )
            enc = pack_uints(z, max_bytes=arr.nbytes - 1)
            if enc is not None:
                return _C_INTV, _dtype_header(arr.dtype) + enc
        return _C_RAW, _dtype_header(arr.dtype) + arr.tobytes()
    spec = uniform_element_spec(col)
    if spec is not None:
        edtype, shape = spec
        stacked = np.ascontiguousarray(
            np.stack(col.tolist()), dtype=edtype
        )
        header = _shape_header(shape)
        if quant and _quantizable(stacked.dtype):
            qp = _quantize(stacked, quant)
            if qp is not None:
                return _C_STACK_QUANT, header + qp
        return _C_STACK, header + _dtype_header(edtype) + stacked.tobytes()
    return _C_PKL, pickle.dumps(col, protocol=pickle.HIGHEST_PROTOCOL)


def _rows_to_object(stacked: np.ndarray) -> np.ndarray:
    out = np.empty(stacked.shape[0], dtype=object)
    for i in range(stacked.shape[0]):
        out[i] = stacked[i]
    return out


def _decode_column(tag: int, raw: memoryview, n: int) -> np.ndarray:
    if tag == _C_RAW:
        dtype, off = _read_dtype(raw, 0)
        arr = np.frombuffer(raw[off:], dtype=dtype)
        if arr.shape[0] != n:
            raise WireError("raw column length mismatch")
        return arr.copy()  # decoded batches must be writable
    if tag == _C_PKL:
        col = pickle.loads(raw)
        if len(col) != n:
            raise WireError("pickled column length mismatch")
        return col
    if tag == _C_STACK:
        shape, off = _read_shape(raw, 0)
        dtype, off = _read_dtype(raw, off)
        flat = np.frombuffer(raw[off:], dtype=dtype)
        return _rows_to_object(flat.reshape((n,) + shape).copy())
    if tag == _C_INTV:
        dtype, off = _read_dtype(raw, 0)
        z = unpack_uints(np.frombuffer(raw[off:], dtype=np.uint8), n)
        vals = unzigzag(z) if dtype.kind == "i" else z
        return vals.astype(dtype)
    if tag == _C_QUANT:
        out = _dequantize(raw)
        if out.shape[0] != n:
            raise WireError("quantized column length mismatch")
        return out
    if tag == _C_STACK_QUANT:
        shape, off = _read_shape(raw, 0)
        flat = _dequantize(raw[off:])
        return _rows_to_object(flat.reshape((n,) + shape))
    raise WireError(f"unknown column encoding {tag}")


# --- batch / frame encoding ------------------------------------------------


def _encode_batch(b: DiffBatch, quant: str | None, out: list[bytes]) -> int:
    """Append one batch's sections to ``out``; returns the batch's
    dense in-memory byte size (typed columns at full width, object
    columns at their wire size) for the compression-ratio gauge."""
    n = len(b)
    cols = b.columns
    out.append(struct.pack("<IH", n, len(cols)))
    ktag, kraw = _encode_keys(b.keys)
    out.append(struct.pack("<BI", ktag, len(kraw)))
    out.append(kraw)
    dtag, draw = _encode_diffs(b.diffs)
    out.append(struct.pack("<BI", dtag, len(draw)))
    out.append(draw)
    raw_bytes = 16 * n  # uint64 keys + int64 diffs at full width
    for name, col in cols.items():
        nb = name.encode("utf-8")
        ctag, craw = _encode_column(col, quant)
        out.append(struct.pack("<H", len(nb)))
        out.append(nb)
        out.append(struct.pack("<BI", ctag, len(craw)))
        out.append(craw)
        raw_bytes += _column_raw_nbytes(col, ctag, len(craw))
    return raw_bytes


def _column_raw_nbytes(col: np.ndarray, ctag: int, wire_len: int) -> int:
    if col.dtype != object:
        return col.nbytes
    if ctag in (_C_STACK, _C_STACK_QUANT) and len(col):
        return int(col[0].nbytes) * len(col)
    # mixed/ragged object columns ship as pickle either way: count their
    # wire size so the ratio reflects savings on the typed parts only
    return wire_len


def _decode_batch(raw: memoryview, off: int) -> tuple[DiffBatch, int]:
    n, ncols = struct.unpack_from("<IH", raw, off)
    off += 6
    ktag, klen = struct.unpack_from("<BI", raw, off)
    off += 5
    keys = _decode_keys(ktag, bytes(raw[off : off + klen]), n)
    off += klen
    dtag, dlen = struct.unpack_from("<BI", raw, off)
    off += 5
    diffs = _decode_diffs(dtag, bytes(raw[off : off + dlen]), n)
    off += dlen
    columns: dict[str, np.ndarray] = {}
    for _ in range(ncols):
        (nlen,) = struct.unpack_from("<H", raw, off)
        off += 2
        name = bytes(raw[off : off + nlen]).decode("utf-8")
        off += nlen
        ctag, clen = struct.unpack_from("<BI", raw, off)
        off += 5
        columns[name] = _decode_column(ctag, raw[off : off + clen], n)
        off += clen
    return DiffBatch(keys, diffs, columns), off


def is_batch_list(payload: Any) -> bool:
    return isinstance(payload, list) and all(
        isinstance(b, DiffBatch) for b in payload
    )


def encode_frame(
    frame: tuple, wire: str = "codec", quant: str | None = None
) -> tuple[bytes, dict | None]:
    """Encode one mesh frame into a tagged body. Data frames whose
    payload is a list of DiffBatches take the columnar path when
    ``wire == "codec"``; everything else (barriers, scalar exchanges,
    ``wire == "pickle"``) stays a whole-frame pickle. Returns
    ``(body, stats)`` where stats (codec frames only) carries
    ``raw_bytes``/``rows`` for the compression-ratio gauge."""
    if wire == "codec" and frame[0] == "data" and is_batch_list(frame[4]):
        _kind, src, channel, tick, batches, tp = frame
        chan = channel.encode("utf-8")
        tpb = tp.encode("utf-8") if tp is not None else None
        parts: list[bytes] = [
            FRAME_CODEC,
            struct.pack("<Biq", _CODEC_VERSION, src, tick),
            struct.pack("<H", len(chan)),
            chan,
            struct.pack("<B", 1 if tpb is not None else 0),
        ]
        if tpb is not None:
            parts.append(struct.pack("<H", len(tpb)))
            parts.append(tpb)
        parts.append(struct.pack("<H", len(batches)))
        raw_bytes = 0
        rows = 0
        for b in batches:
            raw_bytes += _encode_batch(b, quant, parts)
            rows += len(b)
        return b"".join(parts), {"raw_bytes": raw_bytes, "rows": rows}
    return (
        FRAME_PICKLE
        + pickle.dumps(frame, protocol=pickle.HIGHEST_PROTOCOL),
        None,
    )


def tap_frame(channel: str, wire_bytes: int, stats: dict | None) -> None:
    """Report one encoded data frame to Tick Scope's per-channel byte
    ledger (observability/tickscope.py). Callers (the mesh sender loop,
    replication shippers) pass the encoded body length plus the codec
    stats from :func:`encode_frame`; pickle frames carry no row count.
    Best-effort — accounting must never fail a send."""
    try:
        from pathway_tpu.observability import tickscope

        tickscope.wire_tap(
            channel,
            wire_bytes,
            raw_bytes=(stats or {}).get("raw_bytes", 0),
            rows=(stats or {}).get("rows", 0),
        )
    except Exception:  # pragma: no cover - defensive
        pass


def decode_frame(body: bytes) -> tuple:
    """Inverse of :func:`encode_frame`; returns the mesh frame tuple."""
    tag = body[:1]
    if tag == FRAME_PICKLE:
        return pickle.loads(memoryview(body)[1:])
    if tag != FRAME_CODEC:
        raise WireError(f"unknown frame tag {tag!r}")
    raw = memoryview(body)
    version, src, tick = struct.unpack_from("<Biq", raw, 1)
    if version != _CODEC_VERSION:
        raise WireError(f"unsupported codec version {version}")
    off = 1 + struct.calcsize("<Biq")
    (clen,) = struct.unpack_from("<H", raw, off)
    off += 2
    channel = bytes(raw[off : off + clen]).decode("utf-8")
    off += clen
    (tp_flag,) = struct.unpack_from("<B", raw, off)
    off += 1
    tp = None
    if tp_flag:
        (tlen,) = struct.unpack_from("<H", raw, off)
        off += 2
        tp = bytes(raw[off : off + tlen]).decode("utf-8")
        off += tlen
    (nbatches,) = struct.unpack_from("<H", raw, off)
    off += 2
    batches: list[DiffBatch] = []
    for _ in range(nbatches):
        b, off = _decode_batch(raw, off)
        batches.append(b)
    if off != len(body):
        raise WireError("trailing bytes after last batch")
    return ("data", src, channel, tick, batches, tp)


def batches_equal(a: Sequence[DiffBatch], b: Sequence[DiffBatch]) -> bool:
    """Bit-exact comparison helper for differential tests: same batch
    count, keys, diffs, column names, dtypes and values."""
    if len(a) != len(b):
        return False
    for x, y in zip(a, b):
        if (
            not np.array_equal(x.keys, y.keys)
            or not np.array_equal(x.diffs, y.diffs)
            or x.column_names != y.column_names
        ):
            return False
        for name in x.column_names:
            cx, cy = x.columns[name], y.columns[name]
            if cx.dtype != cy.dtype:
                return False
            if cx.dtype == object:
                from pathway_tpu.engine.batch import _values_eq

                if not _values_eq(tuple(cx), tuple(cy)):
                    return False
            elif not np.array_equal(
                cx, cy, equal_nan=cx.dtype.kind in "fc"
            ):
                return False
    return True
