"""Device mesh management.

TPU-native replacement for the reference's worker/process topology
(reference: src/engine/dataflow/config.rs:63-121 — PATHWAY_THREADS ×
PATHWAY_PROCESSES workers over TCP): scaling out means adding mesh devices,
not OS processes. The 'data' axis carries the key-shard dimension (the analog
of the reference's 16-bit key shards, src/engine/value.rs:38)."""

from __future__ import annotations

import os
from typing import Any, Sequence

import numpy as np


_default_mesh: Any = None


def make_mesh(
    n_devices: int | None = None,
    axis_names: Sequence[str] = ("data",),
    *,
    backend: str | None = None,
):
    """Build a Mesh over available devices. Falls back to the virtual CPU
    device pool (xla_force_host_platform_device_count) when the accelerator
    has fewer devices than requested — how unit tests and the driver's
    dryrun exercise multi-chip code paths on one host."""
    import jax
    from jax.sharding import Mesh

    if backend is not None:
        devices = jax.devices(backend)
    else:
        devices = jax.devices()
        if n_devices is not None and len(devices) < n_devices:
            try:
                cpu = jax.devices("cpu")
                if len(cpu) >= n_devices:
                    devices = cpu
            except RuntimeError:
                pass
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"requested {n_devices} devices, have {len(devices)}"
            )
        devices = devices[:n_devices]
    shape = _factor_shape(len(devices), len(axis_names))
    dev_array = np.array(devices).reshape(shape)
    return Mesh(dev_array, axis_names=tuple(axis_names))


def _factor_shape(n: int, n_axes: int) -> tuple[int, ...]:
    if n_axes == 1:
        return (n,)
    # put everything on the first axis by default; callers wanting tp×dp
    # meshes pass explicit shapes via Mesh directly
    return (n,) + (1,) * (n_axes - 1)


def set_default_mesh(mesh: Any) -> None:
    global _default_mesh
    _default_mesh = mesh


def get_mesh() -> Any:
    return _default_mesh
