"""Device mesh management.

TPU-native replacement for the reference's worker/process topology
(reference: src/engine/dataflow/config.rs:63-121 — PATHWAY_THREADS ×
PATHWAY_PROCESSES workers over TCP): scaling out means adding mesh devices,
not OS processes. The 'data' axis carries the key-shard dimension (the analog
of the reference's 16-bit key shards, src/engine/value.rs:38)."""

from __future__ import annotations

import os
from typing import Any, Sequence

import numpy as np


_default_mesh: Any = None


def make_mesh(
    n_devices: int | None = None,
    axis_names: Sequence[str] = ("data",),
    *,
    backend: str | None = None,
):
    """Build a Mesh over available devices. Falls back to the virtual CPU
    device pool (xla_force_host_platform_device_count) when the accelerator
    has fewer devices than requested — how unit tests and the driver's
    dryrun exercise multi-chip code paths on one host."""
    import jax
    from jax.sharding import Mesh

    if backend is not None:
        devices = jax.devices(backend)
    else:
        # LOCAL devices only: the engine mesh (host-driven per-tick
        # device_put/np.asarray round trips) must never include another
        # process's non-addressable devices; cross-process meshes are
        # built explicitly via parallel.distributed.global_mesh
        devices = jax.local_devices()
        if n_devices is not None and len(devices) < n_devices:
            try:
                cpu = jax.devices("cpu")
                if len(cpu) >= n_devices:
                    devices = cpu
            except RuntimeError:
                pass
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"requested {n_devices} devices, have {len(devices)}"
            )
        devices = devices[:n_devices]
    shape = _factor_shape(len(devices), len(axis_names))
    dev_array = np.array(devices).reshape(shape)
    return Mesh(dev_array, axis_names=tuple(axis_names))


def _factor_shape(n: int, n_axes: int) -> tuple[int, ...]:
    if n_axes == 1:
        return (n,)
    # put everything on the first axis by default; callers wanting tp×dp
    # meshes pass explicit shapes via Mesh directly
    return (n,) + (1,) * (n_axes - 1)


def set_default_mesh(mesh: Any) -> None:
    global _default_mesh
    _default_mesh = mesh


def get_mesh() -> Any:
    return _default_mesh


# --- engine mesh ------------------------------------------------------------
# When set, STATEFUL ENGINE OPERATORS themselves shard over the mesh (per-
# shard keyed state + all-to-all exchange, engine/sharded.py) — the analog of
# the reference's PATHWAY_THREADS worker count (config.rs:88-121). Activated
# explicitly via set_engine_mesh() or by the PATHWAY_ENGINE_SHARDS env var
# (which `pathway spawn -n N` sets instead of forking redundant processes).

_engine_mesh: Any = None
_engine_mesh_resolved = False


def set_engine_mesh(mesh: Any, axis: str = "data") -> None:
    """Enable (or disable with mesh=None) engine-level key sharding."""
    global _engine_mesh, _engine_mesh_resolved
    _engine_mesh = (mesh, axis) if mesh is not None else None
    _engine_mesh_resolved = True


def get_engine_mesh() -> tuple[Any, str] | None:
    global _engine_mesh, _engine_mesh_resolved
    if not _engine_mesh_resolved:
        _engine_mesh_resolved = True
        n = os.environ.get("PATHWAY_ENGINE_SHARDS", "")
        if n.isdigit() and int(n) > 1:
            try:
                _engine_mesh = (make_mesh(int(n)), "data")
            except (ValueError, RuntimeError) as exc:
                # not enough devices on this host (e.g. the launcher didn't
                # set xla_force_host_platform_device_count) — run unsharded
                # rather than crash the pipeline at graph build
                import logging

                logging.getLogger("pathway_tpu").warning(
                    "PATHWAY_ENGINE_SHARDS=%s but no %s-device mesh is "
                    "available (%s); engine sharding disabled",
                    n,
                    n,
                    exc,
                )
    return _engine_mesh
