"""Universes: identity of key sets
(reference: python/pathway/internals/universe.py + universe_solver.py).

Tracked structurally: operations that keep keys share the Universe object;
subset/superset promises are recorded but enforcement is best-effort (the
reference solves these with a constraint solver; here they gate the same API
surface)."""

from __future__ import annotations

import itertools

_counter = itertools.count()


class Universe:
    __slots__ = ("id", "parent", "_disjoint")

    def __init__(self, parent: "Universe | None" = None):
        self.id = next(_counter)
        self.parent = parent
        self._disjoint: set[int] = set()  # ids promised disjoint from this

    def promise_disjoint(self, other: "Universe") -> None:
        self._disjoint.add(other.id)
        other._disjoint.add(self.id)

    def is_disjoint_from(self, other: "Universe") -> bool:
        # a subset of a promised-disjoint universe is still disjoint:
        # check every ancestor pair
        a: Universe | None = self
        while a is not None:
            b: Universe | None = other
            while b is not None:
                if b.id in a._disjoint:
                    return True
                b = b.parent
            a = a.parent
        return False

    def subset(self) -> "Universe":
        return Universe(parent=self)

    def is_subset_of(self, other: "Universe") -> bool:
        u: Universe | None = self
        while u is not None:
            if u is other:
                return True
            u = u.parent
        return False

    def __repr__(self) -> str:
        return f"U{self.id}"
