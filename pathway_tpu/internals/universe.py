"""Universes: identity of key sets
(reference: python/pathway/internals/universe.py + universe_solver.py).

Tracked structurally: operations that keep keys share the Universe object;
subset/superset promises are recorded but enforcement is best-effort (the
reference solves these with a constraint solver; here they gate the same API
surface)."""

from __future__ import annotations

import itertools

_counter = itertools.count()


class Universe:
    __slots__ = ("id", "parent")

    def __init__(self, parent: "Universe | None" = None):
        self.id = next(_counter)
        self.parent = parent

    def subset(self) -> "Universe":
        return Universe(parent=self)

    def is_subset_of(self, other: "Universe") -> bool:
        u: Universe | None = self
        while u is not None:
            if u is other:
                return True
            u = u.parent
        return False

    def __repr__(self) -> str:
        return f"U{self.id}"
