"""Joins: JoinResult and friends
(reference: python/pathway/internals/joins.py:135; engine join_tables,
src/engine/dataflow.rs:2740)."""

from __future__ import annotations

from enum import Enum
from typing import Any, Sequence

from pathway_tpu.engine import nodes
from pathway_tpu.engine.expression_eval import InternalColRef
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import (
    CoalesceExpression,
    ColumnBinaryOpExpression,
    ColumnExpression,
    ColumnReference,
    wrap_expr,
)
from pathway_tpu.internals.thisclass import (
    ThisPlaceholder,
    ThisSlice,
    left as left_ph,
    right as right_ph,
    this as this_ph,
)
from pathway_tpu.internals.universe import Universe


class JoinMode(Enum):
    INNER = "inner"
    LEFT = "left"
    RIGHT = "right"
    OUTER = "outer"


class JoinResult:
    """Lazy join: holds both sides + conditions; `select` / `reduce`
    materialize."""

    def __init__(
        self,
        left,
        right,
        on: Sequence[Any],
        mode: JoinMode,
        id_expr: Any = None,
    ):
        if left is right:
            raise ValueError(
                "Cannot join table with itself. Use <table>.copy() as one "
                "of the arguments of the join."
            )
        self._left = left
        self._right = right
        self._mode = mode if isinstance(mode, JoinMode) else JoinMode(mode)
        self._join_mode = self._mode  # reference-public spelling
        self._id_expr = id_expr
        if id_expr is not None and not (
            isinstance(id_expr, ColumnReference)
            and id_expr.name == "id"
            and id_expr.table in (left, right, left_ph, right_ph)
        ):
            # AssertionError to match the reference's contract, raised
            # explicitly so python -O cannot strip it
            raise AssertionError(
                "join id= must be the id column of one side "
                "(left.id or right.id)"
            )
        if id_expr is not None:
            id_is_left = id_expr.table in (left, left_ph)
            # the id side must be preserved by the join mode, or padded
            # rows have no id (reference: KeyError at build)
            if (
                self._mode == JoinMode.OUTER
                or (self._mode == JoinMode.LEFT and not id_is_left)
                or (self._mode == JoinMode.RIGHT and id_is_left)
            ):
                raise KeyError(
                    "join id= side is not preserved by this join mode: "
                    "padded rows would have no id"
                )
        self._left_on: list[ColumnExpression] = []
        self._right_on: list[ColumnExpression] = []
        for cond in on:
            l_e, r_e = self._split_condition(cond)
            self._left_on.append(l_e)
            self._right_on.append(r_e)
        if not isinstance(left, JoinResult) and not isinstance(
            right, JoinResult
        ):
            from pathway_tpu.stdlib.temporal.utils import (
                validate_join_condition_types,
            )

            validate_join_condition_types(
                left, right, self._left_on, self._right_on
            )

    # --- condition handling ---------------------------------------------------

    def _side_of(self, e: ColumnExpression) -> str | None:
        side = None
        for ref in e._dependencies():
            tbl = ref.table
            if tbl is self._left or tbl is left_ph:
                s = "l"
            elif tbl is self._right or tbl is right_ph:
                s = "r"
            elif isinstance(tbl, ThisPlaceholder):
                s = None
            elif (id(tbl), ref.name) in getattr(
                self._left, "_join_aliases", {}
            ):
                s = "l"  # a table folded into the left side by a chained join
            elif (id(tbl), ref.name) in getattr(
                self._right, "_join_aliases", {}
            ):
                s = "r"
            else:
                # resolve tables same-universe: assume left
                s = "l" if tbl._universe is self._left._universe else "r"
            if s is not None:
                if side is None:
                    side = s
                elif side != s:
                    raise ValueError(
                        "join condition side mixes left and right columns"
                    )
        return side

    def _split_condition(self, cond: Any):
        if not (
            isinstance(cond, ColumnBinaryOpExpression) and cond._op == "=="
        ):
            raise ValueError(
                f"join condition must be <left-expr> == <right-expr>, got {cond!r}"
            )
        a, b = cond._left, cond._right
        sa, sb = self._side_of(a), self._side_of(b)
        if sa == "r" or sb == "l":
            # reference rejects swapped conditions outright: the left
            # operand must come from the left table
            raise ValueError(
                "join condition sides are swapped: write "
                "<left-col> == <right-col>"
            )
        from pathway_tpu.internals.table import desugar

        l_e = desugar(a, {left_ph: self._left, this_ph: self._left})
        r_e = desugar(b, {right_ph: self._right, this_ph: self._right})

        def apply_aliases(e, table):
            aliases = getattr(table, "_join_aliases", None)
            if not aliases:
                return e

            def sub(ref):
                al = aliases.get((id(ref.table), ref.name))
                if al is not None:
                    return table[al]
                return None

            return wrap_expr(e)._substitute(sub)

        return apply_aliases(l_e, self._left), apply_aliases(r_e, self._right)

    # --- materialization ------------------------------------------------------

    def _build(self):
        from pathway_tpu.internals.table import Table

        lnames = [f"_on{i}" for i in range(len(self._left_on))]
        left_cols = {n: self._left[n] for n in self._left.column_names()}
        left_prep = self._left._build_rowwise(
            {**left_cols, **dict(zip(lnames, self._left_on))}
        )
        right_cols = {n: self._right[n] for n in self._right.column_names()}
        right_prep = self._right._build_rowwise(
            {**right_cols, **dict(zip(lnames, self._right_on))}
        )
        id_from = None
        if self._id_expr is not None:
            ref = self._id_expr
            if isinstance(ref, ColumnReference):
                if ref.table is self._left or ref.table is left_ph:
                    id_from = "left"
                elif ref.table is self._right or ref.table is right_ph:
                    id_from = "right"
        node = nodes.JoinNode(
            left_prep._node,
            right_prep._node,
            lnames,
            lnames,
            self._mode.value,
            id_from=id_from,
        )
        return node, left_prep, right_prep

    def select(self, *args: Any, **kwargs: Any):
        joined, sub = self._joined_with_sub()

        exprs: dict[str, ColumnExpression] = {}

        def add_side(table, prefix):
            for n in table.column_names():
                if n.startswith("_on") or n.startswith("_pw_"):
                    continue
                exprs[n] = ColumnReference(joined, prefix + n)

        def add_slice(sl: ThisSlice):
            if sl._parent is right_ph:
                sides = [self._right]
            elif sl._parent is left_ph:
                sides = [self._left]
            else:  # pw.this: both sides, right winning collisions like
                sides = [self._left, self._right]  # bare pw.this expansion
            for side in sides:
                for out_name, ref in sl.resolve(side).items():
                    exprs[out_name] = ref

        for arg in args:
            if isinstance(arg, ThisSlice):
                add_slice(arg)
            elif isinstance(arg, ThisPlaceholder):
                add_side(self._left, "l.")
                add_side(self._right, "r.")
            elif isinstance(arg, ColumnReference):
                exprs[arg.name] = arg
            else:
                raise TypeError(arg)
        for name, e in kwargs.items():
            if isinstance(e, ThisSlice):  # `**pw.left.without(...)` etc.
                add_slice(e)
                continue
            if isinstance(e, ThisPlaceholder):  # `**pw.left` expansion
                if e is left_ph or e is this_ph:
                    add_side(self._left, "l.")
                if e is right_ph or e is this_ph:
                    add_side(self._right, "r.")
                continue
            exprs[name] = wrap_expr(e)

        resolved = {n: wrap_expr(e)._substitute(sub) for n, e in exprs.items()}
        return joined.select(**resolved)

    @property
    def _universe(self) -> Universe:
        joined, _sub = self._joined_with_sub()
        return joined._universe

    def _result_universe(self) -> Universe:
        """Universe of the joined table: fresh by default; with id= the
        keys come from one side, so the result lives in (a subset of) that
        side's universe — LEFT join keyed by left.id covers every left
        row and keeps the full universe."""
        ref = self._id_expr
        if isinstance(ref, ColumnReference):
            if ref.table is self._left or ref.table is left_ph:
                side, side_is_left = self._left, True
            else:
                side, side_is_left = self._right, False
            side_u = getattr(side, "_universe", None)
            if side_u is not None:
                keeps_all = (
                    self._mode == JoinMode.LEFT and side_is_left
                ) or (self._mode == JoinMode.RIGHT and not side_is_left)
                return side_u if keeps_all else side_u.subset()
        return Universe()

    def promise_universe_is_subset_of(self, other) -> "JoinResult":
        return self

    def promise_universes_are_equal(self, other) -> "JoinResult":
        return self

    def promise_universe_is_equal_to(self, other) -> "JoinResult":
        return self

    def _maybe_opt(self, d: dt.DType, side: str) -> dt.DType:
        m = self._mode
        if side == "l" and m in (JoinMode.RIGHT, JoinMode.OUTER):
            return dt.Optional_(d)
        if side == "r" and m in (JoinMode.LEFT, JoinMode.OUTER):
            return dt.Optional_(d)
        return d

    def _joined_with_sub(self):
        """Materialize the join with all columns of both sides, plus a
        substitution function mapping left/right/this references onto it."""
        if hasattr(self, "_joined_cache"):
            joined = self._joined_cache
            return joined, self._make_sub(joined)
        from pathway_tpu.internals.table import Table

        node, left_prep, right_prep = self._build()
        joined = Table._from_node(
            node,
            {
                **{
                    "l." + n: self._maybe_opt(
                        left_prep._schema[n].dtype, side="l"
                    )
                    for n in left_prep.column_names()
                },
                **{
                    "r." + n: self._maybe_opt(
                        right_prep._schema[n].dtype, side="r"
                    )
                    for n in right_prep.column_names()
                },
                "_left_id": dt.Optional_(dt.POINTER),
                "_right_id": dt.Optional_(dt.POINTER),
                # nodes may append synthetic result columns past the ids
                # (e.g. the asof join's _pw_self_t) — typed ANY
                **{
                    n: dt.ANY
                    for n in node.column_names
                    if not n.startswith(("l.", "r."))
                    and n not in ("_left_id", "_right_id")
                },
            },
            self._result_universe(),
        )
        self._joined_cache = joined
        return joined, self._make_sub(joined)

    def _equated_names(self) -> set[str]:
        """Column names equated by the join condition (l.x == r.x): safe to
        reference through pw.this even though both sides carry them."""
        out = set()
        for l_e, r_e in zip(self._left_on, self._right_on):
            if (
                isinstance(l_e, ColumnReference)
                and isinstance(r_e, ColumnReference)
                and l_e.name == r_e.name
            ):
                out.add(l_e.name)
        return out

    def _make_sub(self, joined):
        left_aliases = getattr(self._left, "_join_aliases", {})
        right_aliases = getattr(self._right, "_join_aliases", {})

        def sub(ref: ColumnReference) -> ColumnReference | None:
            tbl = ref.table
            if tbl is joined:
                return None
            if tbl is self._left or tbl is left_ph:
                if ref.name == "id":
                    return ColumnReference(joined, "_left_id")
                return ColumnReference(joined, "l." + ref.name)
            if tbl is self._right or tbl is right_ph:
                if ref.name == "id":
                    return ColumnReference(joined, "_right_id")
                return ColumnReference(joined, "r." + ref.name)
            al = left_aliases.get((id(tbl), ref.name))
            if al is not None:
                return ColumnReference(joined, "l." + al)
            ar = right_aliases.get((id(tbl), ref.name))
            if ar is not None:
                return ColumnReference(joined, "r." + ar)
            from pathway_tpu.internals.table import _DeferredThisIxTable

            if isinstance(tbl, _DeferredThisIxTable):
                # pw.this.ix(...) built against the join: resolve the
                # hidden pointer expression through this substitution, and
                # translate the looked-up column name to its prefixed form
                # on the materialized join table
                new = _DeferredThisIxTable(
                    wrap_expr(tbl._expr)._substitute(sub),
                    tbl._optional,
                    tbl._context,
                    tbl._allow_misses,
                )
                if getattr(tbl, "_source", None) is not None:
                    new._source = tbl._source
                name = ref.name
                in_l = name in self._left.column_names()
                in_r = name in self._right.column_names()
                if in_l and in_r and name not in self._equated_names():
                    raise KeyError(
                        f"column {name!r} is ambiguous in join; "
                        "use pw.left/pw.right"
                    )
                if in_l:
                    name = "l." + name
                elif in_r:
                    name = "r." + name
                return ColumnReference(new, name)
            if isinstance(tbl, ThisPlaceholder):
                if ref.name == "id":
                    return ColumnReference(joined, "id")
                in_l = ref.name in self._left.column_names()
                in_r = ref.name in self._right.column_names()
                if in_l and in_r:
                    if ref.name in self._equated_names():
                        # the join condition equates both copies; outer
                        # joins leave one side None on unmatched rows, so
                        # pw.this unifies them via coalesce (reference:
                        # join condition columns unify)
                        from pathway_tpu.internals.expression import (
                            CoalesceExpression,
                        )

                        return CoalesceExpression(
                            ColumnReference(joined, "l." + ref.name),
                            ColumnReference(joined, "r." + ref.name),
                        )
                    raise KeyError(
                        f"column {ref.name!r} is ambiguous in join; "
                        "use pw.left/pw.right"
                    )
                if in_l:
                    return ColumnReference(joined, "l." + ref.name)
                if in_r:
                    return ColumnReference(joined, "r." + ref.name)
                raise ValueError(f"unknown column {ref.name!r} in join")
            return None

        return sub

    def _resolve_in_joined(self, e):
        joined, sub = self._joined_with_sub()
        return wrap_expr(e)._substitute(sub)

    def reduce(self, *args, **kwargs):
        joined, _sub = self._joined_with_sub()
        r_args = []
        for a in args:
            resolved = self._resolve_in_joined(a)
            if isinstance(resolved, ColumnReference):
                # keep the user-facing (unprefixed) output name
                orig = a.name if isinstance(a, ColumnReference) else resolved.name
                kwargs.setdefault(orig, resolved)
            else:
                r_args.append(resolved)
        r_kwargs = {n: self._resolve_in_joined(e) for n, e in kwargs.items()}
        return joined.groupby().reduce(*r_args, **r_kwargs)

    def groupby(self, *args, id=None, **kwargs):
        from pathway_tpu.internals.groupbys import GroupedJoinResult

        joined, _sub = self._joined_with_sub()
        grouping = [self._resolve_in_joined(a) for a in args]
        gt = GroupedJoinResult(
            joined,
            grouping,
            set_id=id is not None,
        )
        gt._join_result = self
        return gt

    def filter(self, expression):
        import copy

        joined, _sub = self._joined_with_sub()
        filtered = joined.filter(self._resolve_in_joined(expression))
        out = copy.copy(self)
        out._joined_cache = filtered
        return out

    # --- chained joins --------------------------------------------------------

    def _flatten(self):
        """Fold this join into one table carrying every column of both
        sides, with an alias map so references to the ORIGINAL tables
        still resolve in further joins/selects (reference: chained joins,
        internals/joins.py JoinResult.join chaining)."""
        joined, _sub = self._joined_with_sub()
        # a column equi-joined under the same name on both sides is ONE
        # column of the result (values match): keep the left copy and
        # alias the right side to it (reference: chained select(*pw.this)
        # yields each on-column once)
        equi_names = {
            l_e.name
            for l_e, r_e in zip(self._left_on, self._right_on)
            if isinstance(l_e, ColumnReference)
            and isinstance(r_e, ColumnReference)
            and l_e.name == r_e.name
        }
        exprs: dict[str, Any] = {}
        aliases: dict[tuple[int, str], str] = {}
        for tbl, prefix, idcol in (
            (self._left, "l.", "_left_id"),
            (self._right, "r.", "_right_id"),
        ):
            sub_aliases = getattr(tbl, "_join_aliases", {})
            # each side's row id stays addressable after flattening
            # (chained conditions like t1.id == t2.id)
            id_hidden = f"_pw_id_{prefix[0]}"
            exprs[id_hidden] = ColumnReference(joined, idcol)
            aliases[(id(tbl), "id")] = id_hidden
            for key, v in sub_aliases.items():
                if key[1] == "id" or v.startswith("_pw_"):
                    # nested hidden columns (pure copies, nested ids) are
                    # carried through under fresh hidden names
                    carried = f"_pw_{prefix[0]}{v}" if not v.startswith("_pw_") else f"_pw_{prefix[0]}_{v[4:]}"
                    if v in tbl.column_names():
                        exprs[carried] = ColumnReference(joined, prefix + v)
                        aliases[key] = carried
            for n in tbl.column_names():
                if n.startswith("_on") or n.startswith("_pw_"):
                    continue
                if n in equi_names:
                    # an equi-joined column shows ONCE, coalesced when the
                    # right side can carry unmatched rows; the PURE copies
                    # live under hidden names so t1.col / t2.col refs (and
                    # further chained conditions on them) read one side
                    hidden = f"_pw_{prefix[0]}_{n}"
                    exprs[hidden] = ColumnReference(joined, prefix + n)
                    aliases[(id(tbl), n)] = hidden
                    for key, v in sub_aliases.items():
                        if v == n:
                            aliases[key] = hidden
                    if prefix == "l.":
                        if self._mode in (JoinMode.RIGHT, JoinMode.OUTER):
                            exprs[n] = CoalesceExpression(
                                ColumnReference(joined, "l." + n),
                                ColumnReference(joined, "r." + n),
                            )
                        else:
                            exprs[n] = ColumnReference(joined, "l." + n)
                    continue
                out_name = n
                while out_name in exprs:
                    out_name = "_" + out_name
                exprs[out_name] = ColumnReference(joined, prefix + n)
                aliases[(id(tbl), n)] = out_name
                for key, v in sub_aliases.items():
                    if v == n:
                        aliases[key] = out_name
        flat = joined.select(**exprs)
        flat._join_aliases = aliases
        return flat

    def join(self, other, *on, id=None, how=JoinMode.INNER):
        return JoinResult(self._flatten(), other, on, how, id_expr=id)

    def join_inner(self, other, *on, id=None):
        return self.join(other, *on, id=id, how=JoinMode.INNER)

    def join_left(self, other, *on, id=None):
        return self.join(other, *on, id=id, how=JoinMode.LEFT)

    def join_right(self, other, *on, id=None):
        return self.join(other, *on, id=id, how=JoinMode.RIGHT)

    def join_outer(self, other, *on, id=None):
        return self.join(other, *on, id=id, how=JoinMode.OUTER)


class OuterJoinResult(JoinResult):
    pass
