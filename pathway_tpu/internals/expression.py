"""Column expression AST.

Capability parity with the reference expression system
(/root/reference/python/pathway/internals/expression.py, 1,179 LoC; evaluated by
src/engine/expression.rs). Expressions are lazy trees over table columns; the
engine evaluates them columnar-batch-at-a-time (vectorized numpy / jax paths in
pathway_tpu/engine/expression_eval.py) rather than row-at-a-time like the
reference interpreter.
"""

from __future__ import annotations

import operator
from typing import Any, Callable, Iterable, Mapping, TYPE_CHECKING

from pathway_tpu.internals import dtype as dt

if TYPE_CHECKING:
    from pathway_tpu.internals.table import Table


class ColumnExpression:
    """Base class of all column expressions."""

    _dtype_hint: dt.DType | None = None

    # --- arithmetic ----------------------------------------------------------

    def __add__(self, other):
        return ColumnBinaryOpExpression("+", self, other)

    def __radd__(self, other):
        return ColumnBinaryOpExpression("+", other, self)

    def __sub__(self, other):
        return ColumnBinaryOpExpression("-", self, other)

    def __rsub__(self, other):
        return ColumnBinaryOpExpression("-", other, self)

    def __mul__(self, other):
        return ColumnBinaryOpExpression("*", self, other)

    def __rmul__(self, other):
        return ColumnBinaryOpExpression("*", other, self)

    def __truediv__(self, other):
        return ColumnBinaryOpExpression("/", self, other)

    def __rtruediv__(self, other):
        return ColumnBinaryOpExpression("/", other, self)

    def __floordiv__(self, other):
        return ColumnBinaryOpExpression("//", self, other)

    def __rfloordiv__(self, other):
        return ColumnBinaryOpExpression("//", other, self)

    def __mod__(self, other):
        return ColumnBinaryOpExpression("%", self, other)

    def __rmod__(self, other):
        return ColumnBinaryOpExpression("%", other, self)

    def __pow__(self, other):
        return ColumnBinaryOpExpression("**", self, other)

    def __rpow__(self, other):
        return ColumnBinaryOpExpression("**", other, self)

    def __matmul__(self, other):
        return ColumnBinaryOpExpression("@", self, other)

    def __rmatmul__(self, other):
        return ColumnBinaryOpExpression("@", other, self)

    def __neg__(self):
        return ColumnUnaryOpExpression("-", self)

    def __abs__(self):
        return ColumnUnaryOpExpression("abs", self)

    # --- comparison ----------------------------------------------------------

    def __eq__(self, other):  # type: ignore[override]
        return ColumnBinaryOpExpression("==", self, other)

    def __ne__(self, other):  # type: ignore[override]
        return ColumnBinaryOpExpression("!=", self, other)

    def __lt__(self, other):
        return ColumnBinaryOpExpression("<", self, other)

    def __le__(self, other):
        return ColumnBinaryOpExpression("<=", self, other)

    def __gt__(self, other):
        return ColumnBinaryOpExpression(">", self, other)

    def __ge__(self, other):
        return ColumnBinaryOpExpression(">=", self, other)

    # --- boolean -------------------------------------------------------------

    def __and__(self, other):
        return ColumnBinaryOpExpression("&", self, other)

    def __rand__(self, other):
        return ColumnBinaryOpExpression("&", other, self)

    def __or__(self, other):
        return ColumnBinaryOpExpression("|", self, other)

    def __ror__(self, other):
        return ColumnBinaryOpExpression("|", other, self)

    def __xor__(self, other):
        return ColumnBinaryOpExpression("^", self, other)

    def __rxor__(self, other):
        return ColumnBinaryOpExpression("^", other, self)

    def __lshift__(self, other):
        return ColumnBinaryOpExpression("<<", self, other)

    def __rlshift__(self, other):
        return ColumnBinaryOpExpression("<<", other, self)

    def __rshift__(self, other):
        return ColumnBinaryOpExpression(">>", self, other)

    def __rrshift__(self, other):
        return ColumnBinaryOpExpression(">>", other, self)

    def __invert__(self):
        return ColumnUnaryOpExpression("~", self)

    def __hash__(self) -> int:
        return id(self)

    def __bool__(self):
        raise RuntimeError(
            "Cannot use a ColumnExpression in a boolean context; "
            "use & | ~ instead of and/or/not."
        )

    def __iter__(self):
        # without this, star-unpacking an expression falls into the legacy
        # iteration protocol over __getitem__ and loops forever building
        # GetExpressions
        raise TypeError(
            f"{type(self).__name__} is not iterable"
        )

    def __repr__(self) -> str:
        from pathway_tpu.internals.expression_printer import (
            get_expression_info,
        )

        try:
            return get_expression_info(self)
        except Exception:
            return object.__repr__(self)

    # --- accessors -----------------------------------------------------------

    def __getitem__(self, item) -> "ColumnExpression":
        return GetExpression(self, item, check_if_exists=False)

    def get(self, item, default: Any = None) -> "ColumnExpression":
        return GetExpression(self, item, default=default, check_if_exists=True)

    @property
    def dt(self):
        from pathway_tpu.internals.expressions.date_time import DateTimeNamespace

        return DateTimeNamespace(self)

    @property
    def str(self):
        from pathway_tpu.internals.expressions.string import StringNamespace

        return StringNamespace(self)

    @property
    def num(self):
        from pathway_tpu.internals.expressions.numerical import NumericalNamespace

        return NumericalNamespace(self)

    @property
    def bin(self):
        from pathway_tpu.internals.expressions.string import BytesNamespace

        return BytesNamespace(self)

    # --- type ops ------------------------------------------------------------

    def is_none(self) -> "ColumnExpression":
        return IsNoneExpression(self)

    def is_not_none(self) -> "ColumnExpression":
        return IsNotNoneExpression(self)

    def as_int(self, unwrap: bool = False) -> "ColumnExpression":
        return ConvertExpression(dt.INT, self, unwrap=unwrap)

    def as_float(self, unwrap: bool = False) -> "ColumnExpression":
        return ConvertExpression(dt.FLOAT, self, unwrap=unwrap)

    def as_str(self, unwrap: bool = False) -> "ColumnExpression":
        return ConvertExpression(dt.STR, self, unwrap=unwrap)

    def as_bool(self, unwrap: bool = False) -> "ColumnExpression":
        return ConvertExpression(dt.BOOL, self, unwrap=unwrap)

    def to_string(self) -> "ColumnExpression":
        return ToStringExpression(self)

    # --- traversal -----------------------------------------------------------

    @property
    def _children(self) -> tuple["ColumnExpression", ...]:
        return ()

    def _dependencies(self) -> list["ColumnReference"]:
        out: list[ColumnReference] = []
        seen: set[tuple[int, str]] = set()
        for child in self._children:
            for ref in child._dependencies():
                key = (id(ref.table), ref.name)
                if key not in seen:
                    seen.add(key)
                    out.append(ref)
        return out

    def _substitute(
        self, mapping: Callable[["ColumnReference"], "ColumnExpression | None"]
    ) -> "ColumnExpression":
        return self._rebuild(
            tuple(c._substitute(mapping) for c in self._children)
        )

    def _rebuild(self, children: tuple["ColumnExpression", ...]) -> "ColumnExpression":
        if not children:
            return self
        raise NotImplementedError(type(self))


def wrap_expr(value: Any) -> ColumnExpression:
    if isinstance(value, ColumnExpression):
        return value
    return ColumnConstExpression(value)


class ColumnConstExpression(ColumnExpression):
    def __init__(self, value: Any):
        self._value = value

    def __repr__(self):
        return repr(self._value)


class ColumnReference(ColumnExpression):
    """Reference to a column of a table: ``table.colname`` / ``table['colname']``.

    ``name == 'id'`` refers to the key column."""

    def __init__(self, table: Any, name: str):
        self._table = table
        self._name = name

    @property
    def table(self):
        return self._table

    @property
    def name(self) -> str:
        return self._name

    @property
    def _column_name(self) -> str:
        return self._name

    def _dependencies(self):
        return [self]

    def _substitute(self, mapping):
        result = mapping(self)
        return result if result is not None else self


    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"column {self._name!r} is not callable; "
            "did you mean to use pw.apply?"
        )


_BINARY_OPS: dict[str, Callable[[Any, Any], Any]] = {
    "+": operator.add,
    "-": operator.sub,
    "*": operator.mul,
    "/": operator.truediv,
    "//": operator.floordiv,
    "%": operator.mod,
    "**": operator.pow,
    "@": operator.matmul,
    "==": operator.eq,
    "!=": operator.ne,
    "<": operator.lt,
    "<=": operator.le,
    ">": operator.gt,
    ">=": operator.ge,
    "&": operator.and_,
    "|": operator.or_,
    "^": operator.xor,
}


class ColumnBinaryOpExpression(ColumnExpression):
    def __init__(self, op: str, left: Any, right: Any):
        self._op = op
        self._left = wrap_expr(left)
        self._right = wrap_expr(right)

    @property
    def _children(self):
        return (self._left, self._right)

    def _rebuild(self, children):
        return ColumnBinaryOpExpression(self._op, children[0], children[1])



class ColumnUnaryOpExpression(ColumnExpression):
    def __init__(self, op: str, expr: Any):
        self._op = op
        self._expr = wrap_expr(expr)

    @property
    def _children(self):
        return (self._expr,)

    def _rebuild(self, children):
        return ColumnUnaryOpExpression(self._op, children[0])



class ReducerExpression(ColumnExpression):
    """A reducer applied inside groupby().reduce() / windowby().reduce()."""

    def __init__(self, reducer: Any, *args: Any, **kwargs: Any):
        self._reducer = reducer  # engine-level Reducer descriptor
        self._args = tuple(wrap_expr(a) for a in args)
        self._kwargs = {k: wrap_expr(v) for k, v in kwargs.items()}

    @property
    def _children(self):
        return self._args + tuple(self._kwargs.values())

    def _rebuild(self, children):
        n = len(self._args)
        args = children[:n]
        kwargs = dict(zip(self._kwargs.keys(), children[n:]))
        return ReducerExpression(self._reducer, *args, **kwargs)



class ApplyExpression(ColumnExpression):
    """Escape hatch: run a python function per row (batched host callback on the
    engine side — reference: AnyExpression::Apply, src/engine/expression.rs)."""

    def __init__(
        self,
        fn: Callable,
        return_type: Any,
        propagate_none: bool,
        deterministic: bool | None,
        args: tuple,
        kwargs: Mapping[str, Any],
        *,
        max_batch_size: int | None = None,
    ):
        self._fn = fn
        self._return_type = dt.wrap(return_type)
        self._propagate_none = propagate_none
        self._deterministic = deterministic
        self._args = tuple(wrap_expr(a) for a in args)
        self._kwargs = {k: wrap_expr(v) for k, v in kwargs.items()}
        self._max_batch_size = max_batch_size
        # UDF provenance for diagnostics; pw.udf overrides with the raw
        # (unwrapped) function's name
        self._udf_name = getattr(fn, "__name__", None)

    @property
    def _children(self):
        return self._args + tuple(self._kwargs.values())

    def _rebuild(self, children):
        n = len(self._args)
        out = type(self)(
            self._fn,
            self._return_type,
            self._propagate_none,
            self._deterministic,
            children[:n],
            dict(zip(self._kwargs.keys(), children[n:])),
            max_batch_size=self._max_batch_size,
        )
        out._udf_name = self._udf_name
        return out



class BatchApplyExpression(ApplyExpression):
    """Columnar UDF: fn receives whole argument LISTS for the batch and
    returns a list of results — the path device-backed UDFs (embedders,
    rerankers) use so one jitted forward serves the whole tick."""


class AsyncApplyExpression(ApplyExpression):
    """Async UDF application (reference: async_apply_table,
    src/engine/dataflow.rs:1899)."""


class FullyAsyncApplyExpression(AsyncApplyExpression):
    pass


class CastExpression(ColumnExpression):
    def __init__(self, target: Any, expr: Any):
        self._target = dt.wrap(target)
        self._expr = wrap_expr(expr)

    @property
    def _children(self):
        return (self._expr,)

    def _rebuild(self, children):
        return CastExpression(self._target, children[0])



class ConvertExpression(ColumnExpression):
    """as_int/as_float/as_str/as_bool — Json/Any extraction."""

    def __init__(self, target: dt.DType, expr: Any, unwrap: bool = False):
        self._target = target
        self._expr = wrap_expr(expr)
        self._unwrap = unwrap

    @property
    def _children(self):
        return (self._expr,)

    def _rebuild(self, children):
        return ConvertExpression(self._target, children[0], self._unwrap)


class DeclareTypeExpression(ColumnExpression):
    def __init__(self, target: Any, expr: Any):
        self._target = dt.wrap(target)
        self._expr = wrap_expr(expr)

    @property
    def _children(self):
        return (self._expr,)

    def _rebuild(self, children):
        return DeclareTypeExpression(self._target, children[0])


class IfElseExpression(ColumnExpression):
    def __init__(self, if_: Any, then: Any, else_: Any):
        self._if = wrap_expr(if_)
        self._then = wrap_expr(then)
        self._else = wrap_expr(else_)

    @property
    def _children(self):
        return (self._if, self._then, self._else)

    def _rebuild(self, children):
        return IfElseExpression(*children)



class CoalesceExpression(ColumnExpression):
    def __init__(self, *args: Any):
        self._args = tuple(wrap_expr(a) for a in args)

    @property
    def _children(self):
        return self._args

    def _rebuild(self, children):
        return CoalesceExpression(*children)


class RequireExpression(ColumnExpression):
    """Evaluates to None if any of the requirements is None."""

    def __init__(self, val: Any, *args: Any):
        self._val = wrap_expr(val)
        self._args = tuple(wrap_expr(a) for a in args)

    @property
    def _children(self):
        return (self._val,) + self._args

    def _rebuild(self, children):
        return RequireExpression(children[0], *children[1:])


class FillErrorExpression(ColumnExpression):
    def __init__(self, expr: Any, replacement: Any):
        self._expr = wrap_expr(expr)
        self._replacement = wrap_expr(replacement)

    @property
    def _children(self):
        return (self._expr, self._replacement)

    def _rebuild(self, children):
        return FillErrorExpression(children[0], children[1])


class IsNoneExpression(ColumnExpression):
    def __init__(self, expr: Any):
        self._expr = wrap_expr(expr)

    @property
    def _children(self):
        return (self._expr,)

    def _rebuild(self, children):
        return IsNoneExpression(children[0])


class IsNotNoneExpression(ColumnExpression):
    def __init__(self, expr: Any):
        self._expr = wrap_expr(expr)

    @property
    def _children(self):
        return (self._expr,)

    def _rebuild(self, children):
        return IsNotNoneExpression(children[0])


class UnwrapExpression(ColumnExpression):
    def __init__(self, expr: Any):
        self._expr = wrap_expr(expr)

    @property
    def _children(self):
        return (self._expr,)

    def _rebuild(self, children):
        return UnwrapExpression(children[0])


class PointerExpression(ColumnExpression):
    """table.pointer_from(*args, optional=..., instance=...) — key derivation
    (reference: Key::for_values + ShardPolicy, src/engine/value.rs:60,94)."""

    def __init__(
        self,
        table: Any,
        *args: Any,
        optional: bool = False,
        instance: Any | None = None,
    ):
        self._table = table
        self._args = tuple(wrap_expr(a) for a in args)
        self._optional = optional
        self._instance = wrap_expr(instance) if instance is not None else None

    @property
    def _children(self):
        extra = (self._instance,) if self._instance is not None else ()
        return self._args + extra

    def _rebuild(self, children):
        if self._instance is not None:
            return PointerExpression(
                self._table,
                *children[:-1],
                optional=self._optional,
                instance=children[-1],
            )
        return PointerExpression(self._table, *children, optional=self._optional)


class MakeTupleExpression(ColumnExpression):
    def __init__(self, *args: Any):
        self._args = tuple(wrap_expr(a) for a in args)

    @property
    def _children(self):
        return self._args

    def _rebuild(self, children):
        return MakeTupleExpression(*children)


class SequenceGetExpression(ColumnExpression):
    pass


class GetExpression(ColumnExpression):
    """expr[i] / expr.get(i, default) over tuples, Json, strings, arrays."""

    def __init__(
        self, expr: Any, index: Any, default: Any = None, check_if_exists: bool = True
    ):
        self._expr = wrap_expr(expr)
        self._index = wrap_expr(index)
        self._default = wrap_expr(default)
        self._check_if_exists = check_if_exists

    @property
    def _children(self):
        return (self._expr, self._index, self._default)

    def _rebuild(self, children):
        return GetExpression(
            children[0], children[1], children[2], self._check_if_exists
        )


class ToStringExpression(ColumnExpression):
    def __init__(self, expr: Any):
        self._expr = wrap_expr(expr)

    @property
    def _children(self):
        return (self._expr,)

    def _rebuild(self, children):
        return ToStringExpression(children[0])


# ---------------------------------------------------------------------------
# purity / determinism facts (consumed by pathway_tpu/analysis)


def iter_subexpressions(e: ColumnExpression) -> "Iterable[ColumnExpression]":
    """Depth-first walk over an expression tree (self included)."""
    yield e
    for c in e._children:
        yield from iter_subexpressions(c)


def iter_apply_expressions(
    e: ColumnExpression,
) -> "Iterable[ApplyExpression]":
    """Every UDF application (pw.apply / @pw.udf / async variants) inside
    an expression tree."""
    for sub in iter_subexpressions(e):
        if isinstance(sub, ApplyExpression):
            yield sub


def expression_is_deterministic(e: ColumnExpression) -> bool:
    """True when re-evaluating the expression over the same rows provably
    yields the same values: every UDF inside is tagged deterministic.
    Built-in operators and method namespaces are always deterministic."""
    return all(a._deterministic for a in iter_apply_expressions(e))


def expression_is_pure(e: ColumnExpression) -> bool:
    """True when the expression contains no escape-hatch UDF at all —
    the engine fully understands its semantics."""
    for _ in iter_apply_expressions(e):
        return False
    return True


class MethodCallExpression(ColumnExpression):
    """A named method over columns (powers the .dt/.str/.num namespaces).

    ``scalar_fn`` operates on single values; ``vector_fn``, when given, operates
    on whole numpy arrays (vectorized / jax-dispatchable path)."""

    def __init__(
        self,
        name: str,
        scalar_fn: Callable,
        return_type: Any,
        *args: Any,
        vector_fn: Callable | None = None,
        propagate_none: bool = True,
    ):
        self._name = name
        self._scalar_fn = scalar_fn
        self._vector_fn = vector_fn
        self._return_type = dt.wrap(return_type)
        self._args = tuple(wrap_expr(a) for a in args)
        self._propagate_none = propagate_none

    @property
    def _children(self):
        return self._args

    def _rebuild(self, children):
        return MethodCallExpression(
            self._name,
            self._scalar_fn,
            self._return_type,
            *children,
            vector_fn=self._vector_fn,
            propagate_none=self._propagate_none,
        )

