"""Low-level value model: keys, pointers, hashing.

TPU-native analog of the reference engine value model
(/root/reference/src/engine/value.rs:41,207): the reference uses a 128-bit xxh3
key whose low 16 bits pick the worker shard. Here a row key is a 64-bit hash
stored in uint64 columns (device-friendly — keys live in HBM next to the data);
the low SHARD_BITS select the mesh shard, preserving the co-location semantics
of `with_shard_of` / instance sharding.
"""

from __future__ import annotations

import hashlib
import struct
from enum import Enum
from typing import Any, Iterable

import numpy as np

SHARD_BITS = 16
SHARD_MASK = (1 << SHARD_BITS) - 1

_SALT = b"pathway-tpu-key-v1"


class Pointer(int):
    """A row id — 64-bit stable hash. Subclasses int so it packs into uint64
    columns directly (reference: src/engine/value.rs Key + python Pointer)."""

    __slots__ = ()

    def __repr__(self) -> str:
        return f"^{self:016X}"

    def __str__(self) -> str:
        return f"^{self:016X}"

    @property
    def shard(self) -> int:
        return int(self) & SHARD_MASK

    def with_shard_of(self, other: "Pointer") -> "Pointer":
        return Pointer((int(self) & ~SHARD_MASK) | (int(other) & SHARD_MASK))


def _hash_bytes(data: bytes) -> int:
    return struct.unpack(
        "<Q", hashlib.blake2b(data, digest_size=8, key=_SALT).digest()
    )[0]


def _value_bytes(v: Any) -> bytes:
    """Stable serialization of a value for key derivation."""
    if v is None:
        return b"\x00"
    if isinstance(v, Pointer):
        return b"\x07" + struct.pack("<Q", int(v))
    if isinstance(v, (bool, np.bool_)):
        return b"\x01" + (b"\x01" if v else b"\x00")
    if isinstance(v, (int, np.integer)):
        x = int(v)
        if -(2**63) <= x < 2**63:
            return b"\x02" + struct.pack("<q", x)
        return b"\x0d" + str(x).encode()
    if isinstance(v, (float, np.floating)):
        f = float(v)
        import math

        if math.isfinite(f) and f == int(f) and abs(f) < 2**53:
            # ints and equal floats hash alike so 1 and 1.0 key identically
            return b"\x02" + struct.pack("<q", int(f))
        return b"\x03" + struct.pack("<d", f)
    if isinstance(v, str):
        return b"\x04" + v.encode("utf-8")
    if isinstance(v, bytes):
        return b"\x05" + v
    if isinstance(v, (tuple, list)):
        parts = [b"\x06", struct.pack("<I", len(v))]
        for item in v:
            b = _value_bytes(item)
            parts.append(struct.pack("<I", len(b)))
            parts.append(b)
        return b"".join(parts)
    if isinstance(v, np.ndarray):
        return b"\x08" + v.tobytes() + str(v.dtype).encode() + str(v.shape).encode()
    import datetime

    if isinstance(v, datetime.datetime):
        return b"\x09" + v.isoformat().encode()
    if isinstance(v, datetime.timedelta):
        return b"\x0a" + struct.pack("<d", v.total_seconds())
    from pathway_tpu.internals.json import Json

    if isinstance(v, Json):
        import json as _json

        return b"\x0b" + _json.dumps(v.value, sort_keys=True).encode()
    if isinstance(v, dict):
        import json as _json

        return b"\x0b" + _json.dumps(v, sort_keys=True).encode()
    return b"\x0c" + repr(v).encode()


_native_mod: Any = None
_native_checked = False


def _get_native():
    global _native_mod, _native_checked
    if not _native_checked:
        _native_checked = True
        from pathway_tpu.internals.native import get_native

        _native_mod = get_native()
    return _native_mod


def ref_scalar(*values: Any, optional: bool = False) -> Pointer:
    """Derive a stable Pointer from a tuple of values
    (reference: Key::for_values, src/engine/value.rs:60). Uses the native
    kernel (native/pathway_native.cc) when built; byte-identical fallback."""
    nat = _get_native()
    if nat is not None:
        return Pointer(nat.hash_value(tuple(values)))
    return Pointer(_hash_bytes(_value_bytes(tuple(values))))


def ptr_column(keys: np.ndarray) -> tuple:
    """Mark a raw uint64 key array as a Pointer column for
    ref_scalars_columns — the native kernel serializes it straight from the
    buffer instead of boxing one Pointer object per row."""
    return ("__ptr__", np.ascontiguousarray(keys, dtype=np.uint64))


def ref_scalars_columns(columns: list, n: int) -> np.ndarray:
    """Batch key derivation: row i keys as ref_scalar(col0[i], col1[i], ...).
    The native path hashes all rows without re-entering the interpreter;
    int64/float64 numpy columns and ptr_column-marked key arrays serialize
    directly from their buffers."""
    nat = _get_native()
    if nat is not None:
        raw = nat.hash_columns(tuple(columns), n)
        return np.frombuffer(raw, dtype=np.uint64).copy()
    cols = [
        [Pointer(int(x)) for x in col[1]]
        if isinstance(col, tuple) and len(col) == 2 and col[0] == "__ptr__"
        else col
        for col in columns
    ]
    out = np.empty(n, dtype=np.uint64)
    for i in range(n):
        out[i] = int(ref_scalar(*(col[i] for col in cols)))
    return out


def expand_ranges(starts: np.ndarray, counts: np.ndarray) -> np.ndarray:
    """Concatenate [starts[i], starts[i]+counts[i]) ranges into one index
    array (the vectorized range-expansion trick shared by the equijoin
    probe fallback and the arrangement gather)."""
    total = int(counts.sum())
    if not total:
        return np.empty(0, dtype=np.int64)
    nz = counts > 0
    reps = counts[nz]
    offs = np.arange(total, dtype=np.int64) - np.repeat(
        np.cumsum(reps) - reps, reps
    )
    return np.repeat(starts[nz].astype(np.int64), reps) + offs


def match_keys(
    left: np.ndarray, right: np.ndarray, right_sorted: bool = False
) -> tuple[np.ndarray, np.ndarray]:
    """Equijoin match step over two uint64 key arrays: every (li, ri) index
    pair with ``left[li] == right[ri]``, ordered by li (and per li, by ri in
    right order) — the probe signature the columnar delta join is built on
    (native: pathway_native.cc match_fk, a threaded GIL-free hash probe;
    fallback: sort + searchsorted).  Pass ``right_sorted=True`` when the
    right side is already ascending (arrangement segments) to skip the
    fallback's argsort."""
    nl, nr = len(left), len(right)
    if not nl or not nr:
        e = np.empty(0, dtype=np.int64)
        return e, e
    nat = _get_native()
    if nat is not None and hasattr(nat, "match_fk"):
        li_b, ri_b = nat.match_fk(
            np.ascontiguousarray(left, dtype=np.uint64),
            np.ascontiguousarray(right, dtype=np.uint64),
        )
        return (
            np.frombuffer(li_b, dtype=np.int64),
            np.frombuffer(ri_b, dtype=np.int64),
        )
    if right_sorted:
        order_r = None
        r_sorted = right
    else:
        order_r = np.argsort(right, kind="stable")
        r_sorted = right[order_r]
    lo = np.searchsorted(r_sorted, left, "left")
    hi = np.searchsorted(r_sorted, left, "right")
    counts = hi - lo
    if not counts.any():
        e = np.empty(0, dtype=np.int64)
        return e, e
    li = np.repeat(np.arange(nl, dtype=np.int64), counts)
    ri = expand_ranges(lo, counts)
    if order_r is not None:
        ri = order_r[ri]
    return li, ri


def ref_scalar_with_instance(*values: Any, instance: Any) -> Pointer:
    base = ref_scalar(*values, instance)
    inst = ref_scalar(instance)
    return base.with_shard_of(inst)


_SEQ_SALT = _hash_bytes(b"sequential")


def sequential_key(i: int) -> Pointer:
    """Key for the i-th row of an unkeyed source — hashed so rows spread
    across shards."""
    return Pointer(_hash_bytes(b"\x10" + struct.pack("<q", i)))


def keys_array(keys: Iterable[Any]) -> np.ndarray:
    return np.fromiter((int(k) for k in keys), dtype=np.uint64)


class PyObjectWrapper:
    """Opaque python object carried through the graph
    (reference: src/engine/value.rs PyObjectWrapper). Subscriptable as a
    generic in annotations: ``pw.PyObjectWrapper[dict]``."""

    __slots__ = ("value", "_serializer")

    def __init__(self, value: Any, serializer: Any = None):
        self.value = value
        self._serializer = serializer

    def __class_getitem__(cls, item: Any) -> Any:
        return cls

    def __repr__(self) -> str:
        return f"PyObjectWrapper({self.value!r})"

    def __eq__(self, other: object) -> bool:
        return isinstance(other, PyObjectWrapper) and self.value == other.value

    def __hash__(self) -> int:
        try:
            return hash(self.value)
        except TypeError:
            return hash(id(self.value))


def wrap_py_object(value: Any, serializer: Any = None) -> PyObjectWrapper:
    return PyObjectWrapper(value, serializer)


class PathwayType(Enum):
    """Public column type enum (mirrors reference PathwayType,
    src/python_api.rs:1639)."""

    ANY = "Any"
    STRING = "String"
    INT = "Int"
    BOOL = "Bool"
    FLOAT = "Float"
    POINTER = "Pointer"
    DATE_TIME_NAIVE = "DateTimeNaive"
    DATE_TIME_UTC = "DateTimeUtc"
    DURATION = "Duration"
    ARRAY = "Array"
    JSON = "Json"
    TUPLE = "Tuple"
    LIST = "List"
    BYTES = "Bytes"
    PY_OBJECT_WRAPPER = "PyObjectWrapper"
    FUTURE = "Future"

    @staticmethod
    def optional(t: "PathwayType") -> "PathwayType":
        return t


class PersistenceMode(Enum):
    """Persistence modes (reference: src/connectors/mod.rs:108)."""

    BATCH = "batch"
    SPEEDRUN_REPLAY = "speedrun_replay"
    REALTIME_REPLAY = "realtime_replay"
    PERSISTING = "persisting"
    OPERATOR_PERSISTING = "operator_persisting"
    # only operators with an explicit name persist; inputs are not logged
    SELECTIVE_PERSISTING = "selective_persisting"
    UDF_CACHING = "udf_caching"


class SnapshotAccess(Enum):
    RECORD = "record"
    REPLAY = "replay"
    FULL = "full"
    OFFSETS_ONLY = "offsets_only"


class SessionType(Enum):
    NATIVE = "native"
    UPSERT = "upsert"


class Error:
    """Singleton poison value that flows through the graph instead of raising
    (reference: src/engine/error.rs Value::Error)."""

    _instance: "Error | None" = None

    def __new__(cls) -> "Error":
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:
        return "Error"


ERROR = Error()


def unsafe_make_pointer(x: int) -> Pointer:
    return Pointer(x)
