"""TableSlice — a reshapeable view of a table's column references
(reference: python/pathway/internals/table_slice.py). Supports the
mapping protocol so `**t.slice.with_suffix("_l")` expands in select()."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.expression import ColumnReference


class TableSlice:
    def __init__(self, mapping: dict[str, ColumnReference], table: Any):
        self._mapping = mapping
        self._table = table

    def __iter__(self):
        return iter(self._mapping.values())

    def __repr__(self) -> str:
        return f"TableSlice({self._mapping})"

    def keys(self):
        return self._mapping.keys()

    def _normalize(self, arg: str | ColumnReference) -> str:
        if isinstance(arg, ColumnReference):
            return arg.name
        return arg

    def __getitem__(self, arg):
        if isinstance(arg, (ColumnReference, str)):
            return self._mapping[self._normalize(arg)]
        return TableSlice(
            {self._normalize(k): self[k] for k in arg}, self._table
        )

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        from pathway_tpu.internals.table import Table

        if hasattr(Table, name) and name != "id":
            raise ValueError(
                f"{name!r} is a method name. It is discouraged to use it as "
                f"a column name. If you really want to use it, use "
                f"[{name!r}]."
            )
        if name not in self._mapping:
            raise AttributeError(
                f"Column name {name!r} not found in {self!r}."
            )
        return self._mapping[name]

    def without(self, *cols) -> "TableSlice":
        mapping = dict(self._mapping)
        for col in cols:
            colname = self._normalize(col)
            if colname not in mapping:
                raise KeyError(
                    f"Column name {colname!r} not found in a {self}."
                )
            mapping.pop(colname)
        return TableSlice(mapping, self._table)

    def rename(self, rename_dict: dict) -> "TableSlice":
        normalized = {
            self._normalize(old): self._normalize(new)
            for old, new in rename_dict.items()
        }
        mapping = dict(self._mapping)
        for old in normalized:
            if old not in mapping:
                raise KeyError(f"Column name {old!r} not found in a {self}.")
            mapping.pop(old)
        for old, new in normalized.items():
            mapping[new] = self._mapping[old]
        return TableSlice(mapping, self._table)

    def with_prefix(self, prefix: str) -> "TableSlice":
        return self.rename({name: prefix + name for name in self.keys()})

    def with_suffix(self, suffix: str) -> "TableSlice":
        return self.rename({name: name + suffix for name in self.keys()})

    def ix(self, expression, *, optional: bool = False, context=None):
        new_table = self._table.ix(
            expression, optional=optional, context=context
        )
        return TableSlice(
            {
                name: new_table[colref.name]
                for name, colref in self._mapping.items()
            },
            new_table,
        )

    def ix_ref(self, *args, optional: bool = False, context=None):
        new_table = self._table.ix_ref(
            *args, optional=optional, context=context
        )
        return TableSlice(
            {
                name: new_table[colref.name]
                for name, colref in self._mapping.items()
            },
            new_table,
        )

    @property
    def slice(self) -> "TableSlice":
        return self
