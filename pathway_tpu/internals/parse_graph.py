"""Global parse graph (reference: python/pathway/internals/parse_graph.py:104).

User code *declares* a dataflow; every io.write/subscribe registers an output
node here. `pw.run` hands the registered outputs to the engine Runtime."""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.engine.nodes import Node, OutputNode


class _UniverseSolver:
    """Minimal query surface of the reference's universe solver: answers
    the relations our Universe objects track structurally."""

    def query_are_equal(self, a, b) -> bool:
        return a is b or (a.is_subset_of(b) and b.is_subset_of(a))

    def query_is_subset(self, a, b) -> bool:
        return a.is_subset_of(b)


class ParseGraph:
    def __init__(self) -> None:
        self.outputs: list[Node] = []
        self.streaming_sources: list[Any] = []
        self.post_run_hooks: list[Callable[[], None]] = []
        self.runtime: Any = None  # set while a run is active
        self.last_runtime: Any = None  # kept after the run for stats probing

    def add_output(self, node: Node) -> None:
        self.outputs.append(node)

    @property
    def universe_solver(self) -> _UniverseSolver:
        return _UniverseSolver()

    def clear(self) -> None:
        from pathway_tpu.engine.nodes import ALL_NODES

        self.outputs.clear()
        self.streaming_sources.clear()
        self.post_run_hooks.clear()
        self.runtime = None
        self.last_runtime = None
        ALL_NODES.clear()


G = ParseGraph()
