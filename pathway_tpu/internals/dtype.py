"""Column dtype system for the TPU-native engine.

Capability parity with the reference dtype lattice
(/root/reference/python/pathway/internals/dtype.py), re-designed for a columnar
TPU engine: every dtype knows its columnar storage class (numpy dtype or object)
so batches map directly onto device-friendly arrays.
"""

from __future__ import annotations

import datetime
import typing
from typing import Any, Callable, Optional, Tuple, Union

import numpy as np


class DType:
    """Base class for Pathway column dtypes."""

    _cache: dict[Any, "DType"] = {}

    def __init__(self, name: str, np_dtype: Any, py_type: type | None = None):
        self.name = name
        self.np_dtype = np_dtype  # numpy storage dtype ('O' for boxed values)
        self.py_type = py_type

    def __repr__(self) -> str:
        return self.name

    def __eq__(self, other: object) -> bool:
        return isinstance(other, DType) and self.name == getattr(other, "name", None)

    def __hash__(self) -> int:
        return hash(self.name)

    def is_optional(self) -> bool:
        return isinstance(self, OptionalDType)

    def strip_optional(self) -> "DType":
        return self

    @property
    def typehint(self) -> Any:
        return self.py_type if self.py_type is not None else Any


class OptionalDType(DType):
    def __init__(self, wrapped: DType):
        self.wrapped = wrapped
        super().__init__(f"Optional[{wrapped.name}]", np.dtype(object), wrapped.py_type)

    def strip_optional(self) -> DType:
        return self.wrapped

    @property
    def typehint(self) -> Any:
        return typing.Optional[self.wrapped.typehint]


class TupleDType(DType):
    def __init__(self, args: tuple[DType, ...] | None = None):
        self.args = args

        name = (
            "Tuple[...]"
            if args is None
            else "Tuple[" + ", ".join(a.name for a in args) + "]"
        )
        super().__init__(name, np.dtype(object), tuple)

    @property
    def typehint(self) -> Any:
        if self.args is None:
            return tuple
        return tuple[tuple(a.typehint for a in self.args)]


class ListDType(DType):
    def __init__(self, wrapped: DType):
        self.wrapped = wrapped
        super().__init__(f"List[{wrapped.name}]", np.dtype(object), tuple)

    @property
    def typehint(self) -> Any:
        return list[self.wrapped.typehint]


class ArrayDType(DType):
    """N-dimensional numeric array column (boxed np.ndarray per row)."""

    def __init__(self, n_dim: int | None = None, wrapped: DType | None = None):
        self.n_dim = n_dim
        self.wrapped = wrapped
        name = f"Array[{n_dim}, {wrapped.name if wrapped else 'Any'}]"
        super().__init__(name, np.dtype(object), np.ndarray)


class PointerDType(DType):
    def __init__(self, wrapped: Any = None):
        self.wrapped = wrapped
        name = "Pointer" if wrapped is None else f"Pointer[{wrapped}]"
        super().__init__(name, np.dtype(np.uint64), None)


def Pointer(*args: Any) -> PointerDType:
    """Parametrized pointer type: dt.Pointer(dt.INT) is the id type of a
    groupby over one int column (reference: dt.Pointer)."""
    if not args:
        return POINTER
    if len(args) == 1:
        return PointerDType(args[0])
    return PointerDType(tuple(args))


class CallableDType(DType):
    def __init__(self, arg_types: Any = ..., return_type: DType | None = None):
        self.arg_types = arg_types
        self.return_type = return_type
        super().__init__("Callable", np.dtype(object), None)


# --- scalar singletons -------------------------------------------------------

NONE = DType("None", np.dtype(object), type(None))
BOOL = DType("bool", np.dtype(bool), bool)
INT = DType("int", np.dtype(np.int64), int)
FLOAT = DType("float", np.dtype(np.float64), float)
STR = DType("str", np.dtype(object), str)
BYTES = DType("bytes", np.dtype(object), bytes)
ANY = DType("Any", np.dtype(object), None)
POINTER = PointerDType()
DATE_TIME_NAIVE = DType("DateTimeNaive", np.dtype(object), datetime.datetime)
DATE_TIME_UTC = DType("DateTimeUtc", np.dtype(object), datetime.datetime)
DURATION = DType("Duration", np.dtype(object), datetime.timedelta)
JSON = DType("Json", np.dtype(object), None)
PY_OBJECT_WRAPPER = DType("PyObjectWrapper", np.dtype(object), None)
ANY_TUPLE = TupleDType(None)
ANY_ARRAY = ArrayDType(None, None)
INT_ARRAY = ArrayDType(None, INT)
FLOAT_ARRAY = ArrayDType(None, FLOAT)
FUTURE = ANY  # placeholder for async column results


def Optional_(wrapped: DType) -> DType:
    if wrapped == ANY or isinstance(wrapped, OptionalDType) or wrapped == NONE:
        return wrapped
    return OptionalDType(wrapped)


# reference-style alias: tests spell dt.Optional(dt.INT); the trailing
# underscore avoided shadowing typing.Optional inside this module
Optional = Optional_  # noqa: F811

_PY_TO_DTYPE: dict[Any, DType] = {
    int: INT,
    float: FLOAT,
    bool: BOOL,
    str: STR,
    bytes: BYTES,
    type(None): NONE,
    datetime.datetime: DATE_TIME_NAIVE,
    datetime.timedelta: DURATION,
    np.ndarray: ANY_ARRAY,
    tuple: ANY_TUPLE,
    list: ANY_TUPLE,
    dict: JSON,
    Any: ANY,
}


def wrap(x: Any) -> DType:
    """Convert a python typehint / dtype-ish object into a DType."""
    if isinstance(x, DType):
        return x
    if x is None:
        return NONE
    if x in _PY_TO_DTYPE:
        return _PY_TO_DTYPE[x]
    origin = typing.get_origin(x)
    if origin is not None:
        args = typing.get_args(x)
        import types as _types

        # typing.Optional[float] and the PEP-604 spelling float | None
        # have different origins (typing.Union vs types.UnionType)
        if origin is Union or origin is _types.UnionType:
            non_none = [a for a in args if a is not type(None)]
            has_none = len(non_none) != len(args)
            if len(non_none) == 1:
                inner = wrap(non_none[0])
                return Optional_(inner) if has_none else inner
            return ANY
        if origin in (tuple,):
            if args and args[-1] is Ellipsis:
                return ListDType(wrap(args[0]))
            return TupleDType(tuple(wrap(a) for a in args))
        if origin in (list,):
            return ListDType(wrap(args[0]) if args else ANY)
        if origin is np.ndarray:
            return ANY_ARRAY
        if origin is Callable or origin is typing.Callable:  # type: ignore[comparison-overlap]
            return CallableDType()
        return ANY
    # late imports to avoid cycles
    from pathway_tpu.internals.json import Json

    if x is Json:
        return JSON
    from pathway_tpu.internals.api import Pointer

    if x is Pointer or (isinstance(x, type) and issubclass(x, Pointer)):
        return POINTER
    from pathway_tpu.internals.datetime_types import (
        DateTimeNaive,
        DateTimeUtc,
        Duration,
    )

    if x is DateTimeNaive:
        return DATE_TIME_NAIVE
    if x is DateTimeUtc:
        return DATE_TIME_UTC
    if x is Duration:
        return DURATION
    if isinstance(x, type):
        return ANY
    return ANY


def dtype_of_value(v: Any) -> DType:
    """Infer the dtype of a runtime value."""
    from pathway_tpu.internals.json import Json
    from pathway_tpu.internals.api import Pointer

    if v is None:
        return NONE
    if isinstance(v, Pointer):
        return POINTER
    if isinstance(v, bool) or isinstance(v, np.bool_):
        return BOOL
    if isinstance(v, (int, np.integer)):
        return INT
    if isinstance(v, (float, np.floating)):
        return FLOAT
    if isinstance(v, str):
        return STR
    if isinstance(v, bytes):
        return BYTES
    if isinstance(v, datetime.datetime):
        return DATE_TIME_UTC if v.tzinfo is not None else DATE_TIME_NAIVE
    if isinstance(v, datetime.timedelta):
        return DURATION
    if isinstance(v, np.ndarray):
        return ANY_ARRAY
    if isinstance(v, (tuple, list)):
        return ANY_TUPLE
    if isinstance(v, Json) or isinstance(v, dict):
        return JSON
    return ANY


def lub(a: DType, b: DType) -> DType:
    """Least upper bound of two dtypes (simplified lattice)."""
    if a == b:
        return a
    if a == NONE:
        return Optional_(b)
    if b == NONE:
        return Optional_(a)
    if isinstance(a, OptionalDType) or isinstance(b, OptionalDType):
        inner = lub(a.strip_optional(), b.strip_optional())
        return Optional_(inner)
    numeric = {BOOL: 0, INT: 1, FLOAT: 2}
    if a in numeric and b in numeric:
        return a if numeric[a] >= numeric[b] else b
    if isinstance(a, (TupleDType, ListDType)) and isinstance(b, (TupleDType, ListDType)):
        return ANY_TUPLE
    if isinstance(a, ArrayDType) and isinstance(b, ArrayDType):
        return ANY_ARRAY
    if isinstance(a, PointerDType) and isinstance(b, PointerDType):
        return POINTER
    return ANY


def is_compatible(value_dtype: DType, target: DType) -> bool:
    if target == ANY or value_dtype == ANY:
        return True
    if value_dtype == target:
        return True
    if isinstance(target, OptionalDType):
        return value_dtype == NONE or is_compatible(
            value_dtype.strip_optional(), target.wrapped
        )
    if target == FLOAT and value_dtype in (INT, BOOL):
        return True
    if target == INT and value_dtype == BOOL:
        return True
    if isinstance(target, PointerDType) and isinstance(value_dtype, PointerDType):
        return True
    if isinstance(target, (TupleDType, ListDType)) and isinstance(
        value_dtype, (TupleDType, ListDType)
    ):
        return True
    if isinstance(target, ArrayDType) and isinstance(value_dtype, ArrayDType):
        return True
    return False


def np_storage_dtype(dt: DType) -> np.dtype:
    return dt.np_dtype
