"""Reducer descriptors: bridge between `pw.reducers.*` expressions and engine
accumulators (reference: src/engine/reduce.rs:22-38 Reducer enum +
python/pathway/internals/custom_reducers.py)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod


@dataclass
class ReducerDescriptor:
    name: str
    kind: str  # engine accumulator kind
    n_args: int = 1
    skip_nones: bool = False
    fn: Callable | None = None
    extra: dict = field(default_factory=dict)
    # return dtype from arg dtypes
    ret: Callable[[list[dt.DType]], dt.DType] | None = None


def reducer_return_dtype(e: expr_mod.ReducerExpression, env) -> dt.DType:
    from pathway_tpu.internals.table import infer_dtype

    desc: ReducerDescriptor = e._reducer
    arg_dtypes = [infer_dtype(a, env) for a in e._args]
    if desc.ret is not None:
        return desc.ret(arg_dtypes)
    return dt.ANY
