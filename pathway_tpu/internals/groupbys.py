"""GroupedTable: groupby(...).reduce(...)
(reference: python/pathway/internals/groupbys.py; engine group_by_table,
src/engine/dataflow.rs:3404)."""

from __future__ import annotations

from typing import Any, Sequence

from pathway_tpu.engine import nodes
from pathway_tpu.engine.expression_eval import InternalColRef
from pathway_tpu.engine.reducers import ReducerSpec
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals.expression import (
    ColumnExpression,
    ColumnReference,
    ReducerExpression,
    wrap_expr,
)
from pathway_tpu.internals.reducer_descriptors import ReducerDescriptor
from pathway_tpu.internals.thisclass import ThisPlaceholder, ThisSlice, this
from pathway_tpu.internals.universe import Universe


class GroupedTable:
    def __init__(
        self,
        table,
        grouping: Sequence[ColumnExpression],
        instance: ColumnExpression | None = None,
        set_id: bool = False,
        sort_by: Any = None,
        skip_errors: bool = True,
    ):
        self._table = table
        self._grouping = list(grouping)
        # the instance is part of the group identity: reference ids come
        # from ref_scalar_with_instance(*grouping, instance) — the hash
        # covers the instance value, the shard bits come from it too. It
        # also makes the instance selectable in reduce() like any
        # grouping column.
        if instance is not None:
            self._grouping = self._grouping + [instance]
        self._instance = instance
        self._set_id = set_id
        self._sort_by = sort_by
        self._skip_errors = skip_errors

    def reduce(self, *args: Any, **kwargs: Any) -> Any:
        from pathway_tpu.internals.table import Table, infer_dtype

        table = self._table
        out_exprs: dict[str, ColumnExpression] = {}
        for arg in args:
            if isinstance(arg, ThisSlice):
                for n, ref in arg.resolve(table).items():
                    out_exprs[n] = ref
            elif isinstance(arg, ColumnReference):
                name = arg.name
                ref = (
                    table[name]
                    if isinstance(arg.table, ThisPlaceholder)
                    else arg
                )
                out_exprs[name] = ref
            elif isinstance(arg, ReducerExpression):
                # positional reducer: named after its (single) column arg
                # (reference: reduce(pw.reducers.any(t.pet)) -> column 'pet')
                ref_args = [
                    a for a in arg._args if isinstance(a, ColumnReference)
                ]
                if len(ref_args) != 1:
                    raise TypeError(
                        "positional reducer in reduce() must take exactly "
                        "one column argument (name it with kwarg= instead)"
                    )
                out_exprs[ref_args[0].name] = arg
            else:
                raise TypeError(f"positional reduce argument {arg!r}")
        for name, e in kwargs.items():
            out_exprs[name] = table._desugar(e)

        # --- collect reducer subexpressions & grouping references -------------
        reducer_slots: list[ReducerExpression] = []

        def collect(e: ColumnExpression):
            if isinstance(e, ReducerExpression):
                reducer_slots.append(e)
                return
            for c in e._children:
                collect(c)

        for e in out_exprs.values():
            collect(e)

        grouping_names = [f"_g{i}" for i in range(len(self._grouping))]

        def grouping_index(ref: ColumnReference) -> int | None:
            for i, g in enumerate(self._grouping):
                if (
                    isinstance(g, ColumnReference)
                    and g.table is ref.table
                    and g.name == ref.name
                ):
                    return i
            # same-universe sibling tables (t vs t.select(*pw.this)) may
            # name the grouping column through either table (reference:
            # universe-solver equivalence) — but only when both refs trace
            # back to the SAME source column (a renamed sibling column,
            # b.pet = a.owner, must not silently read the grouping key)
            def origin(r: ColumnReference):
                from pathway_tpu.engine.expression_eval import InternalColRef

                node = getattr(r.table, "_node", None)
                name = r.name
                for _ in range(32):
                    exprs = getattr(node, "exprs", None)
                    if exprs is None:
                        break
                    inner = exprs.get(name)
                    if isinstance(inner, InternalColRef):
                        node = node.inputs[inner._input_index]
                        name = inner._name
                        continue
                    if isinstance(inner, ColumnReference):
                        node = getattr(inner.table, "_node", None)
                        name = inner.name
                        continue
                    break
                return (getattr(node, "id", None), name)

            for i, g in enumerate(self._grouping):
                if (
                    isinstance(g, ColumnReference)
                    and g.name == ref.name
                    and getattr(ref.table, "_universe", None)
                    is getattr(g.table, "_universe", object())
                    and origin(ref) == origin(g)
                ):
                    return i
            return None

        # --- build prep table: grouping cols + reducer args -------------------
        prep_exprs: dict[str, ColumnExpression] = {}
        for i, g in enumerate(self._grouping):
            prep_exprs[grouping_names[i]] = g
        if self._instance is not None:
            prep_exprs["_inst"] = self._instance
        if self._sort_by is not None:
            prep_exprs["_sortby"] = table._desugar(self._sort_by)
        reducer_specs: dict[str, ReducerSpec] = {}
        slot_names: dict[int, str] = {}
        for si, red in enumerate(reducer_slots):
            name = f"_agg{si}"
            slot_names[id(red)] = name
            desc: ReducerDescriptor = red._reducer
            arg_cols = []
            for ai, arg in enumerate(red._args):
                cname = f"_a{si}_{ai}"
                prep_exprs[cname] = table._desugar(arg)
                arg_cols.append(cname)
            reducer_specs[name] = ReducerSpec(
                kind=desc.kind,
                arg_cols=tuple(arg_cols),
                skip_nones=desc.skip_nones,
                skip_errors=self._skip_errors,
                fn=desc.fn,
                extra=desc.extra,
            )
        prep = table._build_rowwise(prep_exprs)

        gb_node = nodes.GroupByNode(
            prep._node,
            grouping_names,
            reducer_specs,
            instance_col="_inst" if self._instance is not None else None,
            set_id=self._set_id,
            sort_by="_sortby" if self._sort_by is not None else None,
        )
        env = table._dtype_env()
        gb_dtypes: dict[str, dt.DType] = {}
        for i, g in enumerate(self._grouping):
            gb_dtypes[grouping_names[i]] = infer_dtype(g, env)
        for name, red in zip(reducer_specs.keys(), reducer_slots):
            from pathway_tpu.internals.reducer_descriptors import (
                reducer_return_dtype,
            )

            gb_dtypes[name] = reducer_return_dtype(red, env)
        agg_table = Table._from_node(gb_node, gb_dtypes, Universe())

        # --- final select over aggregated table -------------------------------
        def rewrite(e: ColumnExpression) -> ColumnExpression:
            if isinstance(e, ReducerExpression):
                return InternalColRef(0, slot_names[id(e)])
            if isinstance(e, ColumnReference):
                gi = grouping_index(e)
                if gi is not None:
                    return InternalColRef(0, grouping_names[gi])
                if e.name == "id" and e.table is table:
                    raise ValueError(
                        "cannot use source ids in reduce output"
                    )
                raise ValueError(
                    f"column {e.name!r} used in reduce() is not a grouping "
                    "column; wrap it in a reducer"
                )
            return e._rebuild(tuple(rewrite(c) for c in e._children))

        final_exprs = {n: rewrite(e) for n, e in out_exprs.items()}
        final_dtypes = {}
        for n, e in out_exprs.items():

            def env2(ref: ColumnReference) -> dt.DType:
                gi = grouping_index(ref)
                if gi is not None:
                    return gb_dtypes[grouping_names[gi]]
                return dt.ANY

            final_dtypes[n] = infer_dtype(e, env2)
        node = nodes.RowwiseNode([agg_table._node], final_exprs)
        return Table._from_node(node, final_dtypes, agg_table._universe)


class GroupedJoinResult(GroupedTable):
    """groupby on a join result: references to pw.left/pw.right resolve onto
    the materialized join (reference: JoinResult.groupby,
    internals/joins.py:748)."""

    _join_result = None

    def reduce(self, *args: Any, **kwargs: Any):
        jr = self._join_result
        if jr is not None:
            new_kwargs: dict[str, Any] = {}
            for a in args:
                if not isinstance(a, ColumnReference):
                    raise TypeError(
                        f"positional reduce argument {a!r} must be a column"
                    )
                resolved = jr._resolve_in_joined(a)
                new_kwargs[a.name] = resolved
            for n, e in kwargs.items():
                new_kwargs[n] = jr._resolve_in_joined(e)
            return super().reduce(**new_kwargs)
        return super().reduce(*args, **kwargs)
