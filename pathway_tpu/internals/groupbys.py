"""GroupedTable: groupby(...).reduce(...)
(reference: python/pathway/internals/groupbys.py; engine group_by_table,
src/engine/dataflow.rs:3404)."""

from __future__ import annotations

from typing import Any, Sequence

from pathway_tpu.engine import nodes
from pathway_tpu.engine.expression_eval import InternalColRef
from pathway_tpu.engine.reducers import ReducerSpec
from pathway_tpu.internals.reducer_descriptors import ReducerDescriptor
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals.expression import (
    ColumnExpression,
    ColumnReference,
    ReducerExpression,
    wrap_expr,
)
from pathway_tpu.internals.reducer_descriptors import ReducerDescriptor
from pathway_tpu.internals.thisclass import ThisPlaceholder, ThisSlice, this
from pathway_tpu.internals.universe import Universe


def _exprs_structurally_equal(a, b) -> bool:
    """Structural expression equality: same class tree, same column refs,
    same non-expression payload (constants, cast targets, functions)."""
    if isinstance(a, ColumnReference) or isinstance(b, ColumnReference):
        return (
            isinstance(a, ColumnReference)
            and isinstance(b, ColumnReference)
            and a.table is b.table
            and a.name == b.name
        )
    if type(a) is not type(b):
        return False
    ca, cb = a._children, b._children
    if len(ca) != len(cb):
        return False

    def payload(x) -> dict:
        out = {}
        for k, v in x.__dict__.items():
            if isinstance(v, ColumnExpression):
                continue
            if isinstance(v, (tuple, list)) and any(
                isinstance(i, ColumnExpression) for i in v
            ):
                continue
            if isinstance(v, dict) and any(
                isinstance(i, ColumnExpression) for i in v.values()
            ):
                continue
            out[k] = v
        return out

    pa, pb = payload(a), payload(b)
    if set(pa) != set(pb):
        return False
    for k in pa:
        va, vb = pa[k], pb[k]
        if isinstance(va, ReducerDescriptor) and isinstance(
            vb, ReducerDescriptor
        ):
            # each reducers.* call builds a fresh descriptor whose `ret`
            # lambda differs by identity; compare the semantic fields
            if not (
                va.name == vb.name
                and va.kind == vb.kind
                and va.n_args == vb.n_args
                and va.skip_nones == vb.skip_nones
                and va.fn is vb.fn
                and va.extra == vb.extra
            ):
                return False
        elif callable(va) or callable(vb):
            if va is not vb:
                return False
        elif va is not vb and va != vb:
            return False
    return all(_exprs_structurally_equal(x, y) for x, y in zip(ca, cb))


class GroupedTable:
    def __init__(
        self,
        table,
        grouping: Sequence[ColumnExpression],
        instance: ColumnExpression | None = None,
        set_id: bool = False,
        sort_by: Any = None,
        skip_errors: bool = True,
    ):
        self._table = table
        self._grouping = list(grouping)
        # the instance is part of the group identity: reference ids come
        # from ref_scalar_with_instance(*grouping, instance) — the hash
        # covers the instance value, the shard bits come from it too. It
        # also makes the instance selectable in reduce() like any
        # grouping column.
        if instance is not None:
            self._grouping = self._grouping + [instance]
        self._instance = instance
        self._set_id = set_id
        self._sort_by = sort_by
        self._skip_errors = skip_errors

    def __getattr__(self, name: str) -> Any:
        # source columns are addressable on the grouped table itself, for
        # reduce expressions like values.ix(grouped.ptr).v (reference:
        # GroupedJoinable column access, internals/groupbys.py)
        if name.startswith("_"):
            raise AttributeError(name)
        try:
            return self._table[name]
        except KeyError:
            raise AttributeError(name) from None

    def __getitem__(self, name: str) -> Any:
        return self._table[name]

    def reduce(self, *args: Any, **kwargs: Any) -> Any:
        from pathway_tpu.internals.table import Table, infer_dtype

        for e in kwargs.values():
            if isinstance(e, ThisPlaceholder):
                raise TypeError(
                    "`**pw.this` expansion is not supported in reduce(); "
                    "name the reduced columns explicitly"
                )

        table = self._table
        out_exprs: dict[str, ColumnExpression] = {}
        for arg in args:
            if isinstance(arg, ThisPlaceholder):
                # reduce(*pw.this): every source column (legal only when
                # each is a grouping column, checked below like any ref)
                for n in table.column_names():
                    out_exprs[n] = table[n]
                continue
            if isinstance(arg, ThisSlice):
                for n, ref in arg.resolve(table).items():
                    out_exprs[n] = ref
            elif isinstance(arg, ColumnReference):
                name = arg.name
                ref = (
                    table[name]
                    if isinstance(arg.table, ThisPlaceholder)
                    else arg
                )
                out_exprs[name] = ref
            elif isinstance(arg, ReducerExpression):
                # positional reducer: named after its (single) column arg
                # (reference: reduce(pw.reducers.any(t.pet)) -> column 'pet')
                ref_args = [
                    a for a in arg._args if isinstance(a, ColumnReference)
                ]
                if len(ref_args) != 1:
                    raise TypeError(
                        "positional reducer in reduce() must take exactly "
                        "one column argument (name it with kwarg= instead)"
                    )
                out_exprs[ref_args[0].name] = arg
            else:
                raise TypeError(f"positional reduce argument {arg!r}")
        for name, e in kwargs.items():
            out_exprs[name] = table._desugar(e)

        # --- post-aggregation ix lookups (pw.this.ix(argmax(...)).col) --------
        from pathway_tpu.internals.table import _DeferredIxTable

        deferred_tables: dict[int, Any] = {}

        def find_deferred(e):
            if isinstance(e, ColumnReference) and isinstance(
                e.table, _DeferredIxTable
            ):
                if e.table._contains_reducer():
                    deferred_tables.setdefault(id(e.table), e.table)
                return
            for c in e._children:
                find_deferred(c)

        for e in out_exprs.values():
            find_deferred(e)
        ix_slots: dict[int, tuple[str, Any, Any]] = {}

        def _same_lookup(d1, d2) -> bool:
            return (
                getattr(d1, "_source", None) is getattr(d2, "_source", None)
                and getattr(d1, "_optional", False)
                == getattr(d2, "_optional", False)
                and getattr(d1, "_allow_misses", False)
                == getattr(d2, "_allow_misses", False)
            )

        for k, (key, dtbl) in enumerate(deferred_tables.items()):
            inners = [table._desugar(p) for p in dtbl._pointer_exprs()]
            if getattr(dtbl, "_raw_expr", True):
                # the single expr IS the pointer (this.ix / table.ix paths)
                ptr_expr = inners[0]
            else:
                # ix_ref(a, b, instance=...): the pointer is derived from
                # the aggregated KEY VALUES, exactly like _materialize
                from pathway_tpu.internals.expression import (
                    PointerExpression,
                )

                inst = dtbl._instance
                ptr_expr = PointerExpression(
                    dtbl._source,
                    *inners,
                    optional=dtbl._optional,
                    instance=table._desugar(inst) if inst is not None else None,
                )
            # structurally identical lookups share one slot (one reducer,
            # one IxNode) — the common multi-column argmax-row pattern
            shared = None
            for other in ix_slots.values():
                if _same_lookup(dtbl, other[2]) and _exprs_structurally_equal(
                    ptr_expr, other[1]
                ):
                    shared = other
                    break
            ix_slots[key] = shared or (f"_ixptr{k}", ptr_expr, dtbl)

        # --- collect reducer subexpressions & grouping references -------------
        reducer_slots: list[ReducerExpression] = []

        def collect(e: ColumnExpression):
            if isinstance(e, ReducerExpression):
                reducer_slots.append(e)
                return
            for c in e._children:
                collect(c)

        for e in out_exprs.values():
            collect(e)
        _seen_slots: set[str] = set()
        for _slot, inner, _d in ix_slots.values():
            if _slot not in _seen_slots:
                _seen_slots.add(_slot)
                collect(inner)

        grouping_names = [f"_g{i}" for i in range(len(self._grouping))]

        def grouping_index(ref: ColumnReference) -> int | None:
            for i, g in enumerate(self._grouping):
                if (
                    isinstance(g, ColumnReference)
                    and g.table is ref.table
                    and g.name == ref.name
                ):
                    return i
            # same-universe sibling tables (t vs t.select(*pw.this)) may
            # name the grouping column through either table (reference:
            # universe-solver equivalence) — but only when both refs trace
            # back to the SAME source column (a renamed sibling column,
            # b.pet = a.owner, must not silently read the grouping key)
            def origin(r: ColumnReference):
                from pathway_tpu.engine.expression_eval import InternalColRef

                node = getattr(r.table, "_node", None)
                name = r.name
                for _ in range(32):
                    exprs = getattr(node, "exprs", None)
                    if exprs is None:
                        break
                    inner = exprs.get(name)
                    if isinstance(inner, InternalColRef):
                        node = node.inputs[inner._input_index]
                        name = inner._name
                        continue
                    if isinstance(inner, ColumnReference):
                        node = getattr(inner.table, "_node", None)
                        name = inner.name
                        continue
                    break
                return (getattr(node, "id", None), name)

            for i, g in enumerate(self._grouping):
                if (
                    isinstance(g, ColumnReference)
                    and g.name == ref.name
                    and getattr(ref.table, "_universe", None)
                    is getattr(g.table, "_universe", object())
                    and origin(ref) == origin(g)
                ):
                    return i
            return None

        # --- build prep table: grouping cols + reducer args -------------------
        prep_exprs: dict[str, ColumnExpression] = {}
        for i, g in enumerate(self._grouping):
            prep_exprs[grouping_names[i]] = g
        if self._instance is not None:
            prep_exprs["_inst"] = self._instance
        if self._sort_by is not None:
            prep_exprs["_sortby"] = table._desugar(self._sort_by)
        reducer_specs: dict[str, ReducerSpec] = {}
        slot_names: dict[int, str] = {}
        for si, red in enumerate(reducer_slots):
            name = f"_agg{si}"
            slot_names[id(red)] = name
            desc: ReducerDescriptor = red._reducer
            arg_cols = []
            for ai, arg in enumerate(red._args):
                cname = f"_a{si}_{ai}"
                prep_exprs[cname] = table._desugar(arg)
                arg_cols.append(cname)
            reducer_specs[name] = ReducerSpec(
                kind=desc.kind,
                arg_cols=tuple(arg_cols),
                skip_nones=desc.skip_nones,
                skip_errors=self._skip_errors,
                fn=desc.fn,
                extra=desc.extra,
            )
        prep = table._build_rowwise(prep_exprs)

        gb_node = nodes.GroupByNode(
            prep._node,
            grouping_names,
            reducer_specs,
            instance_col="_inst" if self._instance is not None else None,
            set_id=self._set_id,
            sort_by="_sortby" if self._sort_by is not None else None,
        )
        # windowby-built groupbys aggregate windows, not raw groups — the
        # Graph Doctor's unbounded-state rule treats them differently
        gb_node._windowed = getattr(self, "_pw_windowed", False)
        env = table._dtype_env()
        gb_dtypes: dict[str, dt.DType] = {}
        for i, g in enumerate(self._grouping):
            gb_dtypes[grouping_names[i]] = infer_dtype(g, env)
        for name, red in zip(reducer_specs.keys(), reducer_slots):
            from pathway_tpu.internals.reducer_descriptors import (
                reducer_return_dtype,
            )

            gb_dtypes[name] = reducer_return_dtype(red, env)
        agg_table = Table._from_node(gb_node, gb_dtypes, Universe())
        # ids of a groupby derive from the grouping values: their type is
        # the parametrized pointer (reference: Pointer[grouping dtypes])
        _id_dtype = dt.Pointer(
            *[gb_dtypes[n] for n in grouping_names]
        )
        agg_table._schema.__id_dtype__ = _id_dtype

        # --- final select over aggregated table -------------------------------
        _expr_matches = _exprs_structurally_equal

        def grouping_expr_index(e) -> int | None:
            for i, g in enumerate(self._grouping):
                if not isinstance(g, ColumnReference) and _expr_matches(e, g):
                    return i
            return None

        def rewrite(e: ColumnExpression) -> ColumnExpression:
            if isinstance(e, ReducerExpression):
                return InternalColRef(0, slot_names[id(e)])
            if not isinstance(e, ColumnReference):
                gie = grouping_expr_index(e)
                if gie is not None:
                    return InternalColRef(0, grouping_names[gie])
            if isinstance(e, ColumnReference):
                gi = grouping_index(e)
                if gi is not None:
                    return InternalColRef(0, grouping_names[gi])
                if e.name == "id" and e.table is table:
                    raise ValueError(
                        "cannot use source ids in reduce output"
                    )
                raise ValueError(
                    f"column {e.name!r} used in reduce() is not a grouping "
                    "column; wrap it in a reducer"
                )
            return e._rebuild(tuple(rewrite(c) for c in e._children))

        def env2(ref: ColumnReference) -> dt.DType:
            gi = grouping_index(ref)
            if gi is not None:
                return gb_dtypes[grouping_names[gi]]
            return dt.ANY

        def has_deferred(e) -> bool:
            if isinstance(e, ColumnReference):
                return id(e.table) in ix_slots
            return any(has_deferred(c) for c in e._children)

        if not ix_slots:
            final_exprs = {n: rewrite(e) for n, e in out_exprs.items()}
            final_dtypes = {
                n: infer_dtype(e, env2) for n, e in out_exprs.items()
            }
            node = nodes.RowwiseNode([agg_table._node], final_exprs)
            out_tbl = Table._from_node(
                node, final_dtypes, agg_table._universe
            )
            out_tbl._schema.__id_dtype__ = _id_dtype
            return out_tbl

        # stage 1: the plain aggregated columns, every reducer slot +
        # grouping column (stage 2 may reference them), and the ix pointer
        # slots
        stage1_exprs: dict[str, ColumnExpression] = {}
        stage1_dtypes: dict[str, dt.DType] = {}
        for n, e in out_exprs.items():
            if not has_deferred(e):
                stage1_exprs[n] = rewrite(e)
                stage1_dtypes[n] = infer_dtype(e, env2)
        for slot in slot_names.values():
            stage1_exprs.setdefault(slot, InternalColRef(0, slot))
            stage1_dtypes.setdefault(slot, gb_dtypes[slot])
        for i, gname in enumerate(grouping_names):
            stage1_exprs.setdefault(gname, InternalColRef(0, gname))
            stage1_dtypes.setdefault(gname, gb_dtypes[gname])
        for slot, inner, _d in ix_slots.values():
            stage1_exprs[slot] = rewrite(inner)
            stage1_dtypes[slot] = dt.POINTER
        node1 = nodes.RowwiseNode([agg_table._node], stage1_exprs)
        stage1 = Table._from_node(node1, stage1_dtypes, Universe())

        # stage 2: ix the source table at the aggregated pointers and
        # substitute the deferred references (reference: in-reduce
        # ix(argmax) lookups, tests/test_common.py test_groupby_ix)
        ixed: dict[int, Table] = {}
        ixed_by_slot: dict[str, Table] = {}
        for key, (slot, _inner, dtbl) in ix_slots.items():
            if slot not in ixed_by_slot:
                src = getattr(dtbl, "_source", None) or table
                ixed_by_slot[slot] = src.ix(
                    stage1[slot],
                    optional=getattr(dtbl, "_optional", False),
                    allow_misses=getattr(dtbl, "_allow_misses", False),
                )
            ixed[key] = ixed_by_slot[slot]

        def rewrite2(e):
            if isinstance(e, ColumnReference):
                if id(e.table) in ixed:
                    return ixed[id(e.table)][e.name]
                gi = grouping_index(e)
                if gi is not None:
                    return stage1[grouping_names[gi]]
                if e.table is stage1:
                    return e
                raise ValueError(
                    f"column {e.name!r} used in reduce() is not a "
                    "grouping column; wrap it in a reducer"
                )
            gie = grouping_expr_index(e)
            if gie is not None:
                return stage1[grouping_names[gie]]
            if isinstance(e, ReducerExpression):
                return stage1[slot_names[id(e)]]
            return e._rebuild(tuple(rewrite2(c) for c in e._children))

        stage2_exprs = {}
        for n, e in out_exprs.items():
            if has_deferred(e):
                stage2_exprs[n] = rewrite2(e)
            else:
                stage2_exprs[n] = stage1[n]
        return stage1.select(**stage2_exprs)


class GroupedJoinResult(GroupedTable):
    """groupby on a join result: references to pw.left/pw.right resolve onto
    the materialized join (reference: JoinResult.groupby,
    internals/joins.py:748)."""

    _join_result = None

    def reduce(self, *args: Any, **kwargs: Any):
        jr = self._join_result
        if jr is not None:
            new_kwargs: dict[str, Any] = {}
            for a in args:
                if not isinstance(a, ColumnReference):
                    raise TypeError(
                        f"positional reduce argument {a!r} must be a column"
                    )
                resolved = jr._resolve_in_joined(a)
                new_kwargs[a.name] = resolved
            for n, e in kwargs.items():
                new_kwargs[n] = jr._resolve_in_joined(e)
            return super().reduce(**new_kwargs)
        return super().reduce(*args, **kwargs)
