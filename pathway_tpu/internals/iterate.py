"""pw.iterate — fixed-point iteration
(reference: internals/common.py:39 pw.iterate; engine iterate,
src/engine/dataflow.rs:4185).

TPU-engine strategy: instead of differential's nested product-order scopes,
each outer tick recomputes the fixpoint over full input snapshots by running
the iteration body subgraph repeatedly (bounded by ``iteration_limit``), then
emits the diff vs the previously emitted fixpoint. Inner iteration is
batch-synchronous — the microbatch analog of `Variable` feedback loops.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from pathway_tpu.engine.batch import DiffBatch, MultisetState
from pathway_tpu.engine.nodes import InputExec, InputNode, Node, NodeExec, OutputNode
from pathway_tpu.engine.runtime import Runtime, StaticSource
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe


class _PlaceholderSource(StaticSource):
    def events(self):
        return []


class IterateNode(Node):
    def __init__(
        self,
        outer_inputs: list[Node],
        placeholder_nodes: list[InputNode],
        boundary_proxies: list[InputNode],
        result_nodes: dict[str, Node],
        iterated_names: list[str],
        out_name: str,
        iteration_limit: int | None,
    ):
        # inputs = initial iterated tables, then boundary outer tables the
        # body reads (their diffs stream in from the OUTER runtime; inside
        # the body they are injected as frozen snapshots via proxies)
        super().__init__(outer_inputs, result_nodes[out_name].column_names)
        self.placeholder_nodes = placeholder_nodes
        self.boundary_proxies = boundary_proxies
        self.result_nodes = result_nodes
        self.iterated_names = iterated_names
        self.out_name = out_name
        self.iteration_limit = iteration_limit

    def make_exec(self):
        return IterateExec(self)


class IterateExec(NodeExec):
    def __init__(self, node: IterateNode):
        super().__init__(node)
        self.states = [
            MultisetState(inp.column_names) for inp in node.inputs
        ]
        self.emitted: dict[int, tuple] = {}

    def _run_body(
        self,
        current: dict[str, dict[int, tuple]],
        boundary: list[dict[int, tuple]],
    ) -> dict[str, dict[int, tuple]]:
        """One application of the iteration body over full snapshots."""
        node = self.node
        captures: dict[str, dict[int, tuple]] = {name: {} for name in node.result_nodes}
        outputs = []

        def make_cb(name):
            def cb(t, batch: DiffBatch):
                store = captures[name]
                for k, d, vals in batch.iter_rows():
                    if d > 0:
                        store[k] = vals
                    else:
                        store.pop(k, None)

            return cb

        for name, rnode in node.result_nodes.items():
            outputs.append(OutputNode(rnode, make_cb(name)))
        # nested per-iteration runtimes are driven via tick() directly and
        # would leak one thread pool per fixpoint iteration
        rt = Runtime(outputs, worker_threads=False, distributed=False)
        injected: dict[int, list[DiffBatch]] = {}
        for ph, name in zip(node.placeholder_nodes, node.iterated_names):
            rows = [(k, 1, v) for k, v in current[name].items()]
            injected[ph.id] = [DiffBatch.from_rows(rows, ph.column_names)]
        for proxy, snap in zip(node.boundary_proxies, boundary):
            rows = [(k, 1, v) for k, v in snap.items()]
            injected[proxy.id] = [DiffBatch.from_rows(rows, proxy.column_names)]
        rt.tick(0, injected)
        rt.tick(1 << 62)  # flush
        return captures

    def process(self, t, inputs):
        touched = False
        for state, batches in zip(self.states, inputs):
            for b in batches:
                if len(b):
                    touched = True
                state.apply(b)
        if not touched:
            return []
        node = self.node
        n_iter = len(node.iterated_names)
        current: dict[str, dict[int, tuple]] = {}
        for name, state in zip(node.iterated_names, self.states[:n_iter]):
            current[name] = {k: e[0] for k, e in state.rows.items()}
        boundary = [
            {k: e[0] for k, e in state.rows.items()}
            for state in self.states[n_iter:]
        ]
        limit = node.iteration_limit or 1000
        for _i in range(limit):
            result = self._run_body(current, boundary)
            new = {name: result[name] for name in node.iterated_names}
            if all(new[name] == current[name] for name in node.iterated_names):
                current = new
                break
            current = new
        final = result[node.out_name]  # type: ignore[possibly-undefined]
        from pathway_tpu.engine.batch import _values_eq

        out_rows = []
        for k, old in list(self.emitted.items()):
            neww = final.get(k)
            if neww is None or not _values_eq(old, neww):
                out_rows.append((k, -1, old))
                del self.emitted[k]
        for k, vals in final.items():
            old = self.emitted.get(k)
            if old is None:
                out_rows.append((k, 1, vals))
                self.emitted[k] = vals
        if not out_rows:
            return []
        return [DiffBatch.from_rows(out_rows, node.column_names)]


def iterate(
    func: Callable,
    iteration_limit: int | None = None,
    **kwargs: Table,
) -> Any:
    """Iterate ``func`` to a fixed point.

    ``func`` receives tables (as keyword args) and returns a Table or a dict /
    namespace of Tables with the same keys; those are fed back until stable.
    """
    iterated_names = list(kwargs.keys())
    placeholders: list[InputNode] = []
    ph_tables: dict[str, Table] = {}
    for name, tbl in kwargs.items():
        ph = InputNode(
            _PlaceholderSource(tbl.column_names()), tbl.column_names()
        )
        placeholders.append(ph)
        ph_tables[name] = Table._from_node(
            ph,
            {n: tbl._schema[n].dtype for n in tbl.column_names()},
            Universe(),
        )
    result = func(**ph_tables)
    if isinstance(result, Table):
        result_map = {iterated_names[0]: result}
        single = True
    elif isinstance(result, dict):
        result_map = result
        single = False
    else:  # namedtuple-ish
        result_map = {
            name: getattr(result, name) for name in iterated_names
        }
        single = False
    result_nodes = {name: tbl._node for name, tbl in result_map.items()}

    # Cut the body graph at the placeholder-downstream boundary: any node
    # the body reads that is NOT downstream of a placeholder belongs to the
    # outer graph (e.g. the edges table of pagerank/louvain). It must not be
    # re-executed inside the body runtime (its execs there would be empty) —
    # instead it becomes an extra IterateNode input whose consolidated state
    # is injected into a proxy InputNode on every body run.
    # (reference analog: differential `iterate` closes over outer-scope
    # collections via `enter`, external/differential-dataflow iterate.rs)
    ph_ids = {ph.id for ph in placeholders}
    downstream_memo: dict[int, bool] = {}

    def downstream(node: Node) -> bool:
        if node.id in ph_ids:
            return True
        if node.id in downstream_memo:
            return downstream_memo[node.id]
        downstream_memo[node.id] = False  # DAG: safe guard
        r = any(downstream(i) for i in node.inputs)
        downstream_memo[node.id] = r
        return r

    topo: list[Node] = []
    visited: set[int] = set()

    def visit(n: Node):
        if n.id in visited:
            return
        visited.add(n.id)
        for i in n.inputs:
            visit(i)
        topo.append(n)

    for rn in result_nodes.values():
        visit(rn)
    boundary_nodes: list[Node] = []
    proxies: dict[int, InputNode] = {}
    for n in topo:
        if not downstream(n) or n.id in ph_ids:
            continue
        for idx, i in enumerate(n.inputs):
            if downstream(i):
                continue
            proxy = proxies.get(i.id)
            if proxy is None:
                proxy = InputNode(
                    _PlaceholderSource(i.column_names), i.column_names
                )
                proxies[i.id] = proxy
                boundary_nodes.append(i)
            n.inputs[idx] = proxy
    boundary_proxies = [proxies[b.id] for b in boundary_nodes]

    outer_nodes = [tbl._node for tbl in kwargs.values()]
    out_tables = {}
    for out_name, rtbl in result_map.items():
        it_node = IterateNode(
            outer_nodes + boundary_nodes,
            placeholders,
            boundary_proxies,
            result_nodes,
            iterated_names,
            out_name,
            iteration_limit,
        )
        out_tables[out_name] = Table._from_node(
            it_node,
            {n: rtbl._schema[n].dtype for n in rtbl.column_names()},
            Universe(),
        )
    if single:
        return out_tables[iterated_names[0]]
    import types

    return types.SimpleNamespace(**out_tables)


def iterate_universe(func: Callable, **kwargs: Table) -> Any:
    return iterate(func, **kwargs)
