"""pw.iterate — fixed-point iteration
(reference: internals/common.py:39 pw.iterate; engine iterate,
src/engine/dataflow.rs:4185-4282).

TPU-engine strategy: a persistent inner runtime PER ITERATION DEPTH, fed
by DIFF batches — the microbatch realization of differential's nested
product-order scopes ((outer time, iteration) lexicographic). Depth-i's
runtime holds the incremental state of the i-th body application; an
outer delta touching d rows flows down the depth chain as diff batches,
costing O(d · depths-reached) instead of O(n · iters) per tick:

  - per-depth consumed-pointer logs (xlog/blog) let a depth that was
    skipped on earlier ticks (early convergence) catch up with exactly
    the accumulated diffs when a later tick reaches it;
  - fixpoint detection is incremental: neq[i] tracks the keys where
    X_i != X_{i-1}, updated only for keys touched this tick — all-empty
    means the sequence is self-converged at depth i;
  - if the incoming diff dies out at depth i (nothing to inject and the
    depth's cache matches), every deeper value is unchanged from the
    previous tick, so the previous fixpoint stands (emit nothing).

The outer input state is still mirrored in MultisetStates so persistence
can snapshot the exec (inner runtimes are not picklable); on restore the
depth chain reseeds from the full snapshot on the next tick.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from pathway_tpu.engine.batch import DiffBatch, MultisetState, _values_eq
from pathway_tpu.engine.nodes import InputExec, InputNode, Node, NodeExec, OutputNode
from pathway_tpu.engine.runtime import Runtime, StaticSource
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe


class _PlaceholderSource(StaticSource):
    def events(self):
        return []


class IterateNode(Node):
    def __init__(
        self,
        outer_inputs: list[Node],
        placeholder_nodes: list[InputNode],
        boundary_proxies: list[InputNode],
        result_nodes: dict[str, Node],
        iterated_names: list[str],
        out_name: str,
        iteration_limit: int | None,
    ):
        # inputs = initial iterated tables, then boundary outer tables the
        # body reads (their diffs stream in from the OUTER runtime; inside
        # the body they are injected as frozen snapshots via proxies)
        # canonical column order = the PLACEHOLDER (input) order: bodies
        # may reorder columns in their selects, and every feedback path
        # (value store, logs, injection) indexes tuples positionally
        out_idx = iterated_names.index(out_name)
        ph_cols = placeholder_nodes[out_idx].column_names
        out_cols = result_nodes[out_name].column_names
        super().__init__(
            outer_inputs,
            ph_cols if set(ph_cols) == set(out_cols) else out_cols,
        )
        self.placeholder_nodes = placeholder_nodes
        self.boundary_proxies = boundary_proxies
        self.result_nodes = result_nodes
        self.iterated_names = iterated_names
        self.out_name = out_name
        self.iteration_limit = iteration_limit

    def _make_local_exec(self):
        return IterateExec(self)

    def make_exec(self):
        if getattr(self, "_dcn", False):
            from pathway_tpu.engine.dcn import DcnIterateExec

            return DcnIterateExec(self)
        return self._make_local_exec()


class _Depth:
    """One iteration depth: a persistent inner runtime computing the
    (i+1)-th sequence element from diffs of the i-th, plus its bookkeeping
    (consumed-log pointers, captured output value, this-tick deltas)."""

    def __init__(self, node: IterateNode):
        self.node = node
        # captured CURRENT value of every result table at this depth
        self.value: dict[str, dict[int, tuple]] = {
            name: {} for name in node.result_nodes
        }
        # diffs captured during the current tick() only
        self.tick_out: dict[str, list[DiffBatch]] = {}
        outputs = []

        ph_order = {
            name: node.placeholder_nodes[i].column_names
            for i, name in enumerate(node.iterated_names)
        }

        def make_cb(name):
            def cb(t, batch: DiffBatch):
                # canonicalize to placeholder column order: feedback and
                # value stores index tuples positionally
                wanted = ph_order.get(name)
                if wanted is not None and set(wanted) == set(batch.columns):
                    if list(batch.columns) != wanted:
                        batch = DiffBatch(
                            keys=batch.keys,
                            diffs=batch.diffs,
                            columns={n: batch.columns[n] for n in wanted},
                        )
                self.tick_out.setdefault(name, []).append(batch)
                store = self.value[name]
                for k, d, vals in batch.iter_rows():
                    if d > 0:
                        store[k] = vals
                    else:
                        store.pop(k, None)

            return cb

        for name, rnode in node.result_nodes.items():
            outputs.append(OutputNode(rnode, make_cb(name)))
        # inner runtimes are driven via tick() directly; no worker pool,
        # never part of the cross-process lockstep cadence
        self.runtime = Runtime(outputs, worker_threads=False, distributed=False)
        # on_end-dependent operators (temporal buffers) cannot live in a
        # persistent per-depth runtime: there is no final tick to flush
        # them, so rows would be silently held forever — refuse loudly
        for ex in self.runtime.execs.values():
            if type(ex).__name__ in ("BufferExec", "ForgetExec", "FreezeExec"):
                raise NotImplementedError(
                    "temporal buffer/forget/freeze operators inside a "
                    "pw.iterate body are not supported by the incremental "
                    "iterate engine (no final flush tick inside the "
                    "fixpoint loop)"
                )
        self.time = 0
        self.xptr: dict[str, int] = {}  # consumed xlog prefix, per name
        self.bptr = 0  # consumed prefix of the boundary log

    def run(
        self,
        x_batches: dict[int, list[DiffBatch]],
        b_batches: dict[int, list[DiffBatch]],
    ) -> dict[str, list[DiffBatch]]:
        self.tick_out = {}
        injected = dict(x_batches)
        injected.update(b_batches)
        self.runtime.tick(self.time, injected)
        self.time += 1
        return self.tick_out


class IterateExec(NodeExec):
    def __init__(self, node: IterateNode):
        super().__init__(node)
        self.states = [
            MultisetState(inp.column_names) for inp in node.inputs
        ]
        self.emitted: dict[int, tuple] = {}
        self._depths: list[_Depth] = []
        # xlog[i] = every diff batch ever produced for sequence element i
        # (i=0: outer input diffs; i>0: depth i-1 output diffs), so a depth
        # skipped by early convergence can catch up later via its xptr
        self._xlog: list[dict[str, list[DiffBatch]]] = []
        self._blog: list[dict[int, list[DiffBatch]]] = []  # boundary diffs
        # neq[i][name] = keys where X_i differs from X_{i-1} (all empty =
        # self-converged at depth i); updated only for touched keys
        self._neq: list[dict[str, set]] = []
        self._v0: dict[str, dict[int, tuple]] = {
            name: {} for name in node.iterated_names
        }
        self._final_depth: int | None = None
        self._needs_reseed = False

    # --- persistence: inner runtimes are rebuilt, not pickled -------------

    def state_dict(self) -> dict | None:
        return {
            "states": self.states,
            "emitted": self.emitted,
            "_needs_reseed": True,
        }

    def load_state(self, state: dict) -> None:
        self.states = state["states"]
        self.emitted = state["emitted"]
        self._needs_reseed = True

    # --- incremental fixpoint --------------------------------------------

    def _depth(self, i: int) -> _Depth:
        while len(self._depths) <= i:
            self._depths.append(_Depth(self.node))
            self._xlog.append({n: [] for n in self.node.iterated_names})
            self._neq.append({n: set() for n in self.node.iterated_names})
        return self._depths[i]

    def _value_at(self, i: int, name: str) -> dict[int, tuple]:
        """X_i: the i-th sequence element (0 = outer input mirror)."""
        if i == 0:
            return self._v0[name]
        return self._depths[i - 1].value[name]

    def _update_neq(self, i: int, name: str, touched) -> None:
        """Re-evaluate X_i vs X_{i-1} equality for the touched keys only.
        Stored in _neq[i-1] (convention: _neq[j] compares X_{j+1} vs X_j,
        created alongside depth j)."""
        if i < 1:
            return
        self._depth(i - 1)
        lo = self._value_at(i - 1, name)
        hi = self._value_at(i, name)
        neq = self._neq[i - 1][name]
        for k in touched:
            a = lo.get(k)
            b = hi.get(k)
            if (a is None) != (b is None) or (
                a is not None and not _values_eq(a, b)
            ):
                neq.add(k)
            else:
                neq.discard(k)

    def _converged_at(self, i: int) -> bool:
        """True when X_{i+1} == X_i (depth i's output equals its input)."""
        return all(not s for s in self._neq[i].values())

    def process(self, t, inputs):
        node = self.node
        n_iter = len(node.iterated_names)
        touched_any = False
        for state, batches in zip(self.states, inputs):
            for b in batches:
                if len(b):
                    touched_any = True
                state.apply(b)
        if self._needs_reseed:
            # after a persistence restore the depth chain is empty: feed
            # the full mirrored state through it once
            self._needs_reseed = False
            self._depths = []
            self._xlog = []
            self._blog = []
            self._neq = []
            self._v0 = {n: {} for n in node.iterated_names}
            seed: list[DiffBatch] = []
            for idx, (name, state) in enumerate(
                zip(node.iterated_names, self.states[:n_iter])
            ):
                rows = [(k, 1, e[0]) for k, e in state.rows.items()]
                ncols = node.placeholder_nodes[idx].column_names
                seed.append(DiffBatch.from_rows(rows, ncols))
            inputs = [[b] for b in seed] + [
                [
                    DiffBatch.from_rows(
                        [(k, 1, e[0]) for k, e in state.rows.items()],
                        proxy.column_names,
                    )
                ]
                for state, proxy in zip(
                    self.states[n_iter:], node.boundary_proxies
                )
            ]
            touched_any = True
        if not touched_any:
            return []

        # stage this tick's outer diffs into the logs + the V0 mirror
        out_touched: set[int] = set()
        self._depth(0)
        x0 = self._xlog[0]
        v0_touched: dict[str, set] = {}
        for idx, name in enumerate(node.iterated_names):
            batches = [b for b in inputs[idx] if len(b)]
            x0[name].extend(batches)
            mirror = self._v0[name]
            tk = v0_touched.setdefault(name, set())
            for b in batches:
                for k, d, vals in b.iter_rows():
                    tk.add(k)
                    if d > 0:
                        mirror[k] = vals
                    else:
                        mirror.pop(k, None)
        bdiffs: dict[int, list[DiffBatch]] = {}
        for bidx, proxy in enumerate(node.boundary_proxies):
            batches = [b for b in inputs[n_iter + bidx] if len(b)]
            if batches:
                bdiffs.setdefault(proxy.id, []).extend(batches)
        self._blog.append(bdiffs)
        for name, tk in v0_touched.items():
            self._update_neq(1, name, tk)

        limit = node.iteration_limit or 1000
        prev_final_depth = self._final_depth
        converged_depth: int | None = None
        i = 0
        # walk the depth chain. Before convergence, new depths are created
        # as diffs demand them; after convergence, EXISTING deeper depths
        # are still drained (their backlogs consumed) so every per-depth
        # log can be truncated each tick — memory stays bounded by one
        # tick's churn, not total history.
        while i < limit:
            if i >= len(self._depths) and converged_depth is not None:
                break
            depth = self._depth(i)
            fresh = depth.time == 0
            xlog_i = self._xlog[i]
            x_pending: dict[int, list[DiffBatch]] = {}
            if fresh:
                # a fresh depth seeds from the CURRENT value of X_i (the
                # consolidated equivalent of the full history) instead of
                # the log — logs can therefore be truncated aggressively
                for idx, name in enumerate(node.iterated_names):
                    rows = [
                        (k, 1, v) for k, v in self._value_at(i, name).items()
                    ]
                    ncols = node.placeholder_nodes[idx].column_names
                    if rows:
                        x_pending[node.placeholder_nodes[idx].id] = [
                            DiffBatch.from_rows(rows, ncols)
                        ]
                    depth.xptr[name] = len(xlog_i[name])
                b_pending: dict[int, list[DiffBatch]] = {}
                for state, proxy in zip(
                    self.states[n_iter:], node.boundary_proxies
                ):
                    rows = [(k, 1, e[0]) for k, e in state.rows.items()]
                    if rows:
                        b_pending[proxy.id] = [
                            DiffBatch.from_rows(rows, proxy.column_names)
                        ]
                depth.bptr = len(self._blog)
            else:
                for idx, name in enumerate(node.iterated_names):
                    tail = xlog_i[name][depth.xptr.get(name, 0) :]
                    if tail:
                        x_pending[node.placeholder_nodes[idx].id] = tail
                    depth.xptr[name] = len(xlog_i[name])
                b_pending = {}
                for blog_entry in self._blog[depth.bptr :]:
                    for pid_, bs in blog_entry.items():
                        b_pending.setdefault(pid_, []).extend(bs)
                depth.bptr = len(self._blog)
            if not x_pending and not b_pending:
                # the delta died out: X_j unchanged from the previous tick
                # for every j >= i, and no deeper depth has backlog either
                # (boundary diffs fan out to every depth, X diffs chain
                # contiguously) — the previous fixpoint stands
                break
            out = depth.run(x_pending, b_pending)
            # record depth output diffs into the next depth's log + neq
            # (unless this is the last depth we will touch: a fresh depth
            # created later seeds from the value, which already includes
            # these diffs)
            if i + 1 < len(self._depths) or converged_depth is None:
                self._depth(i + 1)
                next_log = self._xlog[i + 1]
            else:
                next_log = None
            for name in node.iterated_names:
                produced = [b for b in out.get(name, []) if len(b)]
                if next_log is not None:
                    next_log[name].extend(produced)
                tk = set()
                for b in produced:
                    tk.update(b.keys.tolist())
                if tk:
                    self._update_neq(i + 1, name, tk)
                    if len(self._depths) >= i + 2:
                        self._update_neq(i + 2, name, tk)
            for b in out.get(node.out_name, []):
                out_touched.update(b.keys.tolist())
            if converged_depth is None and self._converged_at(i):
                converged_depth = i
            i += 1
        if converged_depth is not None:
            final_depth = converged_depth
        elif i >= limit:
            final_depth = limit - 1  # iteration_limit semantics: X_limit
        else:
            final_depth = prev_final_depth  # delta died out: unchanged
        # every existing depth has now consumed its full backlog: truncate
        # the logs and drop far-beyond-convergence depths (recreated from
        # value seeds if ever needed again)
        for d_i, depth in enumerate(self._depths):
            log = self._xlog[d_i]
            for name in node.iterated_names:
                consumed = depth.xptr.get(name, 0)
                if consumed:
                    del log[name][:consumed]
                    depth.xptr[name] = 0
            depth.bptr = 0
        self._blog.clear()
        if final_depth is not None and len(self._depths) > final_depth + 4:
            del self._depths[final_depth + 4 :]
            del self._xlog[final_depth + 4 :]
            del self._neq[final_depth + 4 :]
        self._final_depth = final_depth

        # emit the fixpoint's delta vs what we last emitted, checking only
        # keys touched this tick (untouched keys keep their old fixpoint)
        if final_depth is None:
            return []
        final = self._depths[final_depth].value[node.out_name]
        candidates = set(out_touched)
        if prev_final_depth != final_depth:
            # the converged depth moved: values at both depths are the
            # fixpoints, but re-check everything that differs between them
            candidates.update(final.keys())
            candidates.update(self.emitted.keys())
        out_rows = []
        for k in candidates:
            old = self.emitted.get(k)
            neww = final.get(k)
            if old is not None and (
                neww is None or not _values_eq(old, neww)
            ):
                out_rows.append((k, -1, old))
                del self.emitted[k]
                old = None
            if neww is not None and old is None:
                out_rows.append((k, 1, neww))
                self.emitted[k] = neww
        if not out_rows:
            return []
        return [DiffBatch.from_rows(out_rows, node.column_names)]


def iterate(
    func: Callable,
    iteration_limit: int | None = None,
    **kwargs: Table,
) -> Any:
    """Iterate ``func`` to a fixed point.

    ``func`` receives tables (as keyword args) and returns a Table or a dict /
    namespace of Tables with the same keys; those are fed back until stable.

    Limitation: temporal ``buffer`` / ``forget`` / ``freeze`` operators
    (windowby behaviors, ``_buffer`` time-column cutoffs) are not supported
    inside the iterate body and raise ``NotImplementedError`` at build time
    — the incremental fixpoint engine keeps per-depth runtimes alive across
    ticks, so there is no final flush tick that would release buffered rows.
    Apply temporal behaviors before or after the ``iterate`` instead.
    """
    if iteration_limit is not None and iteration_limit < 1:
        raise ValueError("wrong iteration limit")
    iterated_names = list(kwargs.keys())
    placeholders: list[InputNode] = []
    ph_tables: dict[str, Table] = {}
    for name, tbl in kwargs.items():
        ph = InputNode(
            _PlaceholderSource(tbl.column_names()), tbl.column_names()
        )
        placeholders.append(ph)
        ph_tables[name] = Table._from_node(
            ph,
            {n: tbl._schema[n].dtype for n in tbl.column_names()},
            Universe(),
        )
    result = func(**ph_tables)
    if isinstance(result, Table):
        result_map = {iterated_names[0]: result}
        single = True
    elif isinstance(result, dict):
        result_map = result
        single = False
    else:  # namedtuple-ish
        result_map = {
            name: getattr(result, name) for name in iterated_names
        }
        single = False
    result_nodes = {name: tbl._node for name, tbl in result_map.items()}

    # Cut the body graph at the placeholder-downstream boundary: any node
    # the body reads that is NOT downstream of a placeholder belongs to the
    # outer graph (e.g. the edges table of pagerank/louvain). It must not be
    # re-executed inside the body runtime (its execs there would be empty) —
    # instead it becomes an extra IterateNode input whose consolidated state
    # is injected into a proxy InputNode on every body run.
    # (reference analog: differential `iterate` closes over outer-scope
    # collections via `enter`, external/differential-dataflow iterate.rs)
    ph_ids = {ph.id for ph in placeholders}
    downstream_memo: dict[int, bool] = {}

    def downstream(node: Node) -> bool:
        if node.id in ph_ids:
            return True
        if node.id in downstream_memo:
            return downstream_memo[node.id]
        downstream_memo[node.id] = False  # DAG: safe guard
        r = any(downstream(i) for i in node.inputs)
        downstream_memo[node.id] = r
        return r

    topo: list[Node] = []
    visited: set[int] = set()

    def visit(n: Node):
        if n.id in visited:
            return
        visited.add(n.id)
        for i in n.inputs:
            visit(i)
        topo.append(n)

    for rn in result_nodes.values():
        visit(rn)
    boundary_nodes: list[Node] = []
    proxies: dict[int, InputNode] = {}
    for n in topo:
        if not downstream(n) or n.id in ph_ids:
            continue
        for idx, i in enumerate(n.inputs):
            if downstream(i):
                continue
            proxy = proxies.get(i.id)
            if proxy is None:
                proxy = InputNode(
                    _PlaceholderSource(i.column_names), i.column_names
                )
                proxies[i.id] = proxy
                boundary_nodes.append(i)
            n.inputs[idx] = proxy
    boundary_proxies = [proxies[b.id] for b in boundary_nodes]

    outer_nodes = [tbl._node for tbl in kwargs.values()]
    out_tables = {}
    for out_name, rtbl in result_map.items():
        it_node = IterateNode(
            outer_nodes + boundary_nodes,
            placeholders,
            boundary_proxies,
            result_nodes,
            iterated_names,
            out_name,
            iteration_limit,
        )
        out_tables[out_name] = Table._from_node(
            it_node,
            # the node may canonicalize to the placeholder column order
            {n: rtbl._schema[n].dtype for n in it_node.column_names},
            Universe(),
        )
    if single:
        return out_tables[iterated_names[0]]
    import types

    return types.SimpleNamespace(**out_tables)


def iterate_universe(arg: Any = None, **kwargs: Table) -> Any:
    """`pw.iterate_universe(table)` marks an iterated table whose key set
    changes across iterations (reference: iterate_universe). The
    incremental engine handles changing universes natively, so the marker
    is a passthrough; the legacy callable form aliases iterate()."""
    if callable(arg):
        return iterate(arg, **kwargs)
    return arg
