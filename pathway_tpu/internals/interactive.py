"""LiveTable — live results on a separately running graph.

TPU-native counterpart of the reference's interactive mode
(reference: python/pathway/internals/interactive.py:130 — LiveTable runs a
background GraphRunner thread and mirrors a table's current state into the
notebook via ExportedTable.subscribe). Here the background Runtime streams
diffs into an in-memory snapshot with a pandas/_repr_html_ view.
"""

from __future__ import annotations

import threading
from typing import Any

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.engine.nodes import OutputNode
from pathway_tpu.engine.runtime import Runtime
from pathway_tpu.internals import parse_graph


class LiveTable:
    def __init__(self, table: Any):
        self._table = table
        self._column_names = table.column_names()
        self._rows: dict[int, tuple] = {}
        self._lock = threading.Lock()
        self._runtime: Runtime | None = None
        self._thread: threading.Thread | None = None
        self._start()

    def _on_batch(self, t: int, batch: DiffBatch) -> None:
        with self._lock:
            for k, d, vals in batch.iter_rows():
                if d > 0:
                    self._rows[k] = vals
                else:
                    self._rows.pop(k, None)

    def _start(self) -> None:
        # only this table's mirror output — globally declared sinks must
        # not run as a side effect of peeking at a table
        node = OutputNode(self._table._node, self._on_batch)
        G = parse_graph.G
        self._runtime = Runtime([node], autocommit_ms=50)
        G.last_runtime = self._runtime

        def run():
            try:
                self._runtime.run()
            except Exception:  # background thread: keep the notebook alive
                pass

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    # --- views ---------------------------------------------------------------

    def to_pandas(self):
        import pandas as pd

        with self._lock:
            keys = list(self._rows.keys())
            data = {
                n: [self._rows[k][i] for k in keys]
                for i, n in enumerate(self._column_names)
            }
        return pd.DataFrame(data, index=keys)

    def snapshot(self) -> dict[int, tuple]:
        with self._lock:
            return dict(self._rows)

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)

    def _repr_html_(self) -> str:
        return self.to_pandas()._repr_html_()

    def __repr__(self) -> str:
        return repr(self.to_pandas())

    def stop(self) -> None:
        if self._runtime is not None:
            self._runtime.stop()
        if self._thread is not None:
            self._thread.join(timeout=5)


def live(table: Any) -> LiveTable:
    """Start the declared dataflow in the background and return a live view
    of `table` (Jupyter-friendly)."""
    return LiveTable(table)
