"""LiveTable — live results on a separately running graph.

TPU-native counterpart of the reference's interactive mode
(reference: python/pathway/internals/interactive.py:130 — LiveTable runs a
background GraphRunner thread over an export datasink and mirrors the
table back into the session through an import datasource). Here the
background Runtime streams diffs into an in-memory snapshot with:

- ``snapshot()`` / ``snapshot_at`` views and a ``frontier()`` (the last
  completed logical time, END_OF_TIME when the run finished);
- ``subscribe(on_change)`` — push notifications per diff, with the
  current state replayed first so late subscribers see full history;
- ``table()`` — the import half of the reference's export/import pair: a
  fresh Table in the CURRENT parse graph fed live from this mirror, so
  interactive results compose into new dataflows.
"""

from __future__ import annotations

import threading
from typing import Any, Callable

from pathway_tpu.engine.batch import END_OF_TIME, DiffBatch
from pathway_tpu.engine.nodes import InputNode, OutputNode
from pathway_tpu.engine.runtime import Runtime, StreamingSource
from pathway_tpu.internals import parse_graph


class LiveTable:
    def __init__(self, table: Any):
        self._table = table
        self._column_names = table.column_names()
        self._rows: dict[int, tuple] = {}
        self._lock = threading.Lock()  # state: rows/frontier/subscribers
        # callbacks run OUTSIDE _lock (so they may call frontier()/
        # snapshot()/len() without deadlocking) but UNDER _deliver_lock,
        # which serializes replay-then-follow ordering per subscriber
        self._deliver_lock = threading.Lock()
        self._frontier = 0
        self._done = threading.Event()
        self.error: BaseException | None = None
        self._subscribers: list[Callable] = []
        self._runtime: Runtime | None = None
        self._thread: threading.Thread | None = None
        self._start()

    def _on_batch(self, t: int, batch: DiffBatch) -> None:
        with self._deliver_lock:
            with self._lock:
                self._frontier = max(self._frontier, t)
                subs = list(self._subscribers)
                deliveries = []
                for k, d, vals in batch.iter_rows():
                    if d > 0:
                        self._rows[k] = vals
                    else:
                        self._rows.pop(k, None)
                    if subs:
                        deliveries.append(
                            (k, dict(zip(self._column_names, vals)), d > 0)
                        )
            for k, row, add in deliveries:
                for cb in subs:
                    try:
                        cb(k, row, t, add)
                    except Exception:
                        pass

    def _start(self) -> None:
        # only this table's mirror output — globally declared sinks must
        # not run as a side effect of peeking at a table
        node = OutputNode(self._table._node, self._on_batch)
        G = parse_graph.G
        self._runtime = Runtime([node], autocommit_ms=50, distributed=False)
        G.last_runtime = self._runtime

        def run():
            try:
                self._runtime.run()
            except Exception as exc:  # keep the notebook alive, keep the
                self.error = exc  # failure observable (reference: failed())
            finally:
                with self._lock:
                    self._frontier = END_OF_TIME
                self._done.set()

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    # --- reference-parity surface --------------------------------------------

    def frontier(self) -> int:
        with self._lock:
            return self._frontier

    @property
    def done(self) -> bool:
        return self._done.is_set()

    @property
    def failed(self) -> bool:
        return self.error is not None

    def wait(self, timeout: float | None = None) -> bool:
        """Block until the background run finishes. Raises if the run
        failed — a crashed run must not read as clean completion."""
        finished = self._done.wait(timeout)
        if finished and self.error is not None:
            raise RuntimeError(
                f"live table's background run failed: {self.error!r}"
            ) from self.error
        return finished

    def subscribe(self, on_change: Callable) -> Callable:
        """Register on_change(key, row, time, is_addition); the current
        state is replayed first (as insertions at the current frontier),
        then every subsequent diff is delivered in order. Returns the
        callback as an unsubscribe handle."""
        with self._deliver_lock:
            with self._lock:
                replay = [
                    (k, dict(zip(self._column_names, vals)), self._frontier)
                    for k, vals in self._rows.items()
                ]
                self._subscribers.append(on_change)
            for k, row, t in replay:
                try:
                    on_change(k, row, t, True)
                except Exception:
                    pass
        return on_change

    def unsubscribe(self, handle: Callable) -> None:
        with self._lock:
            if handle in self._subscribers:
                self._subscribers.remove(handle)

    def snapshot(self) -> tuple[int, dict[int, tuple]]:
        """(frontier, rows) — the reference's LiveTableSnapshot."""
        with self._lock:
            return self._frontier, dict(self._rows)

    def table(self) -> Any:
        """Import this live mirror into the CURRENT parse graph as a new
        streaming source (reference: import_table/ImportDataSource) so
        interactive results can feed further dataflows."""
        from pathway_tpu.internals.table import Table
        from pathway_tpu.internals.universe import Universe

        source = _LiveImportSource(self)
        node = InputNode(source, self._column_names)
        dtypes = {
            n: self._table._schema[n].dtype for n in self._column_names
        }
        return Table._from_node(node, dtypes, Universe())

    # --- views ---------------------------------------------------------------

    def to_pandas(self):
        import pandas as pd

        with self._lock:
            keys = list(self._rows.keys())
            data = {
                n: [self._rows[k][i] for k in keys]
                for i, n in enumerate(self._column_names)
            }
        return pd.DataFrame(data, index=keys)

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)

    def _repr_html_(self) -> str:
        return self.to_pandas()._repr_html_()

    def __repr__(self) -> str:
        return repr(self.to_pandas())

    def stop(self) -> None:
        if self._runtime is not None:
            self._runtime.stop()
        if self._thread is not None:
            self._thread.join(timeout=5)


class _LiveImportSource(StreamingSource):
    """Feeds a LiveTable's snapshot + subsequent diffs into a session of a
    NEW graph; closes when the live run finishes."""

    def __init__(self, live_table: LiveTable):
        super().__init__(live_table._column_names)
        self._live = live_table
        self._watcher: threading.Thread | None = None
        self._handle: Callable | None = None

    def start(self) -> None:
        cols = self.column_names

        def on_change(k, row, t, is_addition):
            vals = tuple(row[n] for n in cols)
            rows = [(k, 1 if is_addition else -1, vals)]
            self.session.insert_batch(rows)

        self._handle = self._live.subscribe(on_change)

        def watch():
            self._live._done.wait()
            self.session.close()

        self._watcher = threading.Thread(target=watch, daemon=True)
        self._watcher.start()

    def stop(self) -> None:
        # detach so a stopped downstream graph doesn't keep accumulating
        # rows in a session nobody drains
        if self._handle is not None:
            self._live.unsubscribe(self._handle)
            self._handle = None


def live(table: Any) -> LiveTable:
    """Start the declared dataflow in the background and return a live view
    of `table` (Jupyter-friendly)."""
    return LiveTable(table)
