"""`pw.this` / `pw.left` / `pw.right` placeholders
(reference: python/pathway/internals/thisclass.py). Attribute access returns
ColumnReferences bound to the placeholder; desugaring substitutes the actual
table when the expression reaches a table operation."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.expression import ColumnReference, PointerExpression


_KEY_GUARD_COUNTER = 0


class ThisPlaceholder:
    def __init__(self, kind: str):
        self._kind = kind

    @property
    def C(self):
        from pathway_tpu.internals.table import _ColumnNamespace

        return _ColumnNamespace(self)

    def __getattr__(self, name: str) -> ColumnReference:
        if name.startswith("__") and name.endswith("__"):
            raise AttributeError(name)
        return ColumnReference(self, name)

    def __getitem__(self, name) -> Any:
        if isinstance(name, str):
            if name.startswith("_pw_this_expand_"):
                return self  # `**pw.left` guard key (see keys())
            return ColumnReference(self, name)
        if isinstance(name, (list, tuple)):
            return ThisSlice(self, [c if isinstance(c, str) else c.name for c in name])
        raise TypeError(name)

    @property
    def id(self) -> ColumnReference:
        return ColumnReference(self, "id")

    def pointer_from(self, *args, optional: bool = False, instance=None):
        return PointerExpression(self, *args, optional=optional, instance=instance)

    def ix(
        self,
        expression,
        *,
        optional: bool = False,
        context=None,
        allow_misses: bool = False,
    ):
        from pathway_tpu.internals.table import _DeferredThisIxTable

        return _DeferredThisIxTable(expression, optional, context, allow_misses)

    def without(self, *columns) -> "ThisSlice":
        names = [c if isinstance(c, str) else c.name for c in columns]
        return ThisSlice(self, None, without=names)

    def __repr__(self) -> str:
        return f"pw.{self._kind}"

    def __iter__(self):
        # `*pw.this` in select(...) expands to all columns (the positional
        # ThisPlaceholder handler does the expansion; iteration just hands
        # the placeholder through)
        return iter([self])

    def keys(self):
        # `**pw.left` support: the mapping protocol hands select() a
        # single guarded kwarg whose VALUE is this placeholder; select
        # handlers detect it and expand to all columns (reference:
        # thisclass KEY_GUARD keys)
        global _KEY_GUARD_COUNTER  # unique per expansion: collisions would
        _KEY_GUARD_COUNTER += 1  # silently drop one side's columns
        return [f"_pw_this_expand_{_KEY_GUARD_COUNTER}"]


class ThisSlice:
    """`pw.this[["a","b"]]` or `pw.this.without(...)` — resolved against the
    target table at desugaring time."""

    def __init__(self, parent: ThisPlaceholder, names: list[str] | None, without=None):
        self._parent = parent
        self._names = names
        self._without = without or []

    def resolve(self, table) -> dict[str, ColumnReference]:
        names = self._names
        if names is None:
            names = [c for c in table.column_names() if c not in self._without]
        return {n: table[n] for n in names}


this = ThisPlaceholder("this")
left = ThisPlaceholder("left")
right = ThisPlaceholder("right")


def is_this_like(obj: Any) -> bool:
    return isinstance(obj, ThisPlaceholder)
