"""`pw.this` / `pw.left` / `pw.right` placeholders
(reference: python/pathway/internals/thisclass.py). Attribute access returns
ColumnReferences bound to the placeholder; desugaring substitutes the actual
table when the expression reaches a table operation."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.expression import ColumnReference, PointerExpression


_KEY_GUARD_COUNTER = 0


class ThisPlaceholder:
    def __init__(self, kind: str):
        self._kind = kind

    @property
    def C(self):
        from pathway_tpu.internals.table import _ColumnNamespace

        return _ColumnNamespace(self)

    def __getattr__(self, name: str) -> ColumnReference:
        if name.startswith("__") and name.endswith("__"):
            raise AttributeError(name)
        return ColumnReference(self, name)

    def __getitem__(self, name) -> Any:
        if isinstance(name, str):
            if name.startswith("_pw_this_expand_"):
                return self  # `**pw.left` guard key (see keys())
            return ColumnReference(self, name)
        if isinstance(name, (list, tuple)):
            return ThisSlice(self, [c if isinstance(c, str) else c.name for c in name])
        raise TypeError(name)

    @property
    def id(self) -> ColumnReference:
        return ColumnReference(self, "id")

    def pointer_from(self, *args, optional: bool = False, instance=None):
        return PointerExpression(self, *args, optional=optional, instance=instance)

    def ix(
        self,
        expression,
        *,
        optional: bool = False,
        context=None,
        allow_misses: bool = False,
    ):
        from pathway_tpu.internals.table import _DeferredThisIxTable

        return _DeferredThisIxTable(expression, optional, context, allow_misses)

    def without(self, *columns) -> "ThisSlice":
        names = [c if isinstance(c, str) else c.name for c in columns]
        return ThisSlice(self, None, without=names)

    def rename(self, rename_dict: dict) -> "ThisSlice":
        return ThisSlice(self, None).rename(rename_dict)

    def with_prefix(self, prefix: str) -> "ThisSlice":
        return ThisSlice(self, None, prefix=prefix)

    def with_suffix(self, suffix: str) -> "ThisSlice":
        return ThisSlice(self, None, suffix=suffix)

    def __repr__(self) -> str:
        return f"pw.{self._kind}"

    def __iter__(self):
        # `*pw.this` in select(...) expands to all columns (the positional
        # ThisPlaceholder handler does the expansion; iteration just hands
        # the placeholder through)
        return iter([self])

    def keys(self):
        # `**pw.left` support: the mapping protocol hands select() a
        # single guarded kwarg whose VALUE is this placeholder; select
        # handlers detect it and expand to all columns (reference:
        # thisclass KEY_GUARD keys)
        global _KEY_GUARD_COUNTER  # unique per expansion: collisions would
        _KEY_GUARD_COUNTER += 1  # silently drop one side's columns
        return [f"_pw_this_expand_{_KEY_GUARD_COUNTER}"]


class ThisSlice:
    """`pw.this[["a","b"]]`, `pw.this.without(...)`, `pw.left.rename(...)`,
    with_prefix/with_suffix — resolved against the target table at
    desugaring time. Attribute access mints deferred ColumnReferences
    that error at resolve time when the name was sliced away (reference:
    thisclass mock slices / TableSlice)."""

    def __init__(
        self,
        parent: ThisPlaceholder,
        names: list[str] | None,
        without=None,
        renames: dict | None = None,
        prefix: str = "",
        suffix: str = "",
        pick: list[str] | None = None,
        out_renames: dict | None = None,
    ):
        self._parent = parent
        self._names = names
        self._without = list(without or [])
        self._renames = dict(renames or {})  # source name -> output name
        self._prefix = prefix
        self._suffix = suffix
        self._pick = pick  # narrow to these OUTPUT names after renaming
        self._out_renames = dict(out_renames or {})  # output -> new output

    def _derive(self, **overrides) -> "ThisSlice":
        kw = dict(
            names=self._names,
            without=self._without,
            renames=self._renames,
            prefix=self._prefix,
            suffix=self._suffix,
            pick=self._pick,
            out_renames=self._out_renames,
        )
        kw.update(overrides)
        return ThisSlice(self._parent, **kw)

    def without(self, *columns) -> "ThisSlice":
        extra = [c if isinstance(c, str) else c.name for c in columns]
        return self._derive(without=self._without + extra)

    def rename(self, rename_dict: dict) -> "ThisSlice":
        norm = {
            (k if isinstance(k, str) else k.name): (
                v if isinstance(v, str) else v.name
            )
            for k, v in rename_dict.items()
        }
        # renames address OUTPUT names (post prefix/suffix/earlier
        # renames), mirroring TableSlice.rename; unknown names error at
        # resolve time, when the column set is known
        return self._derive(out_renames={**self._out_renames, **norm})

    def with_prefix(self, prefix: str) -> "ThisSlice":
        return self._derive(prefix=prefix + self._prefix)

    def with_suffix(self, suffix: str) -> "ThisSlice":
        return self._derive(suffix=self._suffix + suffix)

    def keys(self):
        # `**pw.left.without("x")` mapping protocol: one guarded key whose
        # value is this slice; select handlers expand it (same guard trick
        # as ThisPlaceholder.keys)
        global _KEY_GUARD_COUNTER
        _KEY_GUARD_COUNTER += 1
        return [f"_pw_this_expand_{_KEY_GUARD_COUNTER}"]

    def __iter__(self):
        return iter([self])

    def __getattr__(self, name: str) -> ColumnReference:
        if name.startswith("_"):
            raise AttributeError(name)
        return ColumnReference(self, name)

    def __getitem__(self, name) -> Any:
        if isinstance(name, str):
            if name.startswith("_pw_this_expand_"):
                return self  # `**slice` guard key (see keys())
            return ColumnReference(self, name)
        if isinstance(name, (list, tuple)):
            picked = [c if isinstance(c, str) else c.name for c in name]
            return self._derive(pick=picked)
        raise TypeError(name)

    def _visible_names(self, table) -> list[str]:
        names = self._names
        if names is None:
            names = list(table.column_names())
        return [c for c in names if c not in self._without]

    def _out_name(self, src: str) -> str:
        return self._prefix + self._renames.get(src, src) + self._suffix

    def resolve(self, table) -> dict[str, ColumnReference]:
        out = {
            self._out_name(n): table[n] for n in self._visible_names(table)
        }
        for old, new in self._out_renames.items():
            if old not in out:
                raise KeyError(
                    f"Column name {old!r} not found in this slice."
                )
            out[new] = out.pop(old)
        if self._pick is not None:
            out = {n: out[n] for n in self._pick}
        return out

    def resolve_ref(self, table, name: str) -> ColumnReference:
        # `name` is an OUTPUT name: apply renames/prefix/suffix/pick
        resolved = self.resolve(table)
        if name not in resolved:
            raise KeyError(
                f"Column name {name!r} not found in this slice."
            )
        return resolved[name]


this = ThisPlaceholder("this")
left = ThisPlaceholder("left")
right = ThisPlaceholder("right")


def is_this_like(obj: Any) -> bool:
    return isinstance(obj, ThisPlaceholder)
