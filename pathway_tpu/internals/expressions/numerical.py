"""`.num` column namespace
(reference surface: python/pathway/internals/expressions/numerical.py)."""

from __future__ import annotations

import numpy as np

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import (
    ColumnExpression,
    CoalesceExpression,
    MethodCallExpression,
)


class NumericalNamespace:
    def __init__(self, expression: ColumnExpression):
        self._expr = expression

    def abs(self):
        return MethodCallExpression(
            "num.abs", abs, dt.ANY, self._expr, vector_fn=np.abs
        )

    def round(self, decimals=0):
        return MethodCallExpression(
            "num.round",
            lambda x, d: round(x, d),
            dt.ANY,
            self._expr,
            decimals,
            vector_fn=lambda x, d: np.round(x, d),
        )

    def fill_na(self, default_value):
        def fn(x):
            if x is None:
                return default_value
            if isinstance(x, float) and x != x:  # NaN
                return default_value
            return x

        return MethodCallExpression(
            "num.fill_na", fn, dt.ANY, self._expr, propagate_none=False
        )
