"""`.dt` column namespace
(reference surface: python/pathway/internals/expressions/date_time.py; the
reference implements these in Rust over chrono, src/engine/time.rs).

Values are pandas Timestamps/Timedeltas (nanosecond precision), so every
method here computes on the exact `.value` nanosecond integers — matching
the reference's chrono i64-nanosecond arithmetic, including the chrono
format extensions (`%f` = 9-digit nanoseconds, `%3f`/`%6f`/`%9f` widths,
`%:z` offsets) and truncation-toward-zero duration components. Methods are
dtype-gated: calling a datetime method on an int column raises
AttributeError at build time when the static dtype is known (reference:
the type_interpreter rejects mistyped namespace calls)."""

from __future__ import annotations

import datetime
import warnings

import pandas as pd

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.datetime_types import (
    DateTimeNaive,
    DateTimeUtc,
    Duration,
)
from pathway_tpu.internals.expression import ColumnExpression, MethodCallExpression


def _m(name, fn, ret, *args):
    return MethodCallExpression(name, fn, ret, *args)


_UNIT_NS = {
    "ns": 1,
    "us": 1_000,
    "ms": 1_000_000,
    "s": 1_000_000_000,
}

# to_duration unit multipliers in ns (reference: get_unit_multiplier,
# src/engine/time.rs:124-140)
_DURATION_UNIT_NS = {}
for _aliases, _mul in (
    (("W",), 7 * 24 * 3600 * 10**9),
    (("D", "day", "days"), 24 * 3600 * 10**9),
    (("h", "hr", "hour", "hours"), 3600 * 10**9),
    (("m", "min", "minute", "minutes"), 60 * 10**9),
    (("s", "sec", "second", "seconds"), 10**9),
    (("ms", "milli", "millis", "millisecond", "milliseconds"), 10**6),
    (("us", "micro", "micros", "microsecond", "microseconds"), 10**3),
    (("ns", "nano", "nanos", "nanosecond", "nanoseconds"), 1),
):
    for _a in _aliases:
        _DURATION_UNIT_NS[_a] = _mul


def _period_ns(p) -> int:
    """Round/floor period in exact nanoseconds: Timedelta, a composite
    duration string ('2h3min'), or a bare offset alias ('D', 'min')."""
    if isinstance(p, str):
        try:
            return int(pd.Timedelta(p).value)
        except ValueError:
            return int(pd.tseries.frequencies.to_offset(p).nanos)
    return int(_td(p).value)

# DST policy for anchoring naive wall-clock times (matches chrono's
# LocalResult handling in the reference): nonexistent times shift forward
# past the gap, ambiguous times resolve to the second (non-DST) occurrence.
_LOCALIZE = dict(nonexistent="shift_forward", ambiguous=False)


def _ts(d) -> pd.Timestamp:
    return d if isinstance(d, pd.Timestamp) else pd.Timestamp(d)


def _td(x) -> pd.Timedelta:
    return x if isinstance(x, pd.Timedelta) else pd.Timedelta(x)


def _dt_ns(d) -> int:
    """Exact nanoseconds since epoch (UTC for aware values)."""
    return int(_ts(d).value)


def _ns_of_second(d) -> int:
    return _dt_ns(d) % 1_000_000_000


def _trunc_div(v: int, unit: int) -> int:
    """Integer division truncating toward zero (chrono num_* semantics)."""
    q = abs(v) // unit
    return -q if v < 0 else q


def _strftime_one(d, fmt: str) -> str:
    """strftime with the chrono fraction extensions: %f renders 9-digit
    nanoseconds, %3f/%6f/%9f fixed widths; %% stays an escape."""
    ts = _ts(d)
    nano = f"{_ns_of_second(ts):09d}"
    out = []
    i = 0
    n = len(fmt)
    while i < n:
        c = fmt[i]
        if c == "%" and i + 1 < n:
            nxt = fmt[i + 1]
            if nxt == "%":
                out.append("%%")
                i += 2
                continue
            if nxt == "f":
                out.append(nano)
                i += 2
                continue
            if nxt in "369" and i + 2 < n and fmt[i + 2] == "f":
                out.append(nano[: int(nxt)])
                i += 3
                continue
        out.append(c)
        i += 1
    return ts.strftime("".join(out))


def _sanitize_format(fmt: str) -> str:
    """Exact port of the reference's sanitize_format_string
    (src/engine/time.rs:107): '.%f' becomes chrono's '%.f'; any remaining
    bare '%f' (not part of a '%%f' escape) is rejected. Fixed-width
    '%3f'/'%6f'/'%9f' contain no '%f' substring and pass."""
    sanitized = fmt.replace(".%f", "%.f")
    if sanitized.count("%f") != sanitized.count("%%f"):
        raise ValueError(
            f'parse error: cannot use format "{sanitized}": using '
            '"%f" without the leading dot is not supported'
        )
    return sanitized


def _strptime_one(s: str, fmt: str):
    from pathway_tpu.internals.datetime_types import _strptime

    display = _sanitize_format(fmt)
    # chrono fixed-width fractions and %:z offsets map onto python's forms
    py_fmt = (
        fmt.replace("%9f", "%f")
        .replace("%6f", "%f")
        .replace("%3f", "%f")
        .replace("%:z", "%z")
    )
    try:
        return _strptime(s, py_fmt, utc=False)
    except ValueError:
        raise ValueError(
            f'parse error: cannot parse date "{s}" using format "{display}"'
        ) from None


# --- dtype gating ----------------------------------------------------------


def _static_dtype(expr) -> dt.DType | None:
    """Best-effort dtype of an expression without an environment: direct
    column references read the table schema; typed expressions carry their
    target. None = unknown (no gating)."""
    from pathway_tpu.internals.expression import (
        CastExpression,
        ColumnReference,
        ConvertExpression,
        DeclareTypeExpression,
    )

    if isinstance(expr, ColumnReference):
        try:
            return expr.table.schema.__columns__[expr.name].dtype
        except Exception:
            return None
    if isinstance(expr, MethodCallExpression):
        rt = expr._return_type
        return rt if isinstance(rt, dt.DType) else None
    if isinstance(expr, (CastExpression, ConvertExpression, DeclareTypeExpression)):
        return expr._target
    return None


_DATETIME_KINDS = (dt.DATE_TIME_NAIVE, dt.DATE_TIME_UTC)
_NUMERIC_KINDS = (dt.INT, dt.FLOAT)


def _require(expr, kinds, method: str) -> None:
    d = _static_dtype(expr)
    if d is None:
        return
    if d.strip_optional() in kinds or d.strip_optional() in (dt.ANY,):
        return
    raise AttributeError(
        f"dt.{method} cannot be applied to a column of type "
        f"{d.strip_optional().name}"
    )


class DateTimeNamespace:
    def __init__(self, expression: ColumnExpression):
        self._expr = expression

    # --- field extraction ----------------------------------------------------

    def nanosecond(self):
        _require(self._expr, _DATETIME_KINDS, "nanosecond")
        return _m("dt.nanosecond", _ns_of_second, dt.INT, self._expr)

    def microsecond(self):
        _require(self._expr, _DATETIME_KINDS, "microsecond")
        return _m(
            "dt.microsecond",
            lambda d: _ns_of_second(d) // 1_000,
            dt.INT,
            self._expr,
        )

    def millisecond(self):
        _require(self._expr, _DATETIME_KINDS, "millisecond")
        return _m(
            "dt.millisecond",
            lambda d: _ns_of_second(d) // 1_000_000,
            dt.INT,
            self._expr,
        )

    def second(self):
        _require(self._expr, _DATETIME_KINDS, "second")
        return _m("dt.second", lambda d: _ts(d).second, dt.INT, self._expr)

    def minute(self):
        _require(self._expr, _DATETIME_KINDS, "minute")
        return _m("dt.minute", lambda d: _ts(d).minute, dt.INT, self._expr)

    def hour(self):
        _require(self._expr, _DATETIME_KINDS, "hour")
        return _m("dt.hour", lambda d: _ts(d).hour, dt.INT, self._expr)

    def day(self):
        _require(self._expr, _DATETIME_KINDS, "day")
        return _m("dt.day", lambda d: _ts(d).day, dt.INT, self._expr)

    def month(self):
        _require(self._expr, _DATETIME_KINDS, "month")
        return _m("dt.month", lambda d: _ts(d).month, dt.INT, self._expr)

    def year(self):
        _require(self._expr, _DATETIME_KINDS, "year")
        return _m("dt.year", lambda d: _ts(d).year, dt.INT, self._expr)

    def weekday(self):
        _require(self._expr, _DATETIME_KINDS, "weekday")
        return _m("dt.weekday", lambda d: _ts(d).weekday(), dt.INT, self._expr)

    def timestamp(self, unit: str | None = None):
        _require(self._expr, _DATETIME_KINDS, "timestamp")
        if unit is None:
            warnings.warn(
                "Not specyfying the `unit` argument of the `timestamp()` "
                "method is deprecated. Please specify its value. Without "
                "specifying, it will default to 'ns'.",
                DeprecationWarning,
                stacklevel=2,
            )
            return _m("dt.timestamp", _dt_ns, dt.INT, self._expr)
        div = _UNIT_NS[unit]
        # float-cast BEFORE dividing, matching the engine's int/int `/`
        # (int64 -> f64 conversion happens first there too)
        return _m(
            "dt.timestamp",
            lambda d: float(_dt_ns(d)) / float(div),
            dt.FLOAT,
            self._expr,
        )

    # --- formatting ----------------------------------------------------------

    def strftime(self, fmt):
        _require(self._expr, _DATETIME_KINDS, "strftime")
        return _m("dt.strftime", _strftime_one, dt.STR, self._expr, fmt)

    def strptime(self, fmt, contains_timezone: bool | None = None):
        _require(self._expr, (dt.STR,), "strptime")

        if contains_timezone is None:
            if isinstance(fmt, str):
                contains_timezone = any(
                    code in fmt for code in ["%z", "%:z", "%Z"]
                )
            else:
                raise ValueError(
                    "If fmt is not a string, you need to specify whether"
                    " objects contain a timezone using `contains_timezone`"
                    " parameter."
                )

        def fn(s, f, _aware=contains_timezone):
            parsed = _strptime_one(s, f)
            if parsed.tzinfo is not None:
                return DateTimeUtc.from_datetime(parsed)
            if _aware:
                # the declared dtype is UTC but the parse produced no
                # offset (e.g. %Z, which python parses without attaching
                # tzinfo) — erroring beats silently mis-typing the column
                raise ValueError(
                    f'parse error: cannot parse date "{s}" using format '
                    f'"{_sanitize_format(f)}"'
                )
            return DateTimeNaive.from_datetime(parsed)

        ret = dt.DATE_TIME_UTC if contains_timezone else dt.DATE_TIME_NAIVE
        return _m("dt.strptime", fn, ret, self._expr, fmt)

    # --- timezone ------------------------------------------------------------

    def to_utc(self, from_timezone: str):
        _require(self._expr, (dt.DATE_TIME_NAIVE,), "to_utc")

        def fn(d, tz):
            return _ts(d).tz_localize(tz, **_LOCALIZE).tz_convert("UTC")

        return _m("dt.to_utc", fn, dt.DATE_TIME_UTC, self._expr, from_timezone)

    def to_naive_in_timezone(self, timezone: str):
        _require(self._expr, (dt.DATE_TIME_UTC,), "to_naive_in_timezone")

        def fn(d, tz):
            return _ts(d).tz_convert(tz).tz_localize(None)

        return _m(
            "dt.to_naive_in_timezone", fn, dt.DATE_TIME_NAIVE, self._expr, timezone
        )

    def add_duration_in_timezone(self, duration, timezone: str):
        _require(self._expr, (dt.DATE_TIME_NAIVE,), "add_duration_in_timezone")

        def fn(d, dur, tz):
            # anchor the wall-clock time in tz, shift in absolute time,
            # read the wall clock back
            anchored = _ts(d).tz_localize(tz, **_LOCALIZE)
            return (anchored + _td(dur)).tz_convert(tz).tz_localize(None)

        return _m(
            "dt.add_duration_in_timezone",
            fn,
            dt.DATE_TIME_NAIVE,
            self._expr,
            duration,
            timezone,
        )

    def subtract_duration_in_timezone(self, duration, timezone: str):
        _require(
            self._expr, (dt.DATE_TIME_NAIVE,), "subtract_duration_in_timezone"
        )

        def fn(d, dur, tz):
            anchored = _ts(d).tz_localize(tz, **_LOCALIZE)
            return (anchored - _td(dur)).tz_convert(tz).tz_localize(None)

        return _m(
            "dt.subtract_duration_in_timezone",
            fn,
            dt.DATE_TIME_NAIVE,
            self._expr,
            duration,
            timezone,
        )

    def subtract_date_time_in_timezone(self, other, timezone: str):
        _require(
            self._expr, (dt.DATE_TIME_NAIVE,), "subtract_date_time_in_timezone"
        )

        def fn(a, b, tz):
            la = _ts(a).tz_localize(tz, **_LOCALIZE)
            lb = _ts(b).tz_localize(tz, **_LOCALIZE)
            return Duration(la - lb)

        return _m(
            "dt.subtract_date_time_in_timezone",
            fn,
            dt.DURATION,
            self._expr,
            other,
            timezone,
        )

    # --- rounding ------------------------------------------------------------

    def round(self, period):
        _require(self._expr, _DATETIME_KINDS, "round")

        def fn(d, p):
            ts = _ts(d)
            pns = _period_ns(p)
            ns = int(ts.value)
            # chrono duration_round: nearest multiple, ties toward +inf
            # (floor division makes (ns + pns//2)//pns match for both signs)
            return pd.Timestamp(
                ((ns + pns // 2) // pns) * pns, unit="ns", tz=ts.tzinfo
            )

        return _m("dt.round", fn, dt.ANY, self._expr, period)

    def floor(self, period):
        _require(self._expr, _DATETIME_KINDS, "floor")

        def fn(d, p):
            ts = _ts(d)
            pns = _period_ns(p)
            # chrono duration_trunc floors toward -inf (pre-epoch included,
            # fixed in chrono 0.4.25) — python floor division matches
            return pd.Timestamp(
                (int(ts.value) // pns) * pns, unit="ns", tz=ts.tzinfo
            )

        return _m("dt.floor", fn, dt.ANY, self._expr, period)

    # --- duration fields -----------------------------------------------------

    def to_duration(self, unit):
        _require(self._expr, _NUMERIC_KINDS, "to_duration")

        def fn(x, u):
            mul = _DURATION_UNIT_NS.get(u)
            if mul is None:
                raise ValueError(
                    f'unit has to be a valid time unit but is "{u}"'
                )
            # exact i64 multiply for ints (reference get_unit_multiplier)
            if isinstance(x, float):
                return Duration(int(x * mul), unit="ns")
            return Duration(int(x) * mul, unit="ns")

        return _m("dt.to_duration", fn, dt.DURATION, self._expr, unit)

    def nanoseconds(self):
        _require(self._expr, (dt.DURATION,), "nanoseconds")
        return _m(
            "dt.nanoseconds", lambda td: int(_td(td).value), dt.INT, self._expr
        )

    def microseconds(self):
        _require(self._expr, (dt.DURATION,), "microseconds")
        return _m(
            "dt.microseconds",
            lambda td: _trunc_div(int(_td(td).value), 1_000),
            dt.INT,
            self._expr,
        )

    def milliseconds(self):
        _require(self._expr, (dt.DURATION,), "milliseconds")
        return _m(
            "dt.milliseconds",
            lambda td: _trunc_div(int(_td(td).value), 1_000_000),
            dt.INT,
            self._expr,
        )

    def seconds(self):
        _require(self._expr, (dt.DURATION,), "seconds")
        return _m(
            "dt.seconds",
            lambda td: _trunc_div(int(_td(td).value), 1_000_000_000),
            dt.INT,
            self._expr,
        )

    def minutes(self):
        _require(self._expr, (dt.DURATION,), "minutes")
        return _m(
            "dt.minutes",
            lambda td: _trunc_div(int(_td(td).value), 60 * 1_000_000_000),
            dt.INT,
            self._expr,
        )

    def hours(self):
        _require(self._expr, (dt.DURATION,), "hours")
        return _m(
            "dt.hours",
            lambda td: _trunc_div(int(_td(td).value), 3600 * 1_000_000_000),
            dt.INT,
            self._expr,
        )

    def days(self):
        _require(self._expr, (dt.DURATION,), "days")
        return _m(
            "dt.days",
            lambda td: _trunc_div(int(_td(td).value), 86400 * 1_000_000_000),
            dt.INT,
            self._expr,
        )

    def weeks(self):
        _require(self._expr, (dt.DURATION,), "weeks")
        return _m(
            "dt.weeks",
            lambda td: _trunc_div(int(_td(td).value), 7 * 86400 * 1_000_000_000),
            dt.INT,
            self._expr,
        )

    # --- from timestamp ------------------------------------------------------

    def from_timestamp(self, unit: str):
        _require(self._expr, _NUMERIC_KINDS, "from_timestamp")
        mul = _UNIT_NS[unit]
        return _m(
            "dt.from_timestamp",
            lambda x: pd.Timestamp(int(x * mul), unit="ns"),
            dt.DATE_TIME_NAIVE,
            self._expr,
        )

    def utc_from_timestamp(self, unit: str):
        _require(self._expr, _NUMERIC_KINDS, "utc_from_timestamp")
        mul = _UNIT_NS[unit]
        return _m(
            "dt.utc_from_timestamp",
            lambda x: pd.Timestamp(int(x * mul), unit="ns", tz="UTC"),
            dt.DATE_TIME_UTC,
            self._expr,
        )
