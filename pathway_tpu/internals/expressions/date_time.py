"""`.dt` column namespace
(reference surface: python/pathway/internals/expressions/date_time.py; the
reference implements these in Rust over chrono, src/engine/time.rs)."""

from __future__ import annotations

import datetime

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.datetime_types import (
    DateTimeNaive,
    DateTimeUtc,
    Duration,
)
from pathway_tpu.internals.expression import ColumnExpression, MethodCallExpression


def _m(name, fn, ret, *args):
    return MethodCallExpression(name, fn, ret, *args)


_UNIT_NS = {
    "ns": 1,
    "us": 1_000,
    "ms": 1_000_000,
    "s": 1_000_000_000,
}


def _dt_ns(d: datetime.datetime) -> int:
    if d.tzinfo is None:
        epoch = datetime.datetime(1970, 1, 1)
        return int((d - epoch) / datetime.timedelta(microseconds=1)) * 1000
    return int(d.timestamp() * 1_000_000) * 1000


def _parse_duration_str(freq: str) -> datetime.timedelta:
    import re

    m = re.fullmatch(r"\s*(\d+(?:\.\d+)?)\s*([a-zA-Z]+)\s*", freq)
    if not m:
        raise ValueError(f"cannot parse duration {freq!r}")
    qty = float(m.group(1))
    unit = m.group(2).lower()
    table = {
        "ns": 1e-9,
        "us": 1e-6,
        "ms": 1e-3,
        "s": 1.0,
        "sec": 1.0,
        "min": 60.0,
        "t": 60.0,
        "h": 3600.0,
        "hr": 3600.0,
        "d": 86400.0,
        "day": 86400.0,
        "w": 604800.0,
    }
    if unit not in table:
        raise ValueError(f"unknown duration unit {unit!r}")
    return datetime.timedelta(seconds=qty * table[unit])


class DateTimeNamespace:
    def __init__(self, expression: ColumnExpression):
        self._expr = expression

    # --- field extraction ----------------------------------------------------

    def nanosecond(self):
        return _m("dt.nanosecond", lambda d: (_dt_ns(d)) % 1_000_000_000, dt.INT, self._expr)

    def microsecond(self):
        return _m("dt.microsecond", lambda d: d.microsecond, dt.INT, self._expr)

    def millisecond(self):
        return _m("dt.millisecond", lambda d: d.microsecond // 1000, dt.INT, self._expr)

    def second(self):
        return _m("dt.second", lambda d: d.second, dt.INT, self._expr)

    def minute(self):
        return _m("dt.minute", lambda d: d.minute, dt.INT, self._expr)

    def hour(self):
        return _m("dt.hour", lambda d: d.hour, dt.INT, self._expr)

    def day(self):
        return _m("dt.day", lambda d: d.day, dt.INT, self._expr)

    def month(self):
        return _m("dt.month", lambda d: d.month, dt.INT, self._expr)

    def year(self):
        return _m("dt.year", lambda d: d.year, dt.INT, self._expr)

    def weekday(self):
        return _m("dt.weekday", lambda d: d.weekday(), dt.INT, self._expr)

    def timestamp(self, unit: str | None = None):
        if unit is None:
            return _m("dt.timestamp", _dt_ns, dt.INT, self._expr)
        div = _UNIT_NS[unit]
        return _m(
            "dt.timestamp", lambda d: _dt_ns(d) / div, dt.FLOAT, self._expr
        )

    # --- formatting ----------------------------------------------------------

    def strftime(self, fmt):
        return _m(
            "dt.strftime", lambda d, f: d.strftime(f), dt.STR, self._expr, fmt
        )

    def strptime(self, fmt, contains_timezone: bool | None = None):
        def fn(s, f):
            from pathway_tpu.internals.datetime_types import _strptime

            # %f accepts nanosecond fractions (reference chrono semantics)
            parsed = _strptime(s, f, utc=False)
            if parsed.tzinfo is not None:
                return DateTimeUtc.from_datetime(parsed)
            return DateTimeNaive.from_datetime(parsed)

        ret = dt.DATE_TIME_UTC if contains_timezone else dt.DATE_TIME_NAIVE
        return _m("dt.strptime", fn, ret, self._expr, fmt)

    # --- timezone ------------------------------------------------------------

    def to_utc(self, from_timezone: str):
        from zoneinfo import ZoneInfo

        def fn(d, tz):
            return DateTimeUtc.from_datetime(d.replace(tzinfo=ZoneInfo(tz)))

        return _m("dt.to_utc", fn, dt.DATE_TIME_UTC, self._expr, from_timezone)

    def to_naive_in_timezone(self, timezone: str):
        from zoneinfo import ZoneInfo

        def fn(d, tz):
            return DateTimeNaive.from_datetime(
                d.astimezone(ZoneInfo(tz)).replace(tzinfo=None)
            )

        return _m(
            "dt.to_naive_in_timezone", fn, dt.DATE_TIME_NAIVE, self._expr, timezone
        )

    def add_duration_in_timezone(self, duration, timezone: str):
        from zoneinfo import ZoneInfo

        def fn(d, dur, tz):
            zone = ZoneInfo(tz)
            local = d.astimezone(zone)
            return DateTimeUtc.from_datetime(
                (local.replace(tzinfo=None) + dur).replace(tzinfo=zone)
            )

        return _m(
            "dt.add_duration_in_timezone",
            fn,
            dt.DATE_TIME_UTC,
            self._expr,
            duration,
            timezone,
        )

    def subtract_duration_in_timezone(self, duration, timezone: str):
        from zoneinfo import ZoneInfo

        def fn(d, dur, tz):
            zone = ZoneInfo(tz)
            local = d.astimezone(zone)
            return DateTimeUtc.from_datetime(
                (local.replace(tzinfo=None) - dur).replace(tzinfo=zone)
            )

        return _m(
            "dt.subtract_duration_in_timezone",
            fn,
            dt.DATE_TIME_UTC,
            self._expr,
            duration,
            timezone,
        )

    def subtract_date_time_in_timezone(self, other, timezone: str):
        from zoneinfo import ZoneInfo

        def fn(a, b, tz):
            zone = ZoneInfo(tz)
            la = a.astimezone(zone).replace(tzinfo=None)
            lb = b.astimezone(zone).replace(tzinfo=None)
            return Duration.from_timedelta(la - lb)

        return _m(
            "dt.subtract_date_time_in_timezone",
            fn,
            dt.DURATION,
            self._expr,
            other,
            timezone,
        )

    # --- rounding ------------------------------------------------------------

    def round(self, period):
        def fn(d, p):
            if isinstance(p, str):
                p = _parse_duration_str(p)
            ns = _dt_ns(d)
            pns = int(p.total_seconds() * 1e9)
            rounded = ((ns + pns // 2) // pns) * pns
            return _from_ns(rounded, aware=d.tzinfo is not None)

        return _m("dt.round", fn, dt.ANY, self._expr, period)

    def floor(self, period):
        def fn(d, p):
            if isinstance(p, str):
                p = _parse_duration_str(p)
            ns = _dt_ns(d)
            pns = int(p.total_seconds() * 1e9)
            return _from_ns((ns // pns) * pns, aware=d.tzinfo is not None)

        return _m("dt.floor", fn, dt.ANY, self._expr, period)

    # --- duration fields -----------------------------------------------------

    def to_duration(self, unit):
        def fn(x, u):
            return Duration.from_timedelta(
                datetime.timedelta(seconds=x * _UNIT_NS[u] / 1e9)
                if u in _UNIT_NS
                else _parse_duration_str(f"{x}{u}")
            )

        return _m("dt.to_duration", fn, dt.DURATION, self._expr, unit)

    def nanoseconds(self):
        return _m(
            "dt.nanoseconds",
            lambda td: int(td.total_seconds() * 1e9),
            dt.INT,
            self._expr,
        )

    def microseconds(self):
        return _m(
            "dt.microseconds",
            lambda td: int(td.total_seconds() * 1e6),
            dt.INT,
            self._expr,
        )

    def milliseconds(self):
        return _m(
            "dt.milliseconds",
            lambda td: int(td.total_seconds() * 1e3),
            dt.INT,
            self._expr,
        )

    def seconds(self):
        return _m(
            "dt.seconds", lambda td: int(td.total_seconds()), dt.INT, self._expr
        )

    def minutes(self):
        return _m(
            "dt.minutes", lambda td: int(td.total_seconds() // 60), dt.INT, self._expr
        )

    def hours(self):
        return _m(
            "dt.hours", lambda td: int(td.total_seconds() // 3600), dt.INT, self._expr
        )

    def days(self):
        return _m(
            "dt.days", lambda td: int(td.total_seconds() // 86400), dt.INT, self._expr
        )

    def weeks(self):
        return _m(
            "dt.weeks", lambda td: int(td.total_seconds() // 604800), dt.INT, self._expr
        )

    # --- from timestamp ------------------------------------------------------

    def from_timestamp(self, unit: str):
        mul = _UNIT_NS[unit]
        return _m(
            "dt.from_timestamp",
            lambda x: _from_ns(int(x * mul), aware=False),
            dt.DATE_TIME_NAIVE,
            self._expr,
        )

    def utc_from_timestamp(self, unit: str):
        mul = _UNIT_NS[unit]
        return _m(
            "dt.utc_from_timestamp",
            lambda x: _from_ns(int(x * mul), aware=True),
            dt.DATE_TIME_UTC,
            self._expr,
        )


def _from_ns(ns: int, aware: bool):
    base = datetime.datetime(
        1970, 1, 1, tzinfo=datetime.timezone.utc if aware else None
    ) + datetime.timedelta(microseconds=ns // 1000)
    if aware:
        return DateTimeUtc.from_datetime(base)
    return DateTimeNaive.from_datetime(base)
