"""`.str` and `.bin` column namespaces
(reference surface: python/pathway/internals/expressions/string.py)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import (
    ColumnExpression,
    MethodCallExpression,
)


def _m(name, fn, ret, *args, vector_fn=None):
    return MethodCallExpression(name, fn, ret, *args, vector_fn=vector_fn)


class StringNamespace:
    def __init__(self, expression: ColumnExpression):
        self._expr = expression

    def lower(self):
        return _m("str.lower", lambda s: s.lower(), dt.STR, self._expr)

    def upper(self):
        return _m("str.upper", lambda s: s.upper(), dt.STR, self._expr)

    def reversed(self):
        return _m("str.reversed", lambda s: s[::-1], dt.STR, self._expr)

    def len(self):
        return _m("str.len", lambda s: len(s), dt.INT, self._expr)

    def replace(self, old_value, new_value, count=-1):
        return _m(
            "str.replace",
            lambda s, o, n, c: s.replace(o, n, c),
            dt.STR,
            self._expr,
            old_value,
            new_value,
            count,
        )

    def startswith(self, prefix):
        return _m(
            "str.startswith", lambda s, p: s.startswith(p), dt.BOOL, self._expr, prefix
        )

    def endswith(self, suffix):
        return _m(
            "str.endswith", lambda s, p: s.endswith(p), dt.BOOL, self._expr, suffix
        )

    def swapcase(self):
        return _m("str.swapcase", lambda s: s.swapcase(), dt.STR, self._expr)

    def strip(self, chars=None):
        return _m("str.strip", lambda s, c: s.strip(c), dt.STR, self._expr, chars)

    def title(self):
        return _m("str.title", lambda s: s.title(), dt.STR, self._expr)

    def count(self, sub, start=None, end=None):
        return _m(
            "str.count",
            lambda s, sub_, st, en: s.count(
                sub_, st if st is not None else 0, en if en is not None else len(s)
            ),
            dt.INT,
            self._expr,
            sub,
            start,
            end,
        )

    def find(self, sub, start=None, end=None):
        return _m(
            "str.find",
            lambda s, sub_, st, en: s.find(
                sub_, st if st is not None else 0, en if en is not None else len(s)
            ),
            dt.INT,
            self._expr,
            sub,
            start,
            end,
        )

    def rfind(self, sub, start=None, end=None):
        return _m(
            "str.rfind",
            lambda s, sub_, st, en: s.rfind(
                sub_, st if st is not None else 0, en if en is not None else len(s)
            ),
            dt.INT,
            self._expr,
            sub,
            start,
            end,
        )

    def removeprefix(self, prefix):
        return _m(
            "str.removeprefix",
            lambda s, p: s.removeprefix(p),
            dt.STR,
            self._expr,
            prefix,
        )

    def removesuffix(self, suffix):
        return _m(
            "str.removesuffix",
            lambda s, p: s.removesuffix(p),
            dt.STR,
            self._expr,
            suffix,
        )

    def slice(self, start, end):
        return _m(
            "str.slice", lambda s, a, b: s[a:b], dt.STR, self._expr, start, end
        )

    def split(self, sep=None, maxsplit=-1):
        return _m(
            "str.split",
            lambda s, sp, m: tuple(s.split(sp, m)),
            dt.ANY_TUPLE,
            self._expr,
            sep,
            maxsplit,
        )

    def parse_int(self, optional: bool = False):
        ret = dt.Optional_(dt.INT) if optional else dt.INT
        if optional:

            def fn(s):
                try:
                    return int(s)
                except (ValueError, TypeError):
                    return None

        else:

            def fn(s):
                try:
                    return int(s)
                except (ValueError, TypeError):
                    from pathway_tpu.internals.errors import EngineError

                    # reference wording (rust i64::from_str error)
                    raise EngineError(
                        f'parse error: cannot parse "{s}" to int: '
                        "invalid digit found in string"
                    )

        return _m("str.parse_int", fn, ret, self._expr)

    def parse_float(self, optional: bool = False):
        ret = dt.Optional_(dt.FLOAT) if optional else dt.FLOAT
        if optional:

            def fn(s):
                try:
                    return float(s)
                except (ValueError, TypeError):
                    return None

        else:
            fn = float
        return _m("str.parse_float", fn, ret, self._expr)

    def parse_bool(
        self,
        true_values=("on", "true", "yes", "1"),
        false_values=("off", "false", "no", "0"),
        optional: bool = False,
    ):
        true_set = {str(v).lower() for v in true_values}
        false_set = {str(v).lower() for v in false_values}

        def fn(s):
            ls = s.lower()
            if ls in true_set:
                return True
            if ls in false_set:
                return False
            if optional:
                return None
            raise ValueError(f"cannot parse {s!r} as bool")

        ret = dt.Optional_(dt.BOOL) if optional else dt.BOOL
        return _m("str.parse_bool", fn, ret, self._expr)

    def to_bytes(self, encoding: str = "utf-8"):
        return _m(
            "str.to_bytes", lambda s, e: s.encode(e), dt.BYTES, self._expr, encoding
        )


class BytesNamespace:
    def __init__(self, expression: ColumnExpression):
        self._expr = expression

    def to_str(self, encoding: str = "utf-8"):
        return _m(
            "bin.to_str", lambda b, e: b.decode(e), dt.STR, self._expr, encoding
        )

    def len(self):
        return _m("bin.len", lambda b: len(b), dt.INT, self._expr)

    def base64_encode(self):
        import base64

        return _m(
            "bin.base64_encode", lambda b: base64.b64encode(b), dt.BYTES, self._expr
        )

    def base64_decode(self):
        import base64

        return _m(
            "bin.base64_decode", lambda b: base64.b64decode(b), dt.BYTES, self._expr
        )
