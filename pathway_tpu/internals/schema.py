"""Schema: static typing of tables.

Capability parity with the reference Schema metaclass
(/root/reference/python/pathway/internals/schema.py:955-ish): class-syntax
schemas with annotations, `column_definition` (primary keys, defaults),
`schema_from_types` / `schema_from_dict` / `schema_from_csv`, `schema_builder`,
plus schema algebra (`|`, `with_types`, `without`, ...).
"""

from __future__ import annotations

import csv as _csv
import typing
from dataclasses import dataclass, field
from typing import Any, Iterable, Mapping

from pathway_tpu.internals import dtype as dt


_NO_DEFAULT = object()


@dataclass
class ColumnDefinition:
    primary_key: bool = False
    default_value: Any = _NO_DEFAULT
    dtype: Any = None
    name: str | None = None
    append_only: bool | None = None
    description: str | None = None
    example: Any = None

    @property
    def has_default_value(self) -> bool:
        return self.default_value is not _NO_DEFAULT


def column_definition(
    *,
    primary_key: bool = False,
    default_value: Any = _NO_DEFAULT,
    dtype: Any = None,
    name: str | None = None,
    append_only: bool | None = None,
    description: str | None = None,
    example: Any = None,
) -> Any:
    return ColumnDefinition(
        primary_key=primary_key,
        default_value=default_value,
        dtype=dtype,
        name=name,
        append_only=append_only,
        description=description,
        example=example,
    )


@dataclass
class ColumnSchema:
    name: str
    dtype: dt.DType
    primary_key: bool = False
    default_value: Any = _NO_DEFAULT
    append_only: bool = False
    description: str | None = None

    @property
    def has_default_value(self) -> bool:
        return self.default_value is not _NO_DEFAULT


@dataclass
class SchemaProperties:
    append_only: bool | None = None


class SchemaMetaclass(type):
    __columns__: dict[str, ColumnSchema]
    __append_only__: bool

    def __eq__(cls, other: object) -> bool:
        # schemas are equal when their column names, dtypes and primary
        # keys agree (reference: Schema equality is structural)
        if not isinstance(other, SchemaMetaclass):
            return NotImplemented
        return [
            (n, c.dtype, c.primary_key) for n, c in cls.__columns__.items()
        ] == [
            (n, c.dtype, c.primary_key)
            for n, c in other.__columns__.items()
        ]

    def __hash__(cls) -> int:
        return hash(tuple(cls.__columns__.keys()))

    def __init__(cls, name, bases, namespace, append_only: bool | None = None):
        super().__init__(name, bases, namespace)
        columns: dict[str, ColumnSchema] = {}
        for base in bases:
            if hasattr(base, "__columns__"):
                columns.update(base.__columns__)  # type: ignore[attr-defined]
        hints = {}
        try:
            hints = typing.get_type_hints(cls)
        except Exception:
            hints = dict(namespace.get("__annotations__", {}))
        for col_name, hint in namespace.get("__annotations__", {}).items():
            if col_name.startswith("__"):
                continue
            resolved = hints.get(col_name, hint)
            definition = namespace.get(col_name, None)
            if isinstance(definition, ColumnDefinition):
                out_name = definition.name or col_name
                columns[out_name] = ColumnSchema(
                    name=out_name,
                    dtype=dt.wrap(definition.dtype or resolved),
                    primary_key=definition.primary_key,
                    default_value=definition.default_value,
                    append_only=bool(definition.append_only),
                    description=definition.description,
                )
            else:
                columns[col_name] = ColumnSchema(name=col_name, dtype=dt.wrap(resolved))
        cls.__columns__ = columns
        cls.__append_only__ = bool(append_only)

    def __or__(cls, other: "SchemaMetaclass") -> "SchemaMetaclass":
        columns = {**cls.__columns__, **other.__columns__}
        return schema_from_columns(columns, name=f"{cls.__name__}|{other.__name__}")

    def column_names(cls) -> list[str]:
        return list(cls.__columns__.keys())

    def columns(cls) -> dict[str, ColumnSchema]:
        return dict(cls.__columns__)

    def keys(cls) -> list[str]:
        return list(cls.__columns__.keys())

    def primary_key_columns(cls) -> list[str] | None:
        pks = [c.name for c in cls.__columns__.values() if c.primary_key]
        return pks or None

    def typehints(cls) -> dict[str, Any]:
        return {name: c.dtype.typehint for name, c in cls.__columns__.items()}

    def dtypes(cls) -> dict[str, dt.DType]:
        return {name: c.dtype for name, c in cls.__columns__.items()}

    # reference spelling used by tests (schema._dtypes())
    _dtypes = dtypes

    @property
    def id(cls) -> ColumnSchema:
        """Type of the table's id column (reference: schema.id —
        parametrized by the grouping columns for groupby outputs)."""
        id_dtype = getattr(cls, "__id_dtype__", None) or dt.POINTER
        return ColumnSchema(name="id", dtype=id_dtype)

    def __getitem__(cls, name: str) -> ColumnSchema:
        return cls.__columns__[name]

    def with_types(cls, **kwargs: Any) -> "SchemaMetaclass":
        columns = dict(cls.__columns__)
        for name, hint in kwargs.items():
            if name not in columns:
                raise ValueError(f"column {name!r} not present in schema")
            old = columns[name]
            columns[name] = ColumnSchema(
                name=name,
                dtype=dt.wrap(hint),
                primary_key=old.primary_key,
                default_value=old.default_value,
                append_only=old.append_only,
            )
        return schema_from_columns(columns, name=cls.__name__)

    def without(cls, *names: Any) -> "SchemaMetaclass":
        drop = {n if isinstance(n, str) else n.name for n in names}
        columns = {k: v for k, v in cls.__columns__.items() if k not in drop}
        return schema_from_columns(columns, name=cls.__name__)

    def update_properties(cls, **kwargs: Any) -> "SchemaMetaclass":
        return cls

    def universe_properties(cls) -> SchemaProperties:
        return SchemaProperties(append_only=cls.__append_only__)

    def __repr__(cls) -> str:
        cols = ", ".join(f"{n}: {c.dtype}" for n, c in cls.__columns__.items())
        return f"<pw.Schema {cls.__name__}({cols})>"


class Schema(metaclass=SchemaMetaclass):
    """Base class for user-declared schemas:

    >>> class InputSchema(pw.Schema):
    ...     name: str
    ...     age: int
    """

    def __init_subclass__(cls, append_only: bool | None = None, **kwargs):
        super().__init_subclass__(**kwargs)


def schema_from_columns(
    columns: Mapping[str, ColumnSchema], name: str = "Schema"
) -> SchemaMetaclass:
    namespace: dict[str, Any] = {"__annotations__": {}}
    cls = SchemaMetaclass(name, (Schema,), namespace)
    cls.__columns__ = dict(columns)
    return cls


def schema_from_types(_name: str = "Schema", **kwargs: Any) -> SchemaMetaclass:
    columns = {k: ColumnSchema(name=k, dtype=dt.wrap(v)) for k, v in kwargs.items()}
    return schema_from_columns(columns, name=_name)


def schema_from_dict(
    columns: Mapping[str, Any],
    *,
    name: str = "Schema",
    properties: SchemaProperties | None = None,
) -> SchemaMetaclass:
    out: dict[str, ColumnSchema] = {}
    for k, v in columns.items():
        if isinstance(v, ColumnDefinition):
            out[k] = ColumnSchema(
                name=k,
                dtype=dt.wrap(v.dtype),
                primary_key=v.primary_key,
                default_value=v.default_value,
            )
        elif isinstance(v, dict):
            out[k] = ColumnSchema(
                name=k,
                dtype=dt.wrap(v.get("dtype")),
                primary_key=bool(v.get("primary_key", False)),
                default_value=v.get("default_value", _NO_DEFAULT),
            )
        else:
            out[k] = ColumnSchema(name=k, dtype=dt.wrap(v))
    return schema_from_columns(out, name=name)


def schema_builder(
    columns: Mapping[str, ColumnDefinition],
    *,
    name: str = "Schema",
    properties: SchemaProperties | None = None,
) -> SchemaMetaclass:
    out = {}
    for k, v in columns.items():
        if not isinstance(v, ColumnDefinition):
            v = ColumnDefinition(dtype=v)
        out[k] = ColumnSchema(
            name=v.name or k,
            dtype=dt.wrap(v.dtype if v.dtype is not None else Any),
            primary_key=v.primary_key,
            default_value=v.default_value,
        )
    return schema_from_columns(out, name=name)


_CSV_TYPES = [int, float, bool, str]


def _infer_csv_type(values: list[str]) -> Any:
    def ok(cast):
        for v in values:
            if v == "":
                continue
            try:
                if cast is bool:
                    if v.lower() not in ("true", "false", "0", "1"):
                        return False
                else:
                    cast(v)
            except ValueError:
                return False
        return True

    if ok(int):
        return int
    if ok(float):
        return float
    if ok(bool):
        return bool
    return str


def schema_from_csv(
    path: str,
    *,
    name: str = "Schema",
    properties: SchemaProperties | None = None,
    delimiter: str = ",",
    quote: str = '"',
    comment_character: str | None = None,
    escape: str | None = None,
    double_quote_escapes: bool = True,
    num_parsed_rows: int | None = None,
) -> SchemaMetaclass:
    with open(path, newline="") as f:
        reader = _csv.reader(f, delimiter=delimiter, quotechar=quote)
        rows = []
        header: list[str] | None = None
        for row in reader:
            if comment_character and row and row[0].startswith(comment_character):
                continue
            if header is None:
                header = row
                continue
            rows.append(row)
            if num_parsed_rows is not None and len(rows) >= num_parsed_rows:
                break
    assert header is not None, "empty csv"
    columns = {}
    for i, col in enumerate(header):
        values = [r[i] for r in rows if i < len(r)]
        columns[col] = ColumnSchema(name=col, dtype=dt.wrap(_infer_csv_type(values)))
    return schema_from_columns(columns, name=name)


def assert_table_has_schema(
    table: Any,
    schema: SchemaMetaclass,
    *,
    allow_superset: bool = True,
    ignore_primary_keys: bool = True,
) -> None:
    table_dtypes = table.schema.dtypes()
    for col, cdt in schema.dtypes().items():
        if col not in table_dtypes:
            raise AssertionError(f"table is missing column {col!r}")
        if not dt.is_compatible(table_dtypes[col], cdt) and not dt.is_compatible(
            cdt, table_dtypes[col]
        ):
            raise AssertionError(
                f"column {col!r} has dtype {table_dtypes[col]}, expected {cdt}"
            )
    if not allow_superset:
        extra = set(table_dtypes) - set(schema.dtypes())
        if extra:
            raise AssertionError(f"table has extra columns: {sorted(extra)}")


def is_subschema(left: SchemaMetaclass, right: SchemaMetaclass) -> bool:
    rd = right.dtypes()
    for col, cdt in left.dtypes().items():
        if col not in rd or not dt.is_compatible(cdt, rd[col]):
            return False
    return True
