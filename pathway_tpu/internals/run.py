"""pw.run / pw.run_all (reference: python/pathway/internals/run.py:12,
GraphRunner internals/graph_runner/__init__.py:36)."""

from __future__ import annotations

import threading
from typing import Any

from pathway_tpu.engine.runtime import Runtime
from pathway_tpu.internals import parse_graph


class MonitoringLevel:
    AUTO = "auto"
    AUTO_ALL = "auto_all"
    NONE = "none"
    IN_OUT = "in_out"
    ALL = "all"


def run(
    *,
    debug: bool = False,
    monitoring_level: Any = MonitoringLevel.AUTO,
    with_http_server: bool = False,
    default_logging: bool = True,
    persistence_config: Any = None,
    runtime_typechecking: bool | None = None,
    license_key: str | None = None,
    terminate_on_error: bool = True,
    autocommit_duration_ms: int = 50,
    **kwargs: Any,
) -> None:
    """Execute the dataflow declared so far (all registered outputs)."""
    G = parse_graph.G
    if not G.outputs:
        return
    runtime = Runtime(G.outputs, autocommit_ms=autocommit_duration_ms)
    G.runtime = runtime
    if persistence_config is not None:
        from pathway_tpu.persistence._runtime_glue import attach_persistence

        attach_persistence(runtime, persistence_config)
    if with_http_server or monitoring_level in (
        MonitoringLevel.ALL,
        MonitoringLevel.IN_OUT,
    ):
        try:
            from pathway_tpu.internals.monitoring_server import start_http_server

            start_http_server(runtime)
        except Exception:
            pass
    try:
        runtime.run()
    finally:
        G.runtime = None
        for hook in G.post_run_hooks:
            try:
                hook()
            except Exception:
                pass


def run_all(**kwargs: Any) -> None:
    run(**kwargs)
