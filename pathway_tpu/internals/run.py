"""pw.run / pw.run_all (reference: python/pathway/internals/run.py:12,
GraphRunner internals/graph_runner/__init__.py:36)."""

from __future__ import annotations

import threading
from typing import Any

from pathway_tpu.engine.runtime import Runtime
from pathway_tpu.internals import parse_graph


class MonitoringLevel:
    AUTO = "auto"
    AUTO_ALL = "auto_all"
    NONE = "none"
    IN_OUT = "in_out"
    ALL = "all"


# set by `python -m pathway_tpu.analysis`: the script's pw.run() calls
# declare the graph but never build a Runtime
_build_only = False


def run(
    *,
    debug: bool = False,
    monitoring_level: Any = MonitoringLevel.AUTO,
    with_http_server: bool = False,
    default_logging: bool = True,
    persistence_config: Any = None,
    runtime_typechecking: bool | None = None,
    license_key: str | None = None,
    terminate_on_error: bool = True,
    autocommit_duration_ms: int = 50,
    diagnostics: str | None = None,
    **kwargs: Any,
) -> None:
    """Execute the dataflow declared so far (all registered outputs).

    ``diagnostics`` runs the Graph Doctor (pathway_tpu.analysis) over the
    declared graph before the engine starts: ``"warn"`` logs findings,
    ``"error"`` raises GraphDoctorError on warning-or-worse findings so
    not a single batch executes, ``"off"``/None skips the pass.
    """
    if _build_only:
        return
    G = parse_graph.G
    seeds = list(G.outputs)
    if kwargs.pop("_all_nodes", False):
        from pathway_tpu.engine import nodes as _nodes

        seeds += _nodes.ALL_NODES
    if not seeds:
        return
    if diagnostics not in (None, "off"):
        from pathway_tpu.analysis import check_before_run

        check_before_run(seeds, diagnostics)
    # join the process group when `pathway spawn -n N` launched us
    # (reference env contract PATHWAY_PROCESSES/PROCESS_ID, config.rs:88).
    # The engine's multi-process transport is the host mesh (TCP, DCN
    # rung) — the Runtime joins it itself; the jax.distributed device
    # group is only needed for cross-process device collectives (sharded
    # KNN/embed) and is joined when PATHWAY_JAX_DISTRIBUTED=1.
    import os as _os

    from pathway_tpu.parallel.host_exchange import dcn_active

    if not dcn_active() or _os.environ.get("PATHWAY_JAX_DISTRIBUTED") == "1":
        from pathway_tpu.parallel.distributed import maybe_initialize

        maybe_initialize()
    else:
        import logging

        logging.getLogger("pathway_tpu").warning(
            "multi-process engine: host-row exchange active; cross-process "
            "DEVICE collectives (sharded KNN/embed over jax.distributed) "
            "are disabled — set PATHWAY_JAX_DISTRIBUTED=1 to join the "
            "device group as well"
        )
    runtime = Runtime(seeds, autocommit_ms=autocommit_duration_ms)
    G.runtime = runtime
    G.last_runtime = runtime
    if persistence_config is None:
        # record/replay debugging via env (reference: PATHWAY_REPLAY_STORAGE,
        # internals/config.py:64-97 + `pathway spawn --record`)
        from pathway_tpu.internals.config import get_pathway_config

        pw_cfg = get_pathway_config()
        if pw_cfg.replay_storage:
            from pathway_tpu import persistence as _p

            persistence_config = _p.Config(
                backend=_p.Backend.filesystem(pw_cfg.replay_storage),
                snapshot_access=pw_cfg.snapshot_access or "record",
            )
    if persistence_config is not None:
        from pathway_tpu.persistence._runtime_glue import attach_persistence

        attach_persistence(runtime, persistence_config)
    if with_http_server or monitoring_level in (
        MonitoringLevel.ALL,
        MonitoringLevel.IN_OUT,
    ):
        try:
            from pathway_tpu.internals.monitoring_server import start_http_server

            start_http_server(runtime)
        except Exception:
            pass
    monitor = None
    import sys as _sys

    want_tui = monitoring_level in (MonitoringLevel.ALL, MonitoringLevel.IN_OUT) or (
        monitoring_level in (MonitoringLevel.AUTO, MonitoringLevel.AUTO_ALL)
        and _sys.stdout.isatty()
    )
    if want_tui:
        try:
            from pathway_tpu.internals.monitoring import StatsMonitor

            monitor = StatsMonitor(runtime)
            monitor.start()
        except Exception:
            monitor = None
    from pathway_tpu.internals.telemetry import get_telemetry

    from pathway_tpu.internals import errors as _errors

    err_pos = _errors.error_count()
    try:
        with get_telemetry().span(
            "pathway.run", nodes=len(runtime.order)
        ):
            runtime.run()
        if terminate_on_error:
            first = _errors.first_exception_since(err_pos)
            if first is not None:
                # surface the first runtime error with its original type
                # (reference: terminate_on_error=true run semantics,
                # python_api.rs:3329)
                if isinstance(first, BaseException):
                    raise first
                raise RuntimeError(first)
    finally:
        if monitor is not None:
            monitor.stop()
        G.runtime = None
        for hook in G.post_run_hooks:
            try:
                hook()
            except Exception:
                pass


def run_all(**kwargs: Any) -> None:
    """Execute the ENTIRE declared graph, including nodes with no
    registered output (reference: GraphRunner run_all vs run_outputs)."""
    run(_all_nodes=True, **kwargs)
