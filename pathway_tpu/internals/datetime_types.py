"""DateTimeNaive / DateTimeUtc / Duration (reference:
python/pathway/internals/datetime_types.py; engine side: src/engine/time.rs
over chrono).

The reference subclasses pandas Timestamp/Timedelta. pandas 3 ignores the
subclass in ``Timestamp.__new__`` (every construction path returns a plain
``Timestamp``), so the datetime types here are *virtual*: calling
``DateTimeNaive(...)`` validates and returns a ``pd.Timestamp``, and
``isinstance(value, DateTimeNaive)`` is metaclass-routed (naive ⇔ no tzinfo).
Values therefore interoperate with everything pandas/stdlib, carry nanosecond
precision, and still satisfy the type checks user code writes against the
reference API. ``Duration`` genuinely subclasses ``pd.Timedelta`` (which does
honor subclasses); ``.value`` is nanoseconds everywhere.
"""

from __future__ import annotations

import datetime
from typing import Any

import numpy as np
import pandas as pd


class _TimestampTypeMeta(type):
    """Virtual-type metaclass: instances are pd.Timestamps of the matching
    tz-awareness."""

    _tz_aware: bool

    def __instancecheck__(cls, obj: Any) -> bool:
        return isinstance(obj, pd.Timestamp) and (
            obj.tzinfo is not None
        ) == cls._tz_aware


class DateTimeNaive(metaclass=_TimestampTypeMeta):
    """Timezone-unaware datetime (nanosecond precision). Constructing one
    returns a naive ``pd.Timestamp``."""

    _tz_aware = False

    def __new__(cls, *args: Any, **kwargs: Any):
        ts = pd.Timestamp(*args, **kwargs)
        if ts.tzinfo is not None:
            raise ValueError("DateTimeNaive cannot hold an aware datetime")
        return ts

    @classmethod
    def from_datetime(cls, dt: datetime.datetime) -> pd.Timestamp:
        if dt.tzinfo is not None:
            raise ValueError("DateTimeNaive cannot hold an aware datetime")
        return pd.Timestamp(dt)

    @classmethod
    def strptime(cls, s: str, fmt: str) -> pd.Timestamp:
        return cls(_strptime(s, fmt, utc=False))


class DateTimeUtc(metaclass=_TimestampTypeMeta):
    """Timezone-aware datetime (stored as UTC, nanosecond precision).
    Constructing one returns an aware ``pd.Timestamp``."""

    _tz_aware = True

    def __new__(cls, *args: Any, **kwargs: Any):
        ts = pd.Timestamp(*args, **kwargs)
        if ts.tzinfo is None:
            raise ValueError("DateTimeUtc requires an aware datetime")
        return ts.tz_convert("UTC")

    @classmethod
    def from_datetime(cls, dt: datetime.datetime) -> pd.Timestamp:
        if dt.tzinfo is None:
            raise ValueError("DateTimeUtc requires an aware datetime")
        return pd.Timestamp(dt).tz_convert("UTC")

    @classmethod
    def strptime(cls, s: str, fmt: str) -> pd.Timestamp:
        return cls(_strptime(s, fmt, utc=True))


class Duration(pd.Timedelta):
    """Time difference (nanosecond precision)."""

    @classmethod
    def from_timedelta(cls, td: datetime.timedelta) -> "Duration":
        return cls(td)

    def nanoseconds(self) -> int:
        return int(self.value)


def timestamp_ns(v: pd.Timestamp | datetime.datetime) -> int:
    """Nanoseconds since epoch (UTC for aware values)."""
    if isinstance(v, pd.Timestamp):
        return int(v.value)
    return int(pd.Timestamp(v).value)


def _strptime(s: str, fmt: str, utc: bool):
    """strptime that, unlike Python's, accepts nanosecond fractions for %f
    (the reference's chrono %f parses up to 9 digits). The given format is
    always honored: on a %f overflow the fraction is truncated to
    microseconds for stdlib parsing and the sub-microsecond remainder is
    re-attached, so a non-conforming string still raises ValueError."""
    import re

    try:
        return datetime.datetime.strptime(s, fmt)
    except ValueError:
        if "%f" not in fmt:
            raise
    m = re.search(r"\.(\d{7,9})(?!\d)", s)
    if not m:
        raise ValueError(f"time data {s!r} does not match format {fmt!r}")
    digits = m.group(1)
    micro, rest = digits[:6], digits[6:]
    truncated = s[: m.start(1)] + micro + s[m.end(1):]
    parsed = datetime.datetime.strptime(truncated, fmt)
    extra_ns = int(rest) * 10 ** (3 - len(rest))
    ts = pd.Timestamp(parsed) + pd.Timedelta(extra_ns, unit="ns")
    if utc and ts.tzinfo is not None:
        ts = ts.tz_convert("UTC")
    return ts


def to_naive(v: Any) -> pd.Timestamp:
    if isinstance(v, pd.Timestamp):
        if v.tzinfo is not None:
            raise ValueError("DateTimeNaive cannot hold an aware datetime")
        return v
    if isinstance(v, datetime.datetime):
        if v.tzinfo is not None:
            raise ValueError("DateTimeNaive cannot hold an aware datetime")
        return pd.Timestamp(v)
    if isinstance(v, np.datetime64):
        return pd.Timestamp(v)
    raise TypeError(f"cannot convert {v!r} to DateTimeNaive")
