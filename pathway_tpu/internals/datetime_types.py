"""DateTimeNaive / DateTimeUtc / Duration — thin subclasses of stdlib datetime
(reference: src/engine/time.rs; python: pathway.DateTimeNaive etc.).

The reference implements these natively in Rust over chrono; here they subclass
`datetime` so all stdlib arithmetic works, while `.dt` column namespaces do the
columnar work.
"""

from __future__ import annotations

import datetime
from typing import Any

import numpy as np


class DateTimeNaive(datetime.datetime):
    """Timezone-unaware datetime."""

    @classmethod
    def from_datetime(cls, dt: datetime.datetime) -> "DateTimeNaive":
        if dt.tzinfo is not None:
            raise ValueError("DateTimeNaive cannot hold an aware datetime")
        return cls(
            dt.year, dt.month, dt.day, dt.hour, dt.minute, dt.second, dt.microsecond
        )

    @classmethod
    def strptime(cls, s: str, fmt: str) -> "DateTimeNaive":  # type: ignore[override]
        return cls.from_datetime(datetime.datetime.strptime(s, fmt))

    def timestamp_ns(self) -> int:
        epoch = datetime.datetime(1970, 1, 1)
        return int((self - epoch).total_seconds() * 1e9)


class DateTimeUtc(datetime.datetime):
    """Timezone-aware datetime (stored as UTC)."""

    @classmethod
    def from_datetime(cls, dt: datetime.datetime) -> "DateTimeUtc":
        if dt.tzinfo is None:
            raise ValueError("DateTimeUtc requires an aware datetime")
        dt = dt.astimezone(datetime.timezone.utc)
        return cls(
            dt.year,
            dt.month,
            dt.day,
            dt.hour,
            dt.minute,
            dt.second,
            dt.microsecond,
            tzinfo=datetime.timezone.utc,
        )

    def timestamp_ns(self) -> int:
        return int(self.timestamp() * 1e9)


class Duration(datetime.timedelta):
    """Time difference."""

    @classmethod
    def from_timedelta(cls, td: datetime.timedelta) -> "Duration":
        return cls(days=td.days, seconds=td.seconds, microseconds=td.microseconds)

    def nanoseconds(self) -> int:
        return int(self.total_seconds() * 1e9)


def to_naive(v: Any) -> DateTimeNaive:
    if isinstance(v, DateTimeNaive):
        return v
    if isinstance(v, datetime.datetime):
        return DateTimeNaive.from_datetime(v)
    if isinstance(v, np.datetime64):
        us = v.astype("datetime64[us]").astype("int64")
        return DateTimeNaive.from_datetime(
            datetime.datetime(1970, 1, 1) + datetime.timedelta(microseconds=int(us))
        )
    raise TypeError(f"cannot convert {v!r} to DateTimeNaive")
