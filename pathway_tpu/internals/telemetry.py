"""Telemetry — spans around graph build and execution + OTLP metrics.

TPU-native counterpart of the reference's tracing stack
(reference: src/engine/telemetry.rs — OTLP traces/metrics;
internals/graph_runner/telemetry.py — python build spans share one trace
with engine spans via trace_parent). The span path is the Trace Weaver
(pathway_tpu/observability/tracing.py): every ``Telemetry.span`` records
into the built-in ring buffer with no external SDK, and dual-emits
through OpenTelemetry when the host application configures a real SDK
TracerProvider. Metrics still go OTLP-only (the Flight Recorder registry
is the in-repo metrics surface).
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Iterator

from pathway_tpu.observability.tracing import (
    current_traceparent,
    get_tracer,
    otel_sdk_provider_active,
)


class Telemetry:
    """Span factory + lightweight local timings (always collected).

    Spans delegate to the Trace Weaver tracer; ``timings`` accumulation
    is lock-guarded — spans close concurrently on the engine's topo-level
    worker pool (engine/runtime.py), and the bare dict read-modify-write
    dropped updates under that concurrency."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self.timings: dict[str, float] = {}
        self._timings_lock = threading.Lock()

    def _add_timing(self, name: str, dt: float) -> None:
        with self._timings_lock:
            self.timings[name] = self.timings.get(name, 0.0) + dt

    @contextlib.contextmanager
    def span(self, name: str, **attributes: Any) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            if self.enabled:
                with get_tracer().span(name, **attributes):
                    yield
            else:
                yield
        finally:
            self._add_timing(name, time.perf_counter() - t0)

    def trace_parent(self) -> str | None:
        """W3C traceparent of the current span — the reference forwards
        this across the Python/engine boundary (python_api.rs:3343).
        Prefers the built-in tracer's ambient context; falls back to an
        ambient OTel span when only the host application's SDK is
        tracing."""
        tp = current_traceparent()
        if tp is not None:
            return tp
        try:
            from opentelemetry import trace as _trace

            ctx = _trace.get_current_span().get_span_context()
            if not ctx.is_valid:
                return None
            return (
                f"00-{ctx.trace_id:032x}-{ctx.span_id:016x}-"
                f"{ctx.trace_flags:02x}"
            )
        except Exception:
            return None


def process_gauges() -> dict[str, float]:
    """Process CPU/memory gauges (reference: sysinfo-backed gauges,
    src/engine/telemetry.rs:359-416). Stdlib-only: os.times for CPU,
    /proc/self/status (linux) or ru_maxrss for resident memory."""
    import os

    t = os.times()
    gauges = {"process_cpu_seconds_total": float(t.user + t.system)}
    rss = None
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    rss = float(line.split()[1]) * 1024.0
                    break
    except OSError:
        pass
    if rss is None:
        # fallback is PEAK rss (and on macOS ru_maxrss is bytes, not KiB)
        try:
            import resource
            import sys

            raw = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            rss = float(raw if sys.platform == "darwin" else raw * 1024)
        except Exception:
            rss = 0.0
    gauges["process_memory_rss_bytes"] = rss
    return gauges


class _OtelMetrics:
    """OTLP metrics when an OTel metrics SDK is configured by the host
    application (reference: telemetry.rs:327-357 operator latency +
    process gauges exported over OTLP); free no-ops otherwise."""

    def __init__(self):
        self._hist = None
        self.enabled = False
        try:
            # a bare OTel API (no SDK) hands out proxy instruments that
            # accept-and-drop every record — skip the per-tick cost unless
            # a real SDK provider is configured at Runtime build time
            # (shared gate with the tracer's dual-emit: tracing.py)
            if not _sdk_provider_active():
                return
            from opentelemetry import metrics as _metrics

            meter = _metrics.get_meter("pathway_tpu")
            self._hist = meter.create_histogram(
                "pathway.operator.latency",
                unit="ns",
                description="per-operator batch processing time",
            )
            # CPU time is monotonic cumulative -> counter semantics
            meter.create_observable_counter(
                "pathway.process.cpu_seconds",
                callbacks=[self._observe_cpu],
            )
            meter.create_observable_gauge(
                "pathway.process.memory_rss_bytes",
                callbacks=[self._observe_rss],
            )
            self.enabled = True
        except Exception:  # pragma: no cover - no OTel metrics API
            self._hist = None
            self.enabled = False

    @staticmethod
    def _observe_cpu(_options):
        from opentelemetry.metrics import Observation

        yield Observation(process_gauges()["process_cpu_seconds_total"])

    @staticmethod
    def _observe_rss(_options):
        from opentelemetry.metrics import Observation

        yield Observation(process_gauges()["process_memory_rss_bytes"])

    def record_operator_latency(self, operator: str, ns: int) -> None:
        if self._hist is not None:
            try:
                self._hist.record(ns, {"operator": operator})
            except Exception:
                pass


_GLOBAL = Telemetry()
_METRICS: _OtelMetrics | None = None
_METRICS_LOCK = __import__("threading").Lock()


def get_telemetry() -> Telemetry:
    return _GLOBAL


def _sdk_provider_active() -> bool:
    return otel_sdk_provider_active("metrics")


def get_metrics() -> _OtelMetrics:
    """Metrics singleton. A disabled instance is re-evaluated on each call
    (cheap: one provider type check) so an SDK MeterProvider configured
    AFTER the first Runtime still turns metrics on for later runtimes."""
    global _METRICS
    with _METRICS_LOCK:
        if _METRICS is None or (
            not _METRICS.enabled and _sdk_provider_active()
        ):
            _METRICS = _OtelMetrics()
    return _METRICS
