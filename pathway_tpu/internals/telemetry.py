"""Telemetry — OpenTelemetry spans around graph build and execution.

TPU-native counterpart of the reference's tracing stack
(reference: src/engine/telemetry.rs — OTLP traces/metrics;
internals/graph_runner/telemetry.py — python build spans share one trace
with engine spans via trace_parent). The image ships the OTel API but no
SDK/exporter, so spans are real when an SDK is configured by the host
application and free no-ops otherwise. Enable by passing
``monitoring_server=...`` / setting PATHWAY_MONITORING_SERVER (the
reference gates OTLP export the same way).
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Iterator

try:
    from opentelemetry import trace as _trace

    _tracer = _trace.get_tracer("pathway_tpu")
    _HAS_OTEL = True
except ImportError:  # pragma: no cover
    _tracer = None
    _HAS_OTEL = False


class Telemetry:
    """Span factory + lightweight local timings (always collected)."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self.timings: dict[str, float] = {}

    @contextlib.contextmanager
    def span(self, name: str, **attributes: Any) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            if self.enabled and _HAS_OTEL:
                with _tracer.start_as_current_span(name) as sp:
                    for k, v in attributes.items():
                        try:
                            sp.set_attribute(k, v)
                        except Exception:
                            pass
                    yield
            else:
                yield
        finally:
            self.timings[name] = self.timings.get(name, 0.0) + (
                time.perf_counter() - t0
            )

    def trace_parent(self) -> str | None:
        """W3C traceparent of the current span — the reference forwards
        this across the Python/engine boundary (python_api.rs:3343)."""
        if not _HAS_OTEL:
            return None
        ctx = _trace.get_current_span().get_span_context()
        if not ctx.is_valid:
            return None
        return f"00-{ctx.trace_id:032x}-{ctx.span_id:016x}-{ctx.trace_flags:02x}"


_GLOBAL = Telemetry()


def get_telemetry() -> Telemetry:
    return _GLOBAL
