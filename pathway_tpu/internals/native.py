"""Loader for the native runtime kernels (native/pathway_native.cc).

Imports `pathway_tpu._native` if already built; otherwise builds it once
with g++ (a few hundred ms) and caches the .so next to the package. Every
caller has a pure-Python fallback, so a missing toolchain degrades
performance, never correctness. Disable with PATHWAY_NO_NATIVE=1.
"""

from __future__ import annotations

import os
import subprocess
import sysconfig

_native = None
_tried = False


def _build() -> bool:
    """Compile to a temp file and swap in atomically: a failed build must
    never clobber (or have required deleting) a working cached kernel."""
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    src = os.path.join(os.path.dirname(pkg_dir), "native", "pathway_native.cc")
    if not os.path.exists(src):
        return False
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    target = os.path.join(pkg_dir, "_native" + suffix)
    # per-process tmp: N processes of one spawn group may rebuild
    # concurrently — a shared tmp path would interleave linker writes
    tmp = f"{target}.{os.getpid()}.tmp"
    include = sysconfig.get_paths()["include"]
    cmd = [
        "g++", "-O3", "-std=c++17", "-fPIC", "-shared", "-pthread",
        f"-I{include}", src, "-o", tmp,
    ]
    try:
        res = subprocess.run(cmd, capture_output=True, timeout=120)
        if res.returncode != 0 or not os.path.exists(tmp):
            return False
        os.replace(tmp, target)
        return True
    except (OSError, subprocess.TimeoutExpired):
        return False
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def get_native():
    """The configured native module, or None."""
    global _native, _tried
    if _native is not None or _tried:
        return _native
    _tried = True
    if os.environ.get("PATHWAY_NO_NATIVE"):
        return None
    # stale-cache guard: rebuild when the source is newer than the .so
    # (a cached kernel from an older source must not mask new entry
    # points). The rebuild goes via a temp file, so a box without g++
    # keeps its working cached kernel — callers feature-check new entry
    # points with hasattr.
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    src = os.path.join(os.path.dirname(pkg_dir), "native", "pathway_native.cc")
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    target = os.path.join(pkg_dir, "_native" + suffix)
    try:
        if (
            os.path.exists(src)
            and os.path.exists(target)
            and os.path.getmtime(src) > os.path.getmtime(target)
        ):
            _build()
    except OSError:
        pass
    try:
        from pathway_tpu import _native as mod  # type: ignore[attr-defined]
    except ImportError:
        if not _build():
            return None
        try:
            from pathway_tpu import _native as mod  # type: ignore[attr-defined]
        except ImportError:
            return None
    from pathway_tpu.internals import api

    mod.configure(api.Pointer, api._value_bytes, api._SALT)
    # self-check: native hashing must agree with the python path, otherwise
    # persisted snapshots written by one would not resume under the other
    probe = (None, True, 7, 2.5, "x", b"y", (1, "z"))
    if mod.hash_value(probe) != api._hash_bytes(api._value_bytes(probe)):
        return None
    _native = mod
    return _native
