"""BaseCustomAccumulator — user-defined reducers
(reference: python/pathway/internals/custom_reducers.py)."""

from __future__ import annotations

from typing import Any


class BaseCustomAccumulator:
    """Subclass and implement:

    - ``from_row(cls, row)`` — build an accumulator from one row's values
    - ``update(self, other)`` — merge another accumulator in
    - ``compute_result(self)`` — the output value
    - optionally ``retract(self, other)`` — support retractions
    - optionally ``neutral(cls)`` — empty accumulator
    """

    @classmethod
    def from_row(cls, row: list[Any]) -> "BaseCustomAccumulator":
        raise NotImplementedError

    @classmethod
    def neutral(cls) -> "BaseCustomAccumulator":
        raise NotImplementedError

    def update(self, other: "BaseCustomAccumulator") -> None:
        raise NotImplementedError

    def retract(self, other: "BaseCustomAccumulator") -> None:
        raise NotImplementedError(
            "retraction not supported by this accumulator"
        )

    def compute_result(self) -> Any:
        raise NotImplementedError
