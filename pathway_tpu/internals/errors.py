"""Error handling: poison values + error logs
(reference: src/engine/error.rs, python/pathway/internals/errors.py).

Expression failures produce `ERROR` poison values that flow through the graph
instead of crashing (when ``terminate_on_error=False``); every recorded error
also lands in the global error log, queryable as a table via
``pw.global_error_log()``."""

from __future__ import annotations

import logging
import threading
from typing import Any

logger = logging.getLogger("pathway_tpu")

_lock = threading.Lock()
_ERROR_LOG: list[dict[str, Any]] = []


def record_error(exc: Exception | str, operator: str | None = None) -> None:
    if isinstance(exc, BaseException):
        # drop traceback frames before retaining: each frame pins the
        # whole evaluation batch (arrays in _elementwise locals), and a
        # UDF failing per-row would otherwise hold every failed batch
        # alive until drain_errors()
        import traceback as _tb

        _tb.clear_frames(exc.__traceback__)
        kept: BaseException | None = exc
    else:
        kept = None
    with _lock:
        _ERROR_LOG.append(
            {
                "message": str(exc),
                "operator_id": operator or "",
                "trace": "",
                # original exception object so terminate_on_error re-raises
                # with its real type (reference: engine propagates DataError
                # as the user's exception when terminate_on_error=true)
                "exc": kept,
            }
        )
    logger.debug("recorded error: %s", exc)


def error_count() -> int:
    with _lock:
        return len(_ERROR_LOG)


def first_exception_since(n0: int) -> BaseException | str | None:
    """First error recorded after position ``n0`` — the original exception
    object when available, else its message string."""
    with _lock:
        for entry in _ERROR_LOG[n0:]:
            return entry["exc"] if entry["exc"] is not None else entry["message"]
    return None


def drain_errors() -> list[dict[str, Any]]:
    with _lock:
        out = list(_ERROR_LOG)
        _ERROR_LOG.clear()
    return out


def peek_errors() -> list[dict[str, Any]]:
    with _lock:
        return list(_ERROR_LOG)


def clear_errors() -> None:
    with _lock:
        _ERROR_LOG.clear()


def global_error_log():
    """Table of errors recorded during the run."""
    from pathway_tpu.internals.error_log_table import error_log_table

    return error_log_table()


def local_error_log():
    return global_error_log()
