"""Error handling: poison values + error logs
(reference: src/engine/error.rs, python/pathway/internals/errors.py).

Expression failures produce `ERROR` poison values that flow through the graph
instead of crashing (when ``terminate_on_error=False``); every recorded error
also lands in the global error log, queryable as a table via
``pw.global_error_log()``."""

from __future__ import annotations

import logging
import threading
from typing import Any

logger = logging.getLogger("pathway_tpu")

_lock = threading.Lock()
_ERROR_LOG: list[dict[str, Any]] = []

# --- error-log scoping (reference: pw.local_error_log contexts) ----------
# Operators built inside a `with pw.local_error_log()` block route their
# RUNTIME errors to that log instead of the global one. The scope is
# captured at build time (Node.__init__) and activated around each exec's
# process()/on_end() via a thread-local (one thread per exec per tick).
_scope_stack: list[int] = []
_scope_counter = iter(range(1, 1 << 62))
_exec_scope = threading.local()


def current_build_scope() -> int | None:
    return _scope_stack[-1] if _scope_stack else None


def set_exec_scope(scope: int | None) -> None:
    _exec_scope.value = scope


def _active_scope() -> int | None:
    return getattr(_exec_scope, "value", None)


class EngineError(ValueError):
    """Engine-originated error whose message is a canonical phrase used
    verbatim in the error log (reference: src/engine/error.rs displays).
    Subclasses ValueError so terminate_on_error re-raises remain
    catchable as the conventional exception type."""


def _normalize_message(exc: Exception | str, user: bool) -> str:
    """Reference-parity wordings (reference: src/engine/error.rs display
    impls) so ported test suites compare error logs verbatim: engine
    errors use canonical phrases (EngineError / plain strings /
    'division by zero'); USER exceptions (udfs, stateful reducers)
    format as 'Type: message'."""
    if isinstance(exc, EngineError):
        return str(exc)
    if isinstance(exc, BaseException):
        if isinstance(exc, ZeroDivisionError) and not user:
            return "division by zero"
        return f"{type(exc).__name__}: {exc}"
    return str(exc)


_SCOPE_ACTIVE = object()  # sentinel: use the thread's active scope


def record_error(
    exc: Exception | str,
    operator: str | None = None,
    user: bool = False,
    scope: Any = _SCOPE_ACTIVE,
) -> None:
    if isinstance(exc, BaseException):
        # drop traceback frames before retaining: each frame pins the
        # whole evaluation batch (arrays in _elementwise locals), and a
        # UDF failing per-row would otherwise hold every failed batch
        # alive until drain_errors()
        import traceback as _tb

        _tb.clear_frames(exc.__traceback__)
        kept: BaseException | None = exc
    else:
        kept = None
    with _lock:
        _ERROR_LOG.append(
            {
                "message": _normalize_message(exc, user),
                "operator_id": operator or "",
                "trace": "",
                "log_id": _active_scope() if scope is _SCOPE_ACTIVE else scope,
                # original exception object so terminate_on_error re-raises
                # with its real type (reference: engine propagates DataError
                # as the user's exception when terminate_on_error=true)
                "exc": kept,
            }
        )
    logger.debug("recorded error: %s", exc)


def error_count() -> int:
    with _lock:
        return len(_ERROR_LOG)


def first_exception_since(n0: int) -> BaseException | str | None:
    """First error recorded after position ``n0`` — the original exception
    object when available, else its message string."""
    with _lock:
        for entry in _ERROR_LOG[n0:]:
            return entry["exc"] if entry["exc"] is not None else entry["message"]
    return None


def drain_errors() -> list[dict[str, Any]]:
    with _lock:
        out = list(_ERROR_LOG)
        _ERROR_LOG.clear()
    return out


def peek_errors() -> list[dict[str, Any]]:
    with _lock:
        return list(_ERROR_LOG)


def clear_errors() -> None:
    with _lock:
        _ERROR_LOG.clear()


def global_error_log():
    """Table of errors recorded during the run (excluding those captured
    by local error-log scopes)."""
    from pathway_tpu.internals.error_log_table import error_log_table

    return error_log_table(scope=None)


class _LocalErrorLog:
    """Context manager: operators built inside route their errors to the
    yielded table (reference: pw.local_error_log)."""

    def __enter__(self):
        from pathway_tpu.internals.error_log_table import error_log_table

        self.scope = next(_scope_counter)
        _scope_stack.append(self.scope)
        # the handle table itself must NOT be scope-tagged (it reads the
        # log, it doesn't produce errors into it)
        _scope_stack.append(None)  # type: ignore[arg-type]
        table = error_log_table(scope=self.scope)
        _scope_stack.pop()
        return table

    def __exit__(self, *exc_info):
        _scope_stack.pop()
        return False


def local_error_log():
    return _LocalErrorLog()
