"""Error handling: poison values + error logs
(reference: src/engine/error.rs, python/pathway/internals/errors.py).

Expression failures produce `ERROR` poison values that flow through the graph
instead of crashing (when ``terminate_on_error=False``); every recorded error
also lands in the global error log, queryable as a table via
``pw.global_error_log()``."""

from __future__ import annotations

import logging
import threading
from typing import Any

logger = logging.getLogger("pathway_tpu")

_lock = threading.Lock()
_ERROR_LOG: list[dict[str, Any]] = []


def record_error(exc: Exception | str, operator: str | None = None) -> None:
    with _lock:
        _ERROR_LOG.append(
            {
                "message": str(exc),
                "operator_id": operator or "",
                "trace": "",
            }
        )
    logger.debug("recorded error: %s", exc)


def drain_errors() -> list[dict[str, Any]]:
    with _lock:
        out = list(_ERROR_LOG)
        _ERROR_LOG.clear()
    return out


def peek_errors() -> list[dict[str, Any]]:
    with _lock:
        return list(_ERROR_LOG)


def clear_errors() -> None:
    with _lock:
        _ERROR_LOG.clear()


def global_error_log():
    """Table of errors recorded during the run."""
    from pathway_tpu.internals.error_log_table import error_log_table

    return error_log_table()


def local_error_log():
    return global_error_log()
