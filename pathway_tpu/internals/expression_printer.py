"""Expression pretty-printer
(reference: python/pathway/internals/expression_printer.py): renders
expressions the way error messages and docs show them — tables are numbered
<table1>, <table2>, ... in first-reference order within one printed
expression."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import expression as expr_mod


class ExpressionFormatter:
    def __init__(self) -> None:
        self._tables: list[Any] = []

    def table_name(self, table: Any) -> str:
        for i, t in enumerate(self._tables):
            if t is table:
                return f"<table{i + 1}>"
        self._tables.append(table)
        return f"<table{len(self._tables)}>"

    # -----------------------------------------------------------------

    def print_expression(self, e: Any) -> str:
        em = expr_mod
        if isinstance(e, em.ColumnReference):
            return f"{self.table_name(e.table)}.{e.name}"
        if isinstance(e, em.ColumnConstExpression):
            return repr(e._value)
        if isinstance(e, em.ColumnBinaryOpExpression):
            return (
                f"({self.print_expression(e._left)} {e._op} "
                f"{self.print_expression(e._right)})"
            )
        if isinstance(e, em.ColumnUnaryOpExpression):
            return f"({e._op}{self.print_expression(e._expr)})"
        if isinstance(e, em.ReducerExpression):
            parts = [self.print_expression(a) for a in e._args]
            parts += [
                f"{k}={self.print_expression(v)}"
                for k, v in e._kwargs.items()
            ]
            name = getattr(e._reducer, "name", str(e._reducer))
            if name in ("argmin", "argmax") and len(parts) > 1:
                # the id argument is an implementation detail of the
                # two-arg accumulator; the reference prints the value only
                parts = parts[:1]
            return f"pathway.reducers.{name}({', '.join(parts)})"
        if isinstance(e, em.ApplyExpression):
            fn_name = getattr(e._fn, "__name__", repr(e._fn))
            parts = [fn_name]
            parts += [self.print_expression(a) for a in e._args]
            parts += [
                f"{k}={self.print_expression(v)}"
                for k, v in e._kwargs.items()
            ]
            kind = (
                "apply_async"
                if isinstance(e, em.AsyncApplyExpression)
                else "apply"
            )
            return f"pathway.{kind}({', '.join(parts)})"
        if isinstance(e, em.CastExpression):
            return (
                f"pathway.cast({e._target.name.upper()}, "
                f"{self.print_expression(e._expr)})"
            )
        if isinstance(e, em.ConvertExpression):
            return (
                f"pathway.as_{e._target.name.lower()}"
                f"({self.print_expression(e._expr)})"
            )
        if isinstance(e, em.DeclareTypeExpression):
            return (
                f"pathway.declare_type({e._target.name.upper()}, "
                f"{self.print_expression(e._expr)})"
            )
        if isinstance(e, em.CoalesceExpression):
            inner = ", ".join(self.print_expression(a) for a in e._args)
            return f"pathway.coalesce({inner})"
        if isinstance(e, em.RequireExpression):
            inner = ", ".join(
                [self.print_expression(e._val)]
                + [self.print_expression(a) for a in e._args]
            )
            return f"pathway.require({inner})"
        if isinstance(e, em.IfElseExpression):
            return (
                f"pathway.if_else({self.print_expression(e._if)}, "
                f"{self.print_expression(e._then)}, "
                f"{self.print_expression(e._else)})"
            )
        if isinstance(e, em.IsNoneExpression):
            return f"({self.print_expression(e._expr)} is None)"
        if isinstance(e, em.IsNotNoneExpression):
            return f"({self.print_expression(e._expr)} is not None)"
        if isinstance(e, em.PointerExpression):
            inner = ", ".join(self.print_expression(a) for a in e._args)
            if e._instance is not None:
                inner += f", instance={self.print_expression(e._instance)}"
            if e._optional:
                inner += ", optional=True"
            return f"{self.table_name(e._table)}.pointer_from({inner})"
        if isinstance(e, em.MethodCallExpression):
            args = [self.print_expression(a) for a in e._args]
            rest = ", ".join(args[1:])
            return f"({args[0]}).{e._name}({rest})"
        if isinstance(e, em.MakeTupleExpression):
            inner = ", ".join(self.print_expression(a) for a in e._args)
            return f"pathway.make_tuple({inner})"
        if isinstance(e, em.GetExpression):
            idx = self.print_expression(e._index)
            if e._check_if_exists:
                return (
                    f"({self.print_expression(e._expr)}).get({idx}, "
                    f"{self.print_expression(e._default)})"
                )
            return f"({self.print_expression(e._expr)})[{idx}]"
        if isinstance(e, em.ToStringExpression):
            return f"({self.print_expression(e._expr)}).to_string()"
        if isinstance(e, em.UnwrapExpression):
            return f"pathway.unwrap({self.print_expression(e._expr)})"
        if isinstance(e, em.FillErrorExpression):
            return (
                f"pathway.fill_error({self.print_expression(e._expr)}, "
                f"{self.print_expression(e._replacement)})"
            )
        return object.__repr__(e)

    def print_table_infos(self) -> str:
        return ", ".join(
            f"<table{i + 1}>={t!r}" for i, t in enumerate(self._tables)
        )


def get_expression_info(e: Any) -> str:
    return ExpressionFormatter().print_expression(e)
