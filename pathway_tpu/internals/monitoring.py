"""In-terminal monitoring dashboard.

TPU-native counterpart of the reference's rich TUI
(reference: python/pathway/internals/monitoring.py:165 StatsMonitor — a
`rich` live dashboard with a connectors table and an operator-latency
table, fed by engine prober callbacks). Here it renders RuntimeStats on a
timer while the tick loop runs.
"""

from __future__ import annotations

import threading
from typing import Any

try:
    from rich.console import Console
    from rich.live import Live
    from rich.table import Table as RichTable

    _HAS_RICH = True
except ImportError:  # pragma: no cover
    _HAS_RICH = False


class StatsMonitor:
    def __init__(self, runtime: Any, refresh_s: float = 0.5):
        self.runtime = runtime
        self.refresh_s = refresh_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _render(self):
        s = self.runtime.stats
        names = {n.id: f"{n.name}#{n.id}" for n in self.runtime.order}
        conn = RichTable(title="connectors")
        conn.add_column("input")
        conn.add_column("rows ingested", justify="right")
        for nid, v in sorted(s.rows_in.items()):
            conn.add_row(names.get(nid, str(nid)), str(v))
        ops = RichTable(title="operators")
        ops.add_column("operator")
        ops.add_column("rows", justify="right")
        ops.add_column("cumulative s", justify="right")
        for nid, ns in sorted(
            s.node_ns.items(), key=lambda kv: -kv[1]
        )[:20]:
            ops.add_row(
                names.get(nid, str(nid)),
                str(s.node_rows.get(nid, 0)),
                f"{ns / 1e9:.3f}",
            )
        from rich.console import Group

        header = (
            f"logical time: {s.current_time}   ticks: {s.ticks}   "
            f"rows in: {sum(s.rows_in.values())}   "
            f"rows out: {sum(s.rows_out.values())}"
        )
        return Group(header, conn, ops)

    def _loop(self):  # pragma: no cover - interactive path
        with Live(
            self._render(), console=Console(), refresh_per_second=4
        ) as live:
            while not self._stop.wait(self.refresh_s):
                live.update(self._render())

    def start(self) -> None:
        if not _HAS_RICH:  # pragma: no cover
            return
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
