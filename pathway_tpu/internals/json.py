"""Json value type (reference: src/engine/value.rs Value::Json +
python/pathway/internals/json.py).

Semantics mirror the reference exactly: `__getitem__`/`__iter__`/`__len__`
delegate to the wrapped Python value (so indexing a number raises TypeError,
indexing a string slices it, iterating a dict yields its keys wrapped as
Json), while `as_*` conversions are isinstance-checked with the reference's
"Cannot convert Json ... " error text. `Json.dumps` serializes datetimes as
nanosecond-precision ISO strings and durations as nanosecond ints (the
reference's _JsonEncoder)."""

from __future__ import annotations

import datetime
import json as _json
import operator
from typing import Any, ClassVar, Iterator


class _JsonEncoder(_json.JSONEncoder):
    def default(self, obj):
        from pathway_tpu.internals import datetime_types as _dtt

        if isinstance(obj, Json):
            return obj.value
        if isinstance(obj, _dtt.Duration):
            return obj.value
        if isinstance(obj, datetime.timedelta):
            return _dtt.Duration(obj).value
        if isinstance(obj, (_dtt.DateTimeNaive, _dtt.DateTimeUtc)):
            return obj.isoformat(timespec="nanoseconds")
        if isinstance(obj, datetime.datetime):
            try:
                import pandas as pd

                return pd.Timestamp(obj).isoformat(timespec="nanoseconds")
            except Exception:
                return obj.isoformat()
        import numpy as np

        if isinstance(obj, np.bool_):
            return bool(obj)
        if isinstance(obj, np.integer):
            return int(obj)
        if isinstance(obj, np.floating):
            return float(obj)
        if isinstance(obj, np.ndarray):
            return obj.tolist()
        if isinstance(obj, tuple):
            return list(obj)
        return super().default(obj)


class Json:
    """Immutable wrapper over a parsed JSON value."""

    __slots__ = ("_value",)

    NULL: ClassVar["Json"]

    def __init__(self, value: Any = None):
        object.__setattr__(self, "_value", value)

    @property
    def value(self) -> Any:
        v = self._value
        while isinstance(v, Json):
            v = v._value
        return v

    # --- parsing / dumping ---------------------------------------------------

    @staticmethod
    def parse(s: str | bytes | bytearray) -> "Json":
        return Json(_json.loads(s))

    @staticmethod
    def dumps(obj: Any) -> str:
        return _json.dumps(obj, cls=_JsonEncoder)

    def to_string(self) -> str:
        return Json.dumps(self.value)

    # --- access (delegate to the wrapped value, reference json.py:69-85) -----

    def __getitem__(self, key: int | str) -> "Json":
        return Json(self.value[key])

    def get(self, item: str | int, default: Any = None) -> Any:
        try:
            return self[item]
        except (KeyError, IndexError, TypeError):
            return default

    def __iter__(self) -> Iterator["Json"]:
        for item in self.value:
            yield Json(item)

    def __reversed__(self) -> Iterator["Json"]:
        for item in reversed(self.value):
            yield Json(item)

    def __len__(self) -> int:
        return len(self.value)

    def __contains__(self, item: Any) -> bool:
        return item in self.value

    def __index__(self) -> int:
        return operator.index(self.value)

    def __int__(self) -> int:
        return int(self.value)

    def __float__(self) -> float:
        return float(self.value)

    # --- conversions ----------------------------------------------------------

    def _as_type(self, type_: type) -> Any:
        if isinstance(self.value, type_):
            return self.value
        raise ValueError(f"Cannot convert Json {self.value} to {type_}")

    def as_int(self) -> int:
        return self._as_type(int)

    def as_float(self) -> float:
        if isinstance(self.value, int):
            return float(self.value)
        return self._as_type(float)

    def as_str(self) -> str:
        return self._as_type(str)

    def as_bool(self) -> bool:
        return self._as_type(bool)

    def as_list(self) -> list:
        return self._as_type(list)

    def as_dict(self) -> dict:
        return self._as_type(dict)

    # --- dunder ---------------------------------------------------------------

    def __repr__(self) -> str:
        return f"pw.Json({self.value!r})"

    def __str__(self) -> str:
        return Json.dumps(self.value)

    def __eq__(self, other: object) -> bool:
        if isinstance(other, Json):
            return self.value == other.value
        return self.value == other

    def __hash__(self) -> int:
        try:
            return hash(Json.dumps_sorted(self.value))
        except TypeError:
            return hash(repr(self.value))

    @staticmethod
    def dumps_sorted(obj: Any) -> str:
        return _json.dumps(obj, cls=_JsonEncoder, sort_keys=True)

    def __bool__(self) -> bool:
        return bool(self.value)


def _is_plain_json(v: Any) -> bool:
    if v is None or isinstance(v, (bool, int, float, str)):
        return True
    if isinstance(v, list):
        return all(_is_plain_json(x) for x in v)
    if isinstance(v, dict):
        return all(
            isinstance(k, str) and _is_plain_json(x) for k, x in v.items()
        )
    return False


def normalize_json(v: Any) -> "Json":
    """Coerce an arbitrary value into a Json holding only plain JSON types —
    the engine-boundary serialization the reference performs when a Python
    Json crosses into serde (datetimes → nanosecond ISO strings, durations
    → nanosecond ints, nested Json unwrapped). Plain values pass through
    without a dumps/loads round-trip."""
    if isinstance(v, Json):
        v = v.value
    if _is_plain_json(v):
        return Json(v)
    return Json(_json.loads(Json.dumps(v)))


JsonValue = (
    int | float | str | bool | list["JsonValue"] | dict[str, "JsonValue"] | None | Json
)

Json.NULL = Json(None)
