"""Json value type (reference: src/engine/value.rs Value::Json +
python/pathway/internals/json.py)."""

from __future__ import annotations

import json as _json
from typing import Any


class Json:
    """Immutable wrapper over a parsed JSON value."""

    __slots__ = ("_value",)

    NULL: "Json"

    def __init__(self, value: Any = None):
        if isinstance(value, Json):
            value = value._value
        self._value = value

    @property
    def value(self) -> Any:
        return self._value

    # --- parsing / dumping ---------------------------------------------------

    @staticmethod
    def parse(s: str | bytes) -> "Json":
        return Json(_json.loads(s))

    @staticmethod
    def dumps(obj: Any) -> str:
        if isinstance(obj, Json):
            obj = obj.value
        return _json.dumps(obj)

    def to_string(self) -> str:
        return _json.dumps(self._value)

    # --- access --------------------------------------------------------------

    def __getitem__(self, item: str | int) -> "Json":
        v = self._value
        if isinstance(item, int) and isinstance(v, list):
            return Json(v[item])
        if isinstance(v, dict):
            return Json(v[item])
        raise KeyError(item)

    def get(self, item: str | int, default: Any = None) -> Any:
        try:
            return self[item]
        except (KeyError, IndexError, TypeError):
            return default

    def __iter__(self):
        v = self._value
        if isinstance(v, list):
            return (Json(x) for x in v)
        if isinstance(v, dict):
            return iter(v)
        raise TypeError(f"Json value {v!r} is not iterable")

    def __len__(self) -> int:
        return len(self._value)

    def __contains__(self, item: Any) -> bool:
        return item in self._value

    # --- conversions ----------------------------------------------------------

    def as_int(self) -> int:
        if isinstance(self._value, bool) or not isinstance(self._value, (int, float)):
            raise ValueError(f"Json {self._value!r} is not an int")
        return int(self._value)

    def as_float(self) -> float:
        if isinstance(self._value, bool) or not isinstance(self._value, (int, float)):
            raise ValueError(f"Json {self._value!r} is not a float")
        return float(self._value)

    def as_str(self) -> str:
        if not isinstance(self._value, str):
            raise ValueError(f"Json {self._value!r} is not a str")
        return self._value

    def as_bool(self) -> bool:
        if not isinstance(self._value, bool):
            raise ValueError(f"Json {self._value!r} is not a bool")
        return self._value

    def as_list(self) -> list:
        if not isinstance(self._value, list):
            raise ValueError(f"Json {self._value!r} is not a list")
        return self._value

    def as_dict(self) -> dict:
        if not isinstance(self._value, dict):
            raise ValueError(f"Json {self._value!r} is not a dict")
        return self._value

    # --- dunder ---------------------------------------------------------------

    def __repr__(self) -> str:
        return f"pw.Json({self._value!r})"

    def __str__(self) -> str:
        return _json.dumps(self._value)

    def __eq__(self, other: object) -> bool:
        if isinstance(other, Json):
            return self._value == other._value
        return self._value == other

    def __hash__(self) -> int:
        try:
            return hash(_json.dumps(self._value, sort_keys=True))
        except TypeError:
            return hash(repr(self._value))

    def __bool__(self) -> bool:
        return bool(self._value)


Json.NULL = Json(None)
