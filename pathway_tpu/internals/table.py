"""pw.Table — the declarative table API.

Capability parity with the reference Table (/root/reference/python/pathway/
internals/table.py:52: select:382, filter:490, groupby:942, reduce:1025,
ix:1164, concat:1334, update_cells:1439, with_universe_of:2037, flatten:2089,
sort:2157). Methods *declare* engine nodes (pathway_tpu/engine/nodes.py); the
runtime executes them as columnar microbatches.
"""

from __future__ import annotations

from typing import Any, Iterable, Mapping, Sequence

import numpy as np

from pathway_tpu.engine import nodes
from pathway_tpu.engine.expression_eval import InternalColRef
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.api import Pointer
from pathway_tpu.internals.expression import (
    ColumnExpression,
    ColumnReference,
    PointerExpression,
    wrap_expr,
)
from pathway_tpu.internals.thisclass import ThisPlaceholder, ThisSlice, this
from pathway_tpu.internals.universe import Universe


class _ColumnNamespace:
    """Attribute/item access that always resolves to columns."""

    def __init__(self, owner: Any):
        object.__setattr__(self, "_owner", owner)

    def __getattr__(self, name: str):
        if name.startswith("__") or name.startswith("_ipython"):
            # dunder/introspection probes (deepcopy, pickle, IPython) must
            # fall through — a ThisPlaceholder owner would otherwise mint a
            # ColumnReference for ANY name
            raise AttributeError(name)
        try:
            return self._owner[name]
        except KeyError:
            # __getattr__ must raise AttributeError so hasattr/getattr
            # defaults work
            raise AttributeError(name) from None

    def __getitem__(self, name: str):
        return self._owner[name]


class TableLike:
    _universe: Universe


class Joinable(TableLike):
    pass


# ---------------------------------------------------------------------------
# Expression desugaring / resolution helpers


def desugar(e: Any, mapping: Mapping[ThisPlaceholder, "Table"]) -> ColumnExpression:
    """Substitute pw.this / pw.left / pw.right placeholders with tables."""
    e = wrap_expr(e)

    def sub(ref: ColumnReference) -> ColumnExpression | None:
        tbl = ref.table
        if isinstance(tbl, ThisSlice):
            target = mapping.get(tbl._parent) or mapping.get(this)
            if target is None:
                raise ValueError(f"cannot resolve {tbl!r} in this context")
            # sliced-away names fail loudly (reference: slice access error)
            return tbl.resolve_ref(target, ref.name)
        if isinstance(tbl, ThisPlaceholder):
            target = mapping.get(tbl)
            if target is None:
                raise ValueError(f"cannot resolve {tbl!r} in this context")
            if ref.name == "id":
                return ColumnReference(target, "id")
            return target[ref.name]
        if isinstance(tbl, _DeferredIxTable):
            if tbl._contains_reducer():
                # pointer computed by a reducer: materialization belongs
                # to GroupedTable.reduce (post-aggregation ix lookup)
                return None
            caller = mapping.get(this)
            if caller is None:
                raise ValueError(
                    "ix_ref() without table-bound arguments can only be "
                    "used inside a table operation (select/filter/...)"
                )
            return tbl._materialize(caller)[ref.name]
        if isinstance(tbl, ThisPlaceholder.__mro__[0]):
            return None
        return None

    return e._substitute(sub)


class _DeferredIxTable:
    """`table.ix_ref(...)` whose indexer universe isn't known yet — the
    args reference no concrete table (constants or pw.this). Column
    accesses return references that desugar() materializes against the
    CALLING operation's table (reference: ix expressions resolve in the
    select's context), enabling e.g. the singleton-broadcast pattern
    ``t.select(v=t.reduce(v=1).ix_ref().v)``."""

    def __init__(
        self,
        source: "Table",
        args: tuple,
        optional: bool,
        instance,
        raw_expr: bool = False,
    ):
        self._source = source
        self._args = args
        self._optional = optional
        self._instance = instance
        self._raw_expr = raw_expr  # args[0] IS the pointer expression
        self._cache: dict[int, "Table"] = {}

    def _materialize(self, caller: "Table") -> "Table":
        key = id(caller)
        if key not in self._cache:
            self._keepalive = getattr(self, "_keepalive", [])
            self._keepalive.append(caller)  # pin: id() reuse after GC
                                            # would alias a dead table
            if self._raw_expr:
                ptr = caller._desugar(self._args[0])
            else:
                ptr = caller.pointer_from(
                    *[caller._desugar(a) for a in self._args],
                    instance=(
                        caller._desugar(self._instance)
                        if self._instance is not None
                        else None
                    ),
                )
            self._cache[key] = self._source.ix(
                ptr, optional=self._optional, context=caller
            )
        return self._cache[key]

    def __getitem__(self, name) -> Any:
        if isinstance(name, (list, tuple)):
            # column slice: a tuple of refs so select(*ix(...)[["a","b"]])
            # unpacks (reference: ix(...)[[...]] usage)
            return tuple(ColumnReference(self, n) for n in name)
        return ColumnReference(self, name)

    def __getattr__(self, name: str) -> ColumnReference:
        if name.startswith("_"):
            raise AttributeError(name)
        return ColumnReference(self, name)

    def _pointer_exprs(self):
        return [wrap_expr(a) for a in self._args]

    def _contains_reducer(self) -> bool:
        return any(_expr_contains_reducer(e) for e in self._pointer_exprs())


def _expr_contains_reducer(e) -> bool:
    from pathway_tpu.internals.expression import ReducerExpression

    if isinstance(e, ReducerExpression):
        return True
    return any(_expr_contains_reducer(c) for c in e._children)


class _DeferredThisIxTable(_DeferredIxTable):
    """``pw.this.ix(expr)`` — both the indexed table AND the pointer
    expression resolve against the CALLING operation's table (reference:
    this.ix inside groupby-reduce, e.g.
    ``reduce(owner=pw.this.ix(pw.reducers.argmax(pw.this.age)).owner)``)."""

    def __init__(self, expr, optional: bool, context, allow_misses: bool):
        self._expr = expr
        self._optional = optional
        self._context = context
        self._allow_misses = allow_misses
        self._cache = {}

    def _materialize(self, caller: "Table") -> "Table":
        key = id(caller)
        if key not in self._cache:
            self._keepalive = getattr(self, "_keepalive", [])
            self._keepalive.append(caller)
            # resolve pw.this against the caller FIRST — otherwise
            # Table.ix sees an unresolved placeholder and re-defers
            self._cache[key] = caller.ix(
                caller._desugar(self._expr),
                optional=self._optional,
                context=self._context,
                allow_misses=self._allow_misses,
            )
        return self._cache[key]

    def _pointer_exprs(self):
        from pathway_tpu.internals.expression import wrap_expr

        return [wrap_expr(self._expr)]


def _require_related_universes(primary: "Table", other: "Table") -> None:
    """Row-aligned multi-table expressions need provably related key sets:
    the same universe or a promised subset relation either way (reference:
    the universe solver rejects cross-universe column mixing)."""
    pu, ou = primary._universe, other._universe
    if pu is ou or ou.is_subset_of(pu) or pu.is_subset_of(ou):
        return
    raise ValueError(
        "expression mixes columns of tables over unrelated universes; "
        "use with_universe_of / pw.universes.promise_is_subset_of to "
        "assert how their key sets relate"
    )


def _collect_tables(exprs: Iterable[ColumnExpression]) -> list["Table"]:
    tables: list[Table] = []
    for e in exprs:
        for ref in e._dependencies():
            tbl = ref.table
            if isinstance(tbl, Table) and all(t is not tbl for t in tables):
                tables.append(tbl)
    return tables


def resolve_to_internal(
    exprs: Mapping[str, ColumnExpression], input_tables: Sequence["Table"]
) -> dict[str, ColumnExpression]:
    """Rewrite ColumnReferences into (input_index, name) InternalColRefs."""

    def sub(ref: ColumnReference) -> ColumnExpression | None:
        tbl = ref.table
        for i, t in enumerate(input_tables):
            if tbl is t:
                return InternalColRef(i, ref.name)
        raise ValueError(
            "reducers can only be used inside groupby(...).reduce(...)"
            if isinstance(tbl, _DeferredIxTable) and tbl._contains_reducer()
            else f"expression references table {tbl!r} which is not an input "
            "of this operation (universes may differ)"
        )

    return {name: e._substitute(sub) for name, e in exprs.items()}


# ---------------------------------------------------------------------------
# dtype inference (lightweight type interpreter —
# reference: internals/type_interpreter.py)


_CMP_OP_NAMES = {
    "==": "eq", "!=": "ne", "<": "lt", "<=": "le", ">": "gt", ">=": "ge"
}


def _dtype_hint(d: dt.DType) -> str:
    """typing-style rendering used in comparison error messages
    (reference type_interpreter wording: tuple[int, str], int | None)."""
    if isinstance(d, dt.OptionalDType):
        return f"{_dtype_hint(d.wrapped)} | None"
    if isinstance(d, dt.TupleDType) and d.args is not None:
        return "tuple[" + ", ".join(_dtype_hint(a) for a in d.args) + "]"
    hint = d.typehint
    return getattr(hint, "__name__", str(hint))


def _elements_comparable(op: str, a: dt.DType, b: dt.DType) -> bool:
    """Recursive element compatibility for tuple/list comparisons
    (reference: _eval_binary_op_on_tuples broadcast semantics). Ordering
    ops reject optional elements at any depth; eq/ne tolerate NONE
    against anything."""
    ordering = op in ("<", "<=", ">", ">=")
    if ordering and (a.is_optional() or b.is_optional()):
        return False
    sa, sb = a.strip_optional(), b.strip_optional()
    if sa == dt.ANY or sb == dt.ANY:
        return True
    if not ordering and (sa == dt.NONE or sb == dt.NONE):
        return True
    if ordering and (sa == dt.NONE or sb == dt.NONE):
        return False
    la = isinstance(sa, dt.TupleDType) and sa.args is not None
    lb = isinstance(sb, dt.TupleDType) and sb.args is not None
    if la or lb or isinstance(sa, dt.ListDType) or isinstance(sb, dt.ListDType):
        return _tuple_like_comparable(op, sa, sb)
    if sa == sb:
        return True
    if sa in (dt.INT, dt.FLOAT) and sb in (dt.INT, dt.FLOAT):
        return True
    return False


def _tuple_like_comparable(op: str, sl: dt.DType, sr: dt.DType) -> bool:
    """Pairwise (with list broadcast) compatibility of two tuple-like
    dtypes."""
    l_args = sl.args if isinstance(sl, dt.TupleDType) else None
    r_args = sr.args if isinstance(sr, dt.TupleDType) else None
    if l_args is not None and r_args is not None:
        if len(l_args) != len(r_args):
            return op in ("==", "!=")
        return all(
            _elements_comparable(op, a, b) for a, b in zip(l_args, r_args)
        )
    l_elt = sl.wrapped if isinstance(sl, dt.ListDType) else None
    r_elt = sr.wrapped if isinstance(sr, dt.ListDType) else None
    if l_elt is not None and r_args is not None:
        return all(_elements_comparable(op, l_elt, b) for b in r_args)
    if r_elt is not None and l_args is not None:
        return all(_elements_comparable(op, a, r_elt) for a in l_args)
    if l_elt is not None and r_elt is not None:
        return _elements_comparable(op, l_elt, r_elt)
    return True  # untyped tuple-likes: no static information to gate on


def _check_tuple_comparable(op: str, l: dt.DType, r: dt.DType) -> None:
    """Reject comparisons of tuples/lists with incompatible element types,
    and orderings over tuples with optional elements (reference:
    test_operators.py tuple comparison type errors)."""

    def tuple_like(d: dt.DType) -> bool:
        s = d.strip_optional()
        return (
            isinstance(s, dt.TupleDType) and s.args is not None
        ) or isinstance(s, dt.ListDType)

    if not (tuple_like(l) and tuple_like(r)):
        return
    if not _tuple_like_comparable(op, l.strip_optional(), r.strip_optional()):
        raise TypeError(
            f"Pathway does not support using binary operator "
            f"{_CMP_OP_NAMES[op]} on columns of types "
            f"{_dtype_hint(l)}, {_dtype_hint(r)}."
        )


def infer_dtype(e: ColumnExpression, env) -> dt.DType:
    if isinstance(e, ColumnReference):
        if e.name == "id":
            return dt.POINTER
        return env(e)
    if isinstance(e, InternalColRef):
        return dt.ANY
    if isinstance(e, expr_mod.ColumnConstExpression):
        return dt.dtype_of_value(e._value)
    if isinstance(e, expr_mod.ColumnBinaryOpExpression):
        l = infer_dtype(e._left, env)
        r = infer_dtype(e._right, env)
        op = e._op
        if op in ("==", "!=", "<", "<=", ">", ">="):
            _check_tuple_comparable(op, l, r)
            return dt.BOOL
        if op in ("<<", ">>"):
            # shifts are defined on (int, int) only (reference
            # operator_mapping: Lshift/Rshift over INT)
            if (
                l.strip_optional() not in (dt.INT, dt.ANY)
                or r.strip_optional() not in (dt.INT, dt.ANY)
            ):
                name = "lshift" if op == "<<" else "rshift"
                raise TypeError(
                    f"Pathway does not support using binary operator "
                    f"{name} on columns of types {_dtype_hint(l)}, "
                    f"{_dtype_hint(r)}."
                )
            return dt.INT
        if op == "/":
            return dt.FLOAT
        if op in ("&", "|", "^") and l == dt.BOOL and r == dt.BOOL:
            return dt.BOOL
        if op == "+" and (l == dt.STR or r == dt.STR):
            return dt.STR
        out = dt.lub(l, r)
        return out
    if isinstance(e, expr_mod.ColumnUnaryOpExpression):
        return infer_dtype(e._expr, env)
    if isinstance(e, expr_mod.IfElseExpression):
        return dt.lub(infer_dtype(e._then, env), infer_dtype(e._else, env))
    if isinstance(e, expr_mod.CoalesceExpression):
        out = infer_dtype(e._args[-1], env)
        for a in e._args[:-1]:
            out = dt.lub(infer_dtype(a, env).strip_optional(), out)
        return out
    if isinstance(e, expr_mod.RequireExpression):
        return dt.Optional_(infer_dtype(e._val, env))
    if isinstance(e, expr_mod.FillErrorExpression):
        return dt.lub(
            infer_dtype(e._expr, env), infer_dtype(e._replacement, env)
        )
    if isinstance(e, (expr_mod.IsNoneExpression, expr_mod.IsNotNoneExpression)):
        return dt.BOOL
    if isinstance(e, expr_mod.UnwrapExpression):
        return infer_dtype(e._expr, env).strip_optional()
    if isinstance(e, (expr_mod.CastExpression, expr_mod.DeclareTypeExpression)):
        return e._target
    if isinstance(e, expr_mod.ConvertExpression):
        return e._target
    if isinstance(e, expr_mod.ToStringExpression):
        return dt.STR
    if isinstance(e, expr_mod.MakeTupleExpression):
        return dt.TupleDType(tuple(infer_dtype(a, env) for a in e._args))
    if isinstance(e, expr_mod.GetExpression):
        from pathway_tpu.internals.json import Json as _Json

        outer = infer_dtype(e._expr, env)
        inner = outer.strip_optional()
        if inner == dt.JSON:
            if outer.is_optional():
                # .get()/[] on Json|None is a build-time error
                # (reference type_interpreter: test_json_get_none)
                raise TypeError(f"Cannot get from {_Json | None}.")
            if e._check_if_exists:
                ddt = infer_dtype(e._default, env)
                if ddt not in (
                    dt.JSON,
                    dt.Optional_(dt.JSON),
                    dt.NONE,
                    dt.ANY,
                    dt.ANY_TUPLE,
                ):
                    raise TypeError(
                        f"Default must be of type {_Json | None}, "
                        f"found {ddt.typehint}."
                    )
            return dt.Optional_(dt.JSON) if e._check_if_exists else dt.JSON
        # tuple / list sequence access (reference type_interpreter
        # SequenceGet rules, tests/test_common.py sequence_get_*)
        idx_e = e._index
        static_idx = (
            idx_e._value
            if isinstance(idx_e, expr_mod.ColumnConstExpression)
            and isinstance(idx_e._value, int)
            else None
        )
        default_dt = infer_dtype(e._default, env)
        if isinstance(inner, dt.TupleDType) and inner.args is not None:
            args = inner.args
            if static_idx is not None:
                in_range = -len(args) <= static_idx < len(args)
                if in_range:
                    elem = args[static_idx]
                    if e._check_if_exists:
                        return dt.lub(elem, default_dt)
                    return elem
                if not e._check_if_exists:
                    raise IndexError(
                        f"Index {static_idx} out of range for a tuple of "
                        f"type {inner.typehint}."
                    )
                import warnings as _warnings

                _warnings.warn(
                    f"Index {static_idx} out of range for a tuple of type "
                    f"{inner.typehint}. The default value will be used. "
                    "Consider using just the default value without .get().",
                    stacklevel=2,
                )
                return default_dt
            # dynamic index
            elem = args[0]
            for a in args[1:]:
                elem = dt.lub(elem, a)
            if e._check_if_exists:
                return dt.lub(dt.Optional_(elem), default_dt)
            return dt.ANY
        if isinstance(inner, dt.ListDType):
            elem = inner.wrapped
            if e._check_if_exists:
                return dt.lub(dt.Optional_(elem), default_dt)
            return elem
        return dt.ANY
    if isinstance(e, PointerExpression):
        return dt.Optional_(dt.POINTER) if e._optional else dt.POINTER
    if isinstance(e, expr_mod.MethodCallExpression):
        return e._return_type
    if isinstance(e, expr_mod.ApplyExpression):
        return e._return_type
    if isinstance(e, expr_mod.ReducerExpression):
        from pathway_tpu.internals.reducer_descriptors import reducer_return_dtype

        return reducer_return_dtype(e, env)
    return dt.ANY


# ---------------------------------------------------------------------------


class Table(Joinable):
    """A (possibly live) table: universe of keys + typed columns."""

    def __init__(
        self,
        node: nodes.Node,
        schema: schema_mod.SchemaMetaclass,
        universe: Universe,
    ):
        assert list(schema.column_names()) == list(node.column_names), (
            schema.column_names(),
            node.column_names,
        )
        self._node = node
        self._schema = schema
        self._universe = universe
        # static-analysis metadata: the universe this node's rows live on
        # (pathway_tpu/analysis re-checks universe relations over the
        # declared graph and surfaces them as diagnostics)
        node._universe = universe
        # ... and the declared column dtypes, so the Plane Doctor can
        # spot object columns headed for the wire/segment pickle
        # fallback (analysis/plane.py pickle-hot-path) without running
        # the encoders
        node._column_dtypes = {
            name: schema[name].dtype for name in schema.column_names()
        }

    # --- metadata -------------------------------------------------------------

    @property
    def schema(self) -> schema_mod.SchemaMetaclass:
        return self._schema

    @property
    def id(self) -> ColumnReference:
        return ColumnReference(self, "id")

    def column_names(self) -> list[str]:
        return list(self._schema.column_names())

    def keys(self):
        return self.column_names()

    @property
    def C(self) -> "_ColumnNamespace":
        """Column-only access namespace: ``t.C.select`` is the COLUMN named
        'select' even though the table has a method of that name
        (reference: Table.C / test_colnamespace.py)."""
        return _ColumnNamespace(self)

    def typehints(self) -> dict[str, Any]:
        return self._schema.typehints()

    def __repr__(self) -> str:
        cols = ", ".join(self.column_names())
        return f"<pw.Table#{self._node.id}({cols})>"

    def __getattr__(self, name: str) -> ColumnReference:
        if name.startswith("__"):
            raise AttributeError(name)
        if name.startswith("_") and name not in self.__dict__.get(
            "_schema", schema_mod.Schema
        ).__columns__:
            raise AttributeError(name)
        if name not in self._schema.__columns__:
            raise AttributeError(
                f"Table has no column {name!r}; columns: {self.column_names()}"
            )
        return ColumnReference(self, name)

    def __getitem__(self, arg):
        if isinstance(arg, str):
            if arg == "id":
                return self.id
            if arg not in self._schema.__columns__:
                raise KeyError(arg)
            return ColumnReference(self, arg)
        if isinstance(arg, ColumnReference):
            return ColumnReference(self, arg.name)
        if isinstance(arg, (list, tuple)):
            refs = [self[c] for c in arg]
            return self.select(*refs)
        raise TypeError(arg)

    def __iter__(self):
        # *table expands to its column references (reference: Table.__iter__)
        return iter([self[n] for n in self.column_names()])

    # --- internal constructors ------------------------------------------------

    @staticmethod
    def _from_node(
        node: nodes.Node,
        dtypes: Mapping[str, dt.DType],
        universe: Universe,
    ) -> "Table":
        cols = {
            name: schema_mod.ColumnSchema(name=name, dtype=d)
            for name, d in dtypes.items()
        }
        schema = schema_mod.schema_from_columns(cols)
        return Table(node, schema, universe)

    def _dtype_env(self):
        def env(ref: ColumnReference) -> dt.DType:
            tbl = ref.table
            if isinstance(tbl, Table):
                if ref.name == "id":
                    return dt.POINTER
                cs = tbl._schema.__columns__.get(ref.name)
                return cs.dtype if cs else dt.ANY
            return dt.ANY

        return env

    def _desugar(self, e: Any) -> ColumnExpression:
        return desugar(e, {this: self})

    def _build_rowwise(
        self,
        exprs: dict[str, ColumnExpression],
        universe: Universe | None = None,
        deterministic: bool = True,
    ) -> "Table":
        exprs = {n: self._desugar(e) for n, e in exprs.items()}
        tables = _collect_tables(exprs.values())
        if self in tables:
            tables.remove(self)
        for t in tables:
            _require_related_universes(self, t)
        input_tables = [self] + tables
        for t in tables:
            if t._universe is not self._universe and not (
                self._universe.is_subset_of(t._universe)
            ):
                # allow: reference requires same universe; we allow subset reads
                pass
        env = self._dtype_env()
        dtypes = {name: infer_dtype(e, env) for name, e in exprs.items()}
        internal = resolve_to_internal(exprs, input_tables)
        node = nodes.RowwiseNode(
            [t._node for t in input_tables], internal, deterministic=deterministic
        )
        return Table._from_node(node, dtypes, universe or self._universe)

    # --- core ops -------------------------------------------------------------

    def select(self, *args: Any, **kwargs: Any) -> "Table":
        exprs: dict[str, ColumnExpression] = {}
        for arg in args:
            if isinstance(arg, ThisSlice):
                for n, ref in arg.resolve(self).items():
                    exprs[n] = ref
            elif isinstance(arg, ThisPlaceholder):
                for n in self.column_names():
                    exprs[n] = self[n]
            elif isinstance(arg, ColumnReference):
                if isinstance(arg.table, ThisPlaceholder):
                    exprs[arg.name] = self[arg.name]
                else:
                    exprs[arg.name] = arg
            elif isinstance(arg, Table):
                for n in arg.column_names():
                    exprs[n] = arg[n]
            else:
                raise TypeError(f"positional select argument {arg!r}")
        for name, e in kwargs.items():
            if isinstance(e, ThisPlaceholder):  # `**pw.this` expansion
                for n in self.column_names():
                    exprs[n] = self[n]
                continue
            if isinstance(e, ThisSlice):  # `**pw.this.without(...)` etc.
                for n, ref in e.resolve(self).items():
                    exprs[n] = ref
                continue
            from pathway_tpu.internals.table_slice import TableSlice

            if isinstance(e, TableSlice):
                for n in e.keys():
                    exprs[n] = e[n]
                continue
            exprs[name] = wrap_expr(e)
        return self._build_rowwise(exprs)

    def with_columns(self, *args: Any, **kwargs: Any) -> "Table":
        kwargs = {
            # `**pw.this` is the identity here: all columns already kept
            n: e
            for n, e in kwargs.items()
            if not isinstance(e, ThisPlaceholder)
        }
        exprs: dict[str, Any] = {n: self[n] for n in self.column_names()}
        for arg in args:
            if isinstance(arg, ColumnReference):
                exprs[arg.name] = arg
            elif isinstance(arg, ThisSlice):
                exprs.update(arg.resolve(self))
            elif isinstance(arg, Table):
                for n in arg.column_names():
                    exprs[n] = arg[n]
        exprs.update(kwargs)
        return self.select(**exprs)

    def without(self, *columns: Any) -> "Table":
        drop = {c if isinstance(c, str) else c.name for c in columns}
        keep = [c for c in self.column_names() if c not in drop]
        return self.select(*[self[c] for c in keep])

    def rename(self, names_mapping: Mapping | None = None, **kwargs) -> "Table":
        if names_mapping is not None:
            return self.rename_by_dict(names_mapping)
        return self.rename_columns(**kwargs)

    def rename_columns(self, **kwargs: Any) -> "Table":
        # new_name=old_ref; reference column order: untouched columns stay
        # in place, renamed ones append in kwargs order
        renamed_src = {
            (old if isinstance(old, str) else old.name): new
            for new, old in kwargs.items()
        }
        exprs = {
            n: self[n]
            for n in self.column_names()
            if n not in renamed_src
        }
        for new, old in kwargs.items():
            exprs[new] = self[old if isinstance(old, str) else old.name]
        return self.select(**exprs)

    def rename_by_dict(self, names_mapping: Mapping) -> "Table":
        mapping = {
            (k if isinstance(k, str) else k.name): (
                v if isinstance(v, str) else v.name
            )
            for k, v in names_mapping.items()
        }
        exprs = {
            mapping.get(n, n): self[n] for n in self.column_names()
        }
        return self.select(**exprs)

    @staticmethod
    def from_columns(*args: Any, **kwargs: Any) -> "Table":
        """Build a table from same-universe columns (reference:
        Table.from_columns, internals/table.py)."""
        cols: dict[str, Any] = {}
        for arg in args:
            cols[arg.name] = arg
        cols.update(kwargs)
        if not cols:
            raise ValueError("Table.from_columns() requires columns")
        first = next(iter(cols.values())).table
        return first.select(**cols)

    def remove_errors(self) -> "Table":
        """Drop rows containing an ERROR value in any column (reference:
        Table.remove_errors, internals/table.py; engine filter_out_errors).
        """
        from pathway_tpu.internals.common import apply_with_type

        cols = [self[n] for n in self.column_names()]
        probe = apply_with_type(lambda *_v: True, bool, *cols)
        import pathway_tpu as pw

        return self.filter(pw.fill_error(probe, False))

    def filter(self, filter_expression: Any) -> "Table":
        e = self._desugar(filter_expression)
        tables = _collect_tables([e])
        if any(t is not self for t in tables):
            # precompute the predicate as a column on self's universe
            with_pred = self._build_rowwise(
                {**{n: self[n] for n in self.column_names()}, "_pred": e}
            )
            filtered = with_pred.filter(with_pred._pred)
            return filtered.without("_pred")
        internal = resolve_to_internal({"p": e}, [self])["p"]
        node = nodes.FilterNode(self._node, internal)
        out = Table(
            node, self._schema, self._universe.subset()
        )
        return out

    def copy(self) -> "Table":
        return self.select(*[self[n] for n in self.column_names()])

    # --- time-column operators (reference: Table._buffer/_forget/_freeze,
    # internals/table.py:666-737; engine: time_column.rs) ---------------------

    def _buffer(self, threshold_column: Any, time_column: Any) -> "Table":
        """Postpone rows until `time_column`'s watermark passes their
        `threshold_column`."""
        from pathway_tpu.engine.nodes import BufferNode
        from pathway_tpu.stdlib.temporal.temporal_behavior import (
            _temporal_table,
        )

        return _temporal_table(
            self,
            BufferNode,
            self._desugar(threshold_column),
            self._desugar(time_column),
        )

    def _forget(
        self,
        threshold_column: Any,
        time_column: Any,
        mark_forgetting_records: bool = False,
    ) -> "Table":
        """Retract rows once `time_column`'s watermark passes their
        `threshold_column` — bounds state for cutoff behaviors."""
        from pathway_tpu.engine.nodes import ForgetNode
        from pathway_tpu.stdlib.temporal.temporal_behavior import (
            _temporal_table,
        )

        return _temporal_table(
            self,
            ForgetNode,
            self._desugar(threshold_column),
            self._desugar(time_column),
            mark_forgetting_records=mark_forgetting_records,
        )

    def _freeze(self, threshold_column: Any, time_column: Any) -> "Table":
        """Drop rows arriving after `time_column`'s watermark passed their
        `threshold_column` (late data)."""
        from pathway_tpu.engine.nodes import FreezeNode
        from pathway_tpu.stdlib.temporal.temporal_behavior import (
            _temporal_table,
        )

        return _temporal_table(
            self,
            FreezeNode,
            self._desugar(threshold_column),
            self._desugar(time_column),
        )

    def _remove_retractions(self) -> "Table":
        """Pass inserts through and DROP deletions (reference:
        Table._remove_retractions — downstream sees an append-only view)."""
        node = nodes.RemoveRetractionsNode(self._node)
        return Table._from_node(
            node,
            {n: self._schema[n].dtype for n in self.column_names()},
            Universe(),
        )

    # --- ids ------------------------------------------------------------------

    def pointer_from(
        self, *args: Any, optional: bool = False, instance: Any = None
    ) -> ColumnExpression:
        return PointerExpression(
            self, *args, optional=optional, instance=instance
        )

    def with_id(self, new_index: ColumnReference) -> "Table":
        # the new-id expression may live on a related table (e.g. a
        # restricted pointer table): route through a row-aligned select
        prep = self._build_rowwise(
            {
                **{n: self[n] for n in self.column_names()},
                "_pw_new_id": new_index,
            }
        )
        internal = resolve_to_internal(
            {"k": prep._pw_new_id}, [prep]
        )["k"]
        node = nodes.ReindexNode(prep._node, internal)
        out = Table(node, prep._schema, Universe())
        return out.without("_pw_new_id")

    def with_id_from(self, *args: Any, instance: Any = None) -> "Table":
        e = self._desugar(
            PointerExpression(self, *args, instance=instance)
        )
        internal = resolve_to_internal({"k": e}, [self])["k"]
        node = nodes.ReindexNode(self._node, internal)
        return Table(node, self._schema, Universe())

    # --- groupby / reduce -----------------------------------------------------

    def groupby(
        self,
        *args: Any,
        id: ColumnReference | None = None,
        sort_by: Any = None,
        _skip_errors: bool = True,
        instance: Any = None,
        **kwargs,
    ):
        from pathway_tpu.internals.groupbys import GroupedTable

        grouping = []
        for a in args:
            a = self._desugar(a)
            grouping.append(a)
        if id is not None:
            grouping = [self._desugar(id)]
        return GroupedTable(
            self, grouping, instance=self._desugar(instance) if instance is not None else None,
            set_id=id is not None, sort_by=sort_by, skip_errors=_skip_errors
        )

    def reduce(self, *args: Any, **kwargs: Any) -> "Table":
        return self.groupby().reduce(*args, **kwargs)

    def deduplicate(
        self,
        *,
        value: Any = None,
        instance: Any = None,
        acceptor: Any = None,
        name: str | None = None,
        persistent_id: str | None = None,
    ) -> "Table":
        cols = {n: self[n] for n in self.column_names()}
        extra: dict[str, Any] = {}
        value_col = None
        inst_cols: list[str] = []
        if value is not None:
            extra["_value"] = self._desugar(value)
            value_col = "_value"
        if instance is not None:
            extra["_instance"] = self._desugar(instance)
            inst_cols = ["_instance"]
        prep = self._build_rowwise({**cols, **extra})
        node = nodes.DeduplicateNode(
            prep._node,
            inst_cols,
            acceptor,
            value_col,
        )
        # explicit name = persistent identity for SELECTIVE_PERSISTING
        node.persistent_name = name or persistent_id
        out = Table._from_node(
            node,
            {n: prep._schema[n].dtype for n in prep.column_names()},
            Universe(),
        )
        keep = [c for c in out.column_names() if not c.startswith("_")]
        result = out.select(*[out[c] for c in keep])
        return result

    # --- joins ----------------------------------------------------------------

    def join(
        self,
        other: "Table",
        *on: Any,
        id: Any = None,
        how: Any = None,
        left_instance: Any = None,
        right_instance: Any = None,
        **kwargs,
    ):
        from pathway_tpu.internals.joins import JoinMode, JoinResult

        if isinstance(other, JoinResult):
            # joining against an unfinished join chains it: fold the inner
            # join into one table first (reference: join chaining)
            other = other._flatten()

        mode = how if how is not None else JoinMode.INNER
        if (left_instance is None) != (right_instance is None):
            raise ValueError(
                "join: left_instance and right_instance must be given "
                "together"
            )
        if left_instance is not None:
            # instance co-location joins as an additional equality
            # (reference: join instance= args, sharded by instance)
            on = (*on, left_instance == right_instance)
        return JoinResult(self, other, on, mode, id)

    def join_inner(self, other: "Table", *on: Any, **kwargs):
        from pathway_tpu.internals.joins import JoinMode

        return self.join(other, *on, how=JoinMode.INNER, **kwargs)

    def join_left(self, other: "Table", *on: Any, **kwargs):
        from pathway_tpu.internals.joins import JoinMode

        return self.join(other, *on, how=JoinMode.LEFT, **kwargs)

    def join_right(self, other: "Table", *on: Any, **kwargs):
        from pathway_tpu.internals.joins import JoinMode

        return self.join(other, *on, how=JoinMode.RIGHT, **kwargs)

    def join_outer(self, other: "Table", *on: Any, **kwargs):
        from pathway_tpu.internals.joins import JoinMode

        return self.join(other, *on, how=JoinMode.OUTER, **kwargs)

    # --- set ops --------------------------------------------------------------

    @staticmethod
    def empty(**kwargs: Any) -> "Table":
        """An empty table whose schema is given by kwargs of column types
        (reference: Table.empty, internals/table.py:355)."""
        from pathway_tpu.engine.nodes import InputNode
        from pathway_tpu.engine.runtime import StaticSource

        class _Empty(StaticSource):
            transient = True

            def events(self):
                return iter(())

        names = list(kwargs.keys())
        node = InputNode(_Empty(names), names)
        return Table._from_node(
            node, {n: dt.wrap(t) for n, t in kwargs.items()}, Universe()
        )

    def concat(self, *others: "Table") -> "Table":
        tables = [self] + list(others)
        # key sets must be provably disjoint or ids could collide
        # (reference: concat requires promise_are_pairwise_disjoint;
        # concat_reindex rehashes and is always safe)
        for i, a in enumerate(tables):
            for b in tables[i + 1 :]:
                if not a._universe.is_disjoint_from(b._universe):
                    raise ValueError(
                        "Table.concat: universes are not provably disjoint; "
                        "call pw.universes.promise_are_pairwise_disjoint "
                        "first, or use concat_reindex"
                    )
        names = self.column_names()
        aligned = [t.select(*[t[n] for n in names]) for t in tables]
        node = nodes.ConcatNode([t._node for t in aligned])
        dtypes = {}
        for n in names:
            out = self._schema[n].dtype
            for t in others:
                out = dt.lub(out, t._schema[n].dtype)
            dtypes[n] = out
        return Table._from_node(node, dtypes, Universe())

    def concat_reindex(self, *others: "Table") -> "Table":
        tables = [self] + list(others)
        reindexed = [
            t.with_id_from(t.id, i) for i, t in enumerate(tables)
        ]
        # the side tag mixed into every rehashed id guarantees disjointness
        for i, a in enumerate(reindexed):
            for b in reindexed[i + 1 :]:
                a._universe.promise_disjoint(b._universe)
        return reindexed[0].concat(*reindexed[1:])

    def update_rows(self, other: "Table") -> "Table":
        names = self.column_names()
        if set(other.column_names()) != set(names):
            raise ValueError(
                "update_rows: column sets must match "
                f"({sorted(names)} vs {sorted(other.column_names())})"
            )
        other_aligned = other.select(*[other[n] for n in names])
        node = nodes.UpdateRowsNode(self._node, other_aligned._node)
        dtypes = {
            n: dt.lub(self._schema[n].dtype, other._schema[n].dtype)
            for n in names
        }
        if self._universe.is_subset_of(other._universe):
            # other covers every key of self: nothing of self survives the
            # override (reference warns and short-circuits)
            import warnings

            warnings.warn(
                "Universe of self is a subset of universe of other in "
                "update_rows. Returning other.",
                stacklevel=2,
            )
            return other_aligned
        # an update from a promised subset cannot add keys: the result
        # keeps self's universe (reference: update_rows universe solver)
        universe = (
            self._universe
            if other._universe.is_subset_of(self._universe)
            else Universe()
        )
        return Table._from_node(node, dtypes, universe)

    def update_cells(self, other: "Table") -> "Table":
        # columns of `other` override; other's universe ⊆ self's
        extra = set(other.column_names()) - set(self.column_names())
        if extra:
            raise ValueError(
                f"update_cells: columns {sorted(extra)} are not present "
                "in the updated table"
            )
        if other._universe is self._universe:
            import warnings

            warnings.warn(
                "Key sets of self and other in update_cells are the same. "
                "Using with_columns instead of update_cells.",
                stacklevel=2,
            )
            return self.with_columns(
                **{n: other[n] for n in other.column_names()}
            )
        names = self.column_names()
        override = [n for n in other.column_names() if n in names]
        exprs: dict[str, Any] = {n: self[n] for n in names}
        from pathway_tpu.internals.common import coalesce

        for n in override:
            exprs[n] = _CellUpdate(self[n], other[n])
        return self._build_rowwise(exprs)

    def __lshift__(self, other: "Table") -> "Table":
        return self.update_cells(other)

    def __add__(self, other: "Table") -> "Table":
        """Column union of two tables over the same rows: C.columns =
        self.columns + other.columns, C.id = self.id (reference:
        Table.__add__, internals/table.py:424). Column names must be
        disjoint. Universe agreement is the caller's contract — this build
        does not prove universe equality (no universe solver here), so
        mixing tables over different row sets yields missing cells rather
        than a build-time error."""
        exprs: dict[str, Any] = {n: self[n] for n in self.column_names()}
        for n in other.column_names():
            if n in exprs and other is not self:
                raise ValueError(
                    f"Table.__add__: column {n!r} exists on both sides; "
                    "columns must be disjoint"
                )
            exprs[n] = other[n]
        return self._build_rowwise(exprs)

    def intersect(self, *tables: "Table") -> "Table":
        node = nodes.UniverseSetOpNode(
            self._node, [t._node for t in tables], "intersect"
        )
        return Table(node, self._schema, self._universe.subset())

    def difference(self, other: "Table") -> "Table":
        node = nodes.UniverseSetOpNode(self._node, [other._node], "difference")
        return Table(node, self._schema, self._universe.subset())

    def restrict(self, other: TableLike) -> "Table":
        if hasattr(other, "_flatten") and not hasattr(other, "_node"):
            other = other._flatten()  # JoinResult used as a key-set source
        node = nodes.UniverseSetOpNode(
            self._node, [other._node], "restrict"  # type: ignore[attr-defined]
        )
        return Table(node, self._schema, other._universe)

    def having(self, *indexers: ColumnReference) -> "Table":
        out = self
        for ix in indexers:
            tbl = ix.table
            keyed = tbl.with_id(ix)
            node = nodes.UniverseSetOpNode(out._node, [keyed._node], "restrict")
            # having() IS the sanctioned drop-missing-keys filter (the
            # result universe stays a subset of self) — the Graph Doctor's
            # universe-safety rule must not treat it as an unchecked
            # restrict over unrelated key sets
            node._intentional_restrict = True
            out = Table(node, out._schema, out._universe.subset())
        return out

    def with_universe_of(self, other: TableLike) -> "Table":
        node = nodes.UniverseSetOpNode(
            self._node, [other._node], "restrict"  # type: ignore[attr-defined]
        )
        return Table(node, self._schema, other._universe)

    # --- ix -------------------------------------------------------------------

    def ix(
        self,
        expression: ColumnExpression,
        *,
        optional: bool = False,
        context=None,
        allow_misses: bool = False,
    ) -> "Table":
        e = expression
        if _expr_contains_reducer(wrap_expr(e)):
            # pointer computed by a reducer: defer — GroupedTable.reduce
            # aggregates the pointer first, then indexes THIS table
            # (reference: in-reduce ix(argmax, context=pw.this))
            d = _DeferredThisIxTable(e, optional, context, allow_misses)
            d._source = self
            return d
        tables = _collect_tables([wrap_expr(e)])
        if tables:
            indexer = tables[0]
        elif context is not None:
            indexer = context
        elif isinstance(e, PointerExpression) and isinstance(e._table, Table):
            indexer = e._table
        else:
            wrapped = wrap_expr(e)
            has_this = any(
                isinstance(getattr(r, "table", None), ThisPlaceholder)
                for r in wrapped._dependencies()
            )
            if not has_this:
                raise ValueError(
                    "ix requires a column expression with a table"
                )
            # pw.this-scoped pointer: defer to the CALLING operation's
            # table, like ix_ref (reference: ix resolves in the select's
            # context — t.select(x=other.ix(pw.this.ptr).col))
            return _DeferredIxTable(
                self,
                (wrapped,),
                optional or allow_misses,
                None,
                raw_expr=True,
            )
        prep = indexer._build_rowwise({"_ptr": e})
        node = nodes.IxNode(
            prep._node, "_ptr", self._node, optional or allow_misses
        )
        dtypes = {n: self._schema[n].dtype for n in self.column_names()}
        if optional:
            dtypes = {n: dt.Optional_(d) for n, d in dtypes.items()}
        return Table._from_node(node, dtypes, indexer._universe)

    def ix_ref(
        self,
        *args: Any,
        optional: bool = False,
        context=None,
        instance: Any = None,
    ):
        if context is None:
            arg_tables = _collect_tables(
                [wrap_expr(a) for a in args]
            ) if args else []
            if arg_tables:
                context = arg_tables[0]
        if context is None:
            # constants / pw.this args: the indexer universe is the CALLER's
            # — defer until the expression is used in a table operation
            return _DeferredIxTable(self, args, optional, instance)
        ptr = context.pointer_from(*args, instance=instance)
        return self.ix(ptr, optional=optional, context=context)

    # --- restructuring --------------------------------------------------------

    def flatten(
        self, *args: ColumnReference, origin_id: str | None = None, **kwargs
    ) -> "Table":
        assert len(args) == 1, "flatten takes exactly one column"
        to_flatten = args[0]
        name = to_flatten.name
        if origin_id is not None and origin_id in self.column_names():
            raise ValueError(
                f"flatten: origin_id {origin_id!r} collides with an "
                "existing column"
            )
        prep = self.select(*[self[n] for n in self.column_names()])
        node = nodes.FlattenNode(prep._node, name, origin_id=origin_id)
        inner = prep._schema[name].dtype
        if isinstance(inner, (dt.ListDType,)):
            item_dt = inner.wrapped
        elif isinstance(inner, dt.TupleDType) and inner.args:
            item_dt = inner.args[0]
        elif inner == dt.STR:
            item_dt = dt.STR
        else:
            item_dt = dt.ANY
        dtypes = {
            n: (item_dt if n == name else prep._schema[n].dtype)
            for n in prep.column_names()
        }
        if origin_id is not None:
            # parent-row pointer column (reference: Table.flatten origin_id)
            dtypes[origin_id] = dt.POINTER
        return Table._from_node(node, dtypes, Universe())

    def sort(
        self,
        key: ColumnExpression,
        instance: ColumnExpression | None = None,
    ) -> "Table":
        exprs: dict[str, Any] = {"_key": key}
        if instance is not None:
            exprs["_instance"] = instance
        prep = self._build_rowwise(exprs)
        node = nodes.SortNode(
            prep._node, "_key", "_instance" if instance is not None else None
        )
        return Table._from_node(
            node,
            {
                "prev": dt.Optional_(dt.POINTER),
                "next": dt.Optional_(dt.POINTER),
            },
            self._universe,
        )

    def _gradual_broadcast(
        self,
        threshold_table: "Table",
        lower_column: ColumnExpression,
        value_column: ColumnExpression,
        upper_column: ColumnExpression,
    ) -> "Table":
        """self + apx_value, where apx_value rolls from `lower` to `upper`
        gradually as `value` sweeps the [lower, upper] interval (reference:
        Table._gradual_broadcast, python/pathway/internals/table.py:631;
        operator: src/engine/dataflow/operators/gradual_broadcast.rs)."""
        thr_prep = threshold_table._build_rowwise(
            {
                "_lower": lower_column,
                "_value": value_column,
                "_upper": upper_column,
            }
        )
        node = nodes.GradualBroadcastNode(self._node, thr_prep._node)
        apx = Table._from_node(
            node,
            {"apx_value": thr_prep._schema["_value"].dtype},
            self._universe,
        )
        return self.with_columns(apx)

    def diff(
        self,
        timestamp: ColumnExpression,
        *values: ColumnReference,
        instance: ColumnExpression | None = None,
    ) -> "Table":
        from pathway_tpu.stdlib.ordered import diff as _diff

        return _diff(self, timestamp, *values, instance=instance)

    # --- typing ---------------------------------------------------------------

    def cast_to_types(self, **kwargs: Any) -> "Table":
        exprs = {n: self[n] for n in self.column_names()}
        for name, target in kwargs.items():
            exprs[name] = expr_mod.CastExpression(target, self[name])
        return self.select(**exprs)

    def update_types(self, **kwargs: Any) -> "Table":
        exprs = {n: self[n] for n in self.column_names()}
        for name, target in kwargs.items():
            exprs[name] = expr_mod.DeclareTypeExpression(target, self[name])
        return self.select(**exprs)

    # --- promises (metadata-only, parity surface) -----------------------------

    def promise_universes_are_disjoint(self, other: "Table") -> "Table":
        self._universe.promise_disjoint(other._universe)
        return self

    def promise_universes_are_equal(self, other: "Table") -> "Table":
        return self

    def promise_universe_is_subset_of(self, other: "Table") -> "Table":
        self._universe = other._universe.subset()
        self._node._universe = self._universe
        return self

    def promise_universe_is_equal_to(self, other: "Table") -> "Table":
        self._universe = other._universe
        self._node._universe = self._universe
        return self

    def _set_universe(self, universe: Universe) -> "Table":
        self._universe = universe
        self._node._universe = universe
        return self

    # --- temporal ops (stdlib.temporal, reference: Table methods added by
    # python/pathway/stdlib/temporal/) --------------------------------------

    def windowby(
        self, time_expr, *, window, behavior=None, instance=None, shard=None
    ):
        from pathway_tpu.stdlib.temporal._window import windowby as _impl

        return _impl(
            self, time_expr, window=window, behavior=behavior,
            instance=instance, shard=shard,
        )

    def interval_join(
        self, other, self_time, other_time, interval, *on, behavior=None,
        how=None,
    ):
        from pathway_tpu.internals.joins import JoinMode
        from pathway_tpu.stdlib.temporal._interval_join import (
            interval_join as _impl,
        )

        return _impl(
            self, other, self_time, other_time, interval, *on,
            behavior=behavior, how=how if how is not None else JoinMode.INNER,
        )

    def interval_join_inner(self, other, self_time, other_time, interval, *on, **kw):
        from pathway_tpu.stdlib.temporal._interval_join import (
            interval_join_inner as _impl,
        )

        return _impl(self, other, self_time, other_time, interval, *on, **kw)

    def interval_join_left(self, other, self_time, other_time, interval, *on, **kw):
        from pathway_tpu.stdlib.temporal._interval_join import (
            interval_join_left as _impl,
        )

        return _impl(self, other, self_time, other_time, interval, *on, **kw)

    def interval_join_right(self, other, self_time, other_time, interval, *on, **kw):
        from pathway_tpu.stdlib.temporal._interval_join import (
            interval_join_right as _impl,
        )

        return _impl(self, other, self_time, other_time, interval, *on, **kw)

    def interval_join_outer(self, other, self_time, other_time, interval, *on, **kw):
        from pathway_tpu.stdlib.temporal._interval_join import (
            interval_join_outer as _impl,
        )

        return _impl(self, other, self_time, other_time, interval, *on, **kw)

    def window_join(self, other, self_time, other_time, window, *on, **kw):
        from pathway_tpu.stdlib.temporal._window_join import (
            window_join as _impl,
        )

        return _impl(self, other, self_time, other_time, window, *on, **kw)

    def window_join_inner(self, other, self_time, other_time, window, *on, **kw):
        from pathway_tpu.stdlib.temporal._window_join import (
            window_join_inner as _impl,
        )

        return _impl(self, other, self_time, other_time, window, *on, **kw)

    def window_join_left(self, other, self_time, other_time, window, *on, **kw):
        from pathway_tpu.stdlib.temporal._window_join import (
            window_join_left as _impl,
        )

        return _impl(self, other, self_time, other_time, window, *on, **kw)

    def window_join_right(self, other, self_time, other_time, window, *on, **kw):
        from pathway_tpu.stdlib.temporal._window_join import (
            window_join_right as _impl,
        )

        return _impl(self, other, self_time, other_time, window, *on, **kw)

    def window_join_outer(self, other, self_time, other_time, window, *on, **kw):
        from pathway_tpu.stdlib.temporal._window_join import (
            window_join_outer as _impl,
        )

        return _impl(self, other, self_time, other_time, window, *on, **kw)

    def asof_join(self, other, self_time, other_time, *on, **kw):
        from pathway_tpu.stdlib.temporal._asof_join import asof_join as _impl

        return _impl(self, other, self_time, other_time, *on, **kw)

    def asof_join_left(self, other, self_time, other_time, *on, **kw):
        from pathway_tpu.stdlib.temporal._asof_join import (
            asof_join_left as _impl,
        )

        return _impl(self, other, self_time, other_time, *on, **kw)

    def asof_join_right(self, other, self_time, other_time, *on, **kw):
        from pathway_tpu.stdlib.temporal._asof_join import (
            asof_join_right as _impl,
        )

        return _impl(self, other, self_time, other_time, *on, **kw)

    def asof_join_outer(self, other, self_time, other_time, *on, **kw):
        from pathway_tpu.stdlib.temporal._asof_join import (
            asof_join_outer as _impl,
        )

        return _impl(self, other, self_time, other_time, *on, **kw)

    def asof_now_join(self, other, *on, **kw):
        from pathway_tpu.stdlib.temporal._asof_now_join import (
            asof_now_join as _impl,
        )

        return _impl(self, other, *on, **kw)

    def asof_now_join_inner(self, other, *on, **kw):
        from pathway_tpu.stdlib.temporal._asof_now_join import (
            asof_now_join_inner as _impl,
        )

        return _impl(self, other, *on, **kw)

    def asof_now_join_left(self, other, *on, **kw):
        from pathway_tpu.stdlib.temporal._asof_now_join import (
            asof_now_join_left as _impl,
        )

        return _impl(self, other, *on, **kw)

    # --- output helpers -------------------------------------------------------

    def _subscribe_node(self, on_batch, on_end=None) -> nodes.OutputNode:
        return nodes.OutputNode(self._node, on_batch, on_end)

    # --- interactive sugar ----------------------------------------------------

    @property
    def slice(self):
        from pathway_tpu.internals.table_slice import TableSlice

        return TableSlice(
            {n: self[n] for n in self.column_names()}, self
        )


def _CellUpdate(left_ref, right_ref):
    """update_cells: use right value when the right table has the row."""
    from pathway_tpu.internals.expression import CoalesceExpression

    # right table's universe is a subset; missing rows read as None
    return CoalesceExpression(right_ref, left_ref)


# free functions mirroring reference module-level joins/groupby


def join(left: Table, right: Table, *on, id=None, how=None, **kwargs):
    return left.join(right, *on, id=id, how=how, **kwargs)


def join_inner(left: Table, right: Table, *on, **kwargs):
    return left.join_inner(right, *on, **kwargs)


def join_left(left: Table, right: Table, *on, **kwargs):
    return left.join_left(right, *on, **kwargs)


def join_right(left: Table, right: Table, *on, **kwargs):
    return left.join_right(right, *on, **kwargs)


def join_outer(left: Table, right: Table, *on, **kwargs):
    return left.join_outer(right, *on, **kwargs)


def groupby(table: Table, *args, **kwargs):
    return table.groupby(*args, **kwargs)
