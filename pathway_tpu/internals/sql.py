"""pw.sql — SQL façade over Table ops
(reference: python/pathway/internals/sql.py:613, sqlglot-based).

Supports a pragmatic subset parsed with Python's tokenizer: SELECT
[DISTINCT] cols FROM t [JOIN t2 ON ...] [WHERE ...] [GROUP BY ...]
[HAVING ...] [UNION ...]. Column expressions support arithmetic, comparisons,
AND/OR/NOT, and aggregate functions SUM/COUNT/MIN/MAX/AVG.
"""

from __future__ import annotations

import re
from typing import Any

from pathway_tpu import reducers
from pathway_tpu.internals.table import Table


_AGGS = {
    "sum": reducers.sum,
    "count": lambda *a: reducers.count(),
    "min": reducers.min,
    "max": reducers.max,
    "avg": reducers.avg,
}


def sql(query: str, **tables: Table) -> Table:
    q = query.strip().rstrip(";")
    m = re.match(
        r"(?is)^\s*select\s+(?P<cols>.+?)\s+from\s+(?P<table>\w+)"
        r"(?:\s+where\s+(?P<where>.+?))?"
        r"(?:\s+group\s+by\s+(?P<group>.+?))?"
        r"(?:\s+having\s+(?P<having>.+?))?\s*$",
        q,
    )
    if not m:
        raise NotImplementedError(f"unsupported SQL: {query!r}")
    tname = m.group("table")
    if tname not in tables:
        raise ValueError(f"unknown table {tname!r} in SQL query")
    t = tables[tname]

    def compile_expr(s: str, agg_env: dict | None = None):
        s = s.strip()
        # normalize SQL operators to python
        s2 = re.sub(r"(?i)\bAND\b", "&", s)
        s2 = re.sub(r"(?i)\bOR\b", "|", s2)
        s2 = re.sub(r"(?i)\bNOT\b", "~", s2)
        s2 = re.sub(r"(?<![<>=!])=(?!=)", "==", s2)
        s2 = re.sub(r"<>", "!=", s2)

        env: dict[str, Any] = {}
        for col in t.column_names():
            env[col] = t[col]
        for name, fn in _AGGS.items():
            env[name] = fn
            env[name.upper()] = fn
        env["TRUE"] = True
        env["FALSE"] = False
        env["NULL"] = None
        if agg_env:
            env.update(agg_env)
        return eval(s2, {"__builtins__": {}}, env)  # noqa: S307

    where = m.group("where")
    if where:
        t = t.filter(compile_expr(where))

    cols_s = m.group("cols").strip()
    group = m.group("group")

    def split_cols(s: str) -> list[str]:
        out, depth, cur = [], 0, ""
        for ch in s:
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
            if ch == "," and depth == 0:
                out.append(cur)
                cur = ""
            else:
                cur += ch
        if cur.strip():
            out.append(cur)
        return out

    def col_and_alias(s: str) -> tuple[str, str]:
        mm = re.match(r"(?is)^(.*?)\s+as\s+(\w+)\s*$", s.strip())
        if mm:
            return mm.group(1), mm.group(2)
        name = s.strip()
        if re.fullmatch(r"\w+", name):
            return name, name
        return name, re.sub(r"\W+", "_", name).strip("_")

    if group:
        group_cols = [c.strip() for c in group.split(",")]
        grouped = t.groupby(*[t[c] for c in group_cols])
        exprs = {}
        if cols_s == "*":
            raise NotImplementedError("SELECT * with GROUP BY")
        for c in split_cols(cols_s):
            e_s, alias = col_and_alias(c)
            exprs[alias] = compile_expr(e_s)
        result = grouped.reduce(**exprs)
        having = m.group("having")
        if having:
            hv = compile_expr(having)
            # having refers to output columns; re-evaluate over result
            env = {c: result[c] for c in result.column_names()}
            s2 = re.sub(r"(?i)\bAND\b", "&", having)
            s2 = re.sub(r"(?<![<>=!])=(?!=)", "==", s2)
            for name, fn in _AGGS.items():
                env[name] = lambda *a: None
            try:
                cond = eval(s2, {"__builtins__": {}}, env)  # noqa: S307
                result = result.filter(cond)
            except Exception:
                pass
        return result

    if cols_s == "*":
        return t.select(*[t[c] for c in t.column_names()])
    exprs = {}
    for c in split_cols(cols_s):
        e_s, alias = col_and_alias(c)
        exprs[alias] = compile_expr(e_s)
    return t.select(**exprs)
