"""pw.sql — SQL façade over Table ops.

TPU-native counterpart of the reference's SQL API
(reference: python/pathway/internals/sql.py:613 — sqlglot-parsed subset:
select / join / group by / having / union / intersect, tested by
python/pathway/tests/test_sql.py). sqlglot is not in this image, so this
module ships its own tokenizer + recursive-descent parser covering the
same surface:

  SELECT [DISTINCT] expr [AS alias], ...
  FROM t [AS a] [[LEFT|RIGHT|FULL|INNER] JOIN t2 [AS b] ON cond]*
  [WHERE cond] [GROUP BY cols] [HAVING cond]
  [UNION [ALL] select | INTERSECT select | EXCEPT select]

Expressions: OR/AND/NOT, comparisons (= <> != < <= > >=), IS [NOT] NULL,
IN (literals), BETWEEN, arithmetic (+ - * / %), unary minus, literals,
parentheses, qualified columns (a.x), and the aggregates
SUM/COUNT/MIN/MAX/AVG (COUNT(*) included).
"""

from __future__ import annotations

import re
from typing import Any

from pathway_tpu import reducers
from pathway_tpu.internals.table import Table

_AGGS = {
    "sum": reducers.sum,
    "count": lambda *a: reducers.count(),
    "min": reducers.min,
    "max": reducers.max,
    "avg": reducers.avg,
}

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<num>\d+\.\d*|\.\d+|\d+)
  | (?P<str>'(?:[^']|'')*')
  | (?P<name>[A-Za-z_][A-Za-z_0-9]*)
  | (?P<op><>|!=|<=|>=|==|=|<|>|\+|-|\*|/|%|\(|\)|,|\.|;)
    """,
    re.VERBOSE,
)

_KEYWORDS = {
    "select", "distinct", "from", "join", "inner", "left", "right", "full",
    "outer", "on", "where", "group", "by", "having", "union", "all",
    "intersect", "except", "as", "and", "or", "not", "is", "null", "in",
    "between", "true", "false", "case", "when", "then", "else", "end",
}


def _tokenize(q: str) -> list[tuple[str, str]]:
    out: list[tuple[str, str]] = []
    i = 0
    while i < len(q):
        m = _TOKEN_RE.match(q, i)
        if not m:
            raise ValueError(f"SQL tokenize error at: {q[i:i+20]!r}")
        i = m.end()
        kind = m.lastgroup
        text = m.group()
        if kind == "ws":
            continue
        if kind == "name" and text.lower() in _KEYWORDS:
            out.append(("kw", text.lower()))
        else:
            out.append((kind, text))  # type: ignore[arg-type]
    out.append(("eof", ""))
    return out


class _Scope:
    """Name resolution for one FROM clause: alias -> Table plus a flat
    name -> expression map (unique unqualified columns only)."""

    def __init__(self):
        self.tables: dict[str, Table] = {}
        self.flat: dict[str, Any] = {}
        self.ambiguous: set[str] = set()

    def add(self, alias: str, table: Table) -> None:
        self.tables[alias] = table
        for c in table.column_names():
            if c in self.flat or c in self.ambiguous:
                self.ambiguous.add(c)
                self.flat.pop(c, None)
            else:
                self.flat[c] = table[c]

    def col(self, name: str, qualifier: str | None = None):
        if qualifier is not None:
            if qualifier not in self.tables:
                raise ValueError(f"unknown table alias {qualifier!r}")
            return self.tables[qualifier][name]
        if name in self.ambiguous:
            raise ValueError(f"ambiguous column {name!r}: qualify it")
        if name not in self.flat:
            raise ValueError(f"unknown column {name!r}")
        return self.flat[name]

    def all_columns(self) -> dict[str, Any]:
        out: dict[str, Any] = {}
        for alias, table in self.tables.items():
            for c in table.column_names():
                key = c if c not in self.ambiguous else f"{alias}_{c}"
                out[key] = table[c]
        return out


class _Parser:
    def __init__(self, tokens: list[tuple[str, str]], tables: dict[str, Table]):
        self.toks = tokens
        self.i = 0
        self.env_tables = tables

    # --- token helpers --------------------------------------------------------

    def peek(self) -> tuple[str, str]:
        return self.toks[self.i]

    def next(self) -> tuple[str, str]:
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept(self, kind: str, text: str | None = None) -> bool:
        k, v = self.peek()
        if k == kind and (text is None or v == text):
            self.i += 1
            return True
        return False

    def expect(self, kind: str, text: str | None = None) -> str:
        k, v = self.next()
        if k != kind or (text is not None and v != text):
            raise ValueError(f"SQL parse error: expected {text or kind}, got {v!r}")
        return v

    # --- grammar --------------------------------------------------------------

    def parse(self) -> Table:
        left = self.parse_select()
        while True:
            k, v = self.peek()
            if (k, v) == ("kw", "union"):
                self.next()
                all_ = self.accept("kw", "all")
                right = self.parse_select()
                left = left.concat_reindex(right)
                if not all_:
                    left = _distinct(left)
            elif (k, v) == ("kw", "intersect"):
                self.next()
                right = self.parse_select()
                left = _intersect(left, right)
            elif (k, v) == ("kw", "except"):
                self.next()
                right = self.parse_select()
                left = _except(left, right)
            else:
                break
        self.accept("op", ";")
        if self.peek()[0] != "eof":
            raise ValueError(f"SQL parse error: trailing {self.peek()[1]!r}")
        return left

    def parse_select(self) -> Table:
        self.expect("kw", "select")
        distinct = self.accept("kw", "distinct")
        select_items = self.parse_select_list()
        self.expect("kw", "from")
        scope = self.parse_from()
        where = None
        if self.accept("kw", "where"):
            where = self.parse_expr(scope, agg_ok=False)
        group_cols = None
        if self.accept("kw", "group"):
            self.expect("kw", "by")
            group_cols = [self.parse_column_ref(scope)]
            while self.accept("op", ","):
                group_cols.append(self.parse_column_ref(scope))
        having_maker = None
        if self.accept("kw", "having"):
            having_maker = self.parse_expr_deferred()

        # --- build -------------------------------------------------------------
        base = scope.result_table
        if where is not None:
            base = base.filter(where)
            scope.rebind(base)
        if group_cols is not None:
            gb_exprs = [scope.col(n, q) for q, n in group_cols]
            grouped = base.groupby(*gb_exprs)
            exprs: dict[str, Any] = {}
            for alias, make in select_items:
                if alias == "*":
                    raise NotImplementedError("SELECT * with GROUP BY")
                exprs[alias] = make(scope)
            if having_maker is not None:
                exprs["_having"] = having_maker(scope)
            result = grouped.reduce(**exprs)
            if having_maker is not None:
                result = result.filter(result._having).select(
                    *[result[c] for c in result.column_names() if c != "_having"]
                )
        else:
            if having_maker is not None:
                raise ValueError("HAVING requires GROUP BY")
            exprs = {}
            for alias, make in select_items:
                if alias == "*":
                    exprs.update(scope.all_columns())
                else:
                    exprs[alias] = make(scope)
            result = base.select(**exprs)
        if distinct:
            result = _distinct(result)
        return result

    def parse_select_list(self):
        items: list[tuple[str, Any]] = []
        auto = 0

        def one():
            nonlocal auto
            if self.accept("op", "*"):
                return [("*", None)]
            expr_start = self.i
            e = self.parse_expr_deferred()
            alias = None
            if self.accept("kw", "as"):
                alias = self.expect("name")
            elif self.peek()[0] == "name":
                alias = self.next()[1]
            if alias is None:
                span_toks = self.toks[expr_start : self.i]
                if len(span_toks) == 1 and span_toks[0][0] == "name":
                    alias = span_toks[0][1]
                elif (
                    len(span_toks) == 3
                    and span_toks[0][0] == "name"
                    and span_toks[1] == ("op", ".")
                    and span_toks[2][0] == "name"
                ):
                    # qualified column keeps its bare column name
                    alias = span_toks[2][1]
                else:
                    span = "".join(v for _k, v in span_toks)
                    auto += 1
                    alias = re.sub(r"\W+", "_", span).strip("_") or f"col{auto}"
            return [(alias, e)]

        items.extend(one())
        while self.accept("op", ","):
            items.extend(one())
        return items

    def parse_expr_deferred(self):
        """Parse an expression syntactically now, bind to a scope later."""
        start = self.i
        self._skip_expr()
        end = self.i
        toks = self.toks[start:end]

        def make(scope):
            sub = _Parser(toks + [("eof", "")], self.env_tables)
            return sub.parse_expr(scope, agg_ok=True)

        return make

    def _skip_expr(self, depth_stop: bool = True):
        """Advance past one expression (balanced parens, stop at top-level
        comma / clause keyword / eof)."""
        depth = 0
        stop_kw = {
            "from", "where", "group", "having", "union", "intersect",
            "except", "on", "join", "inner", "left", "right", "full", "as",
            "by", "all",
        }
        while True:
            k, v = self.peek()
            if k == "eof":
                return
            if k == "op" and v == "(":
                depth += 1
            elif k == "op" and v == ")":
                if depth == 0:
                    return
                depth -= 1
            elif depth == 0:
                if k == "op" and v in (",", ";"):
                    return
                if k == "kw" and v in stop_kw:
                    return
                if k == "name":
                    pk, pv = self.toks[self.i - 1]
                    # bare alias right after a completed expression
                    if pk in ("name", "num", "str") or pv in (")", "end"):
                        return
            self.i += 1

    def parse_from(self) -> "_FromScope":
        scope = _FromScope()
        alias, table = self.parse_table_ref()
        scope.add_base(alias, table)
        while True:
            k, v = self.peek()
            how = None
            if (k, v) == ("kw", "join"):
                self.next()
                how = "inner"
            elif (k, v) in (("kw", "inner"), ("kw", "left"), ("kw", "right"), ("kw", "full")):
                self.next()
                how = {"full": "outer"}.get(v, v)
                self.accept("kw", "outer")
                self.expect("kw", "join")
            else:
                break
            alias2, table2 = self.parse_table_ref()
            self.expect("kw", "on")
            scope.add_join(alias2, table2, how, self)
        return scope

    def parse_table_ref(self) -> tuple[str, Table]:
        name = self.expect("name")
        if name not in self.env_tables:
            raise ValueError(f"unknown table {name!r} in SQL query")
        alias = name
        if self.accept("kw", "as"):
            alias = self.expect("name")
        elif self.peek()[0] == "name":
            alias = self.next()[1]
        return alias, self.env_tables[name]

    def parse_column_ref(self, scope) -> tuple[str | None, str]:
        name = self.expect("name")
        if self.accept("op", "."):
            col = self.expect("name")
            return name, col
        return None, name

    # --- expression grammar (binds to scope immediately) ----------------------

    def parse_expr(self, scope, agg_ok: bool):
        return self.parse_or(scope, agg_ok)

    def parse_or(self, scope, agg_ok):
        left = self.parse_and(scope, agg_ok)
        while self.accept("kw", "or"):
            left = left | self.parse_and(scope, agg_ok)
        return left

    def parse_and(self, scope, agg_ok):
        left = self.parse_not(scope, agg_ok)
        while self.accept("kw", "and"):
            left = left & self.parse_not(scope, agg_ok)
        return left

    def parse_not(self, scope, agg_ok):
        if self.accept("kw", "not"):
            return ~self.parse_not(scope, agg_ok)
        return self.parse_cmp(scope, agg_ok)

    def parse_cmp(self, scope, agg_ok):
        left = self.parse_add(scope, agg_ok)
        k, v = self.peek()
        if (k, v) == ("kw", "is"):
            self.next()
            neg = self.accept("kw", "not")
            self.expect("kw", "null")
            cond = left.is_none()
            return ~cond if neg else cond
        if (k, v) == ("kw", "not"):
            # NOT IN / NOT BETWEEN
            self.next()
            k2, v2 = self.peek()
            if (k2, v2) == ("kw", "in"):
                self.next()
                return ~self._in_rest(left, scope, agg_ok)
            if (k2, v2) == ("kw", "between"):
                self.next()
                return ~self._between_rest(left, scope, agg_ok)
            raise ValueError("expected IN or BETWEEN after NOT")
        if (k, v) == ("kw", "in"):
            self.next()
            return self._in_rest(left, scope, agg_ok)
        if (k, v) == ("kw", "between"):
            self.next()
            return self._between_rest(left, scope, agg_ok)
        if k == "op" and v in ("=", "==", "<>", "!=", "<", "<=", ">", ">="):
            self.next()
            right = self.parse_add(scope, agg_ok)
            if v in ("=", "=="):
                return left == right
            if v in ("<>", "!="):
                return left != right
            if v == "<":
                return left < right
            if v == "<=":
                return left <= right
            if v == ">":
                return left > right
            return left >= right
        return left

    def _in_rest(self, left, scope, agg_ok):
        self.expect("op", "(")
        vals = [self._literal_or_expr(scope, agg_ok)]
        while self.accept("op", ","):
            vals.append(self._literal_or_expr(scope, agg_ok))
        self.expect("op", ")")
        cond = left == vals[0]
        for v in vals[1:]:
            cond = cond | (left == v)
        return cond

    def _between_rest(self, left, scope, agg_ok):
        lo = self.parse_add(scope, agg_ok)
        self.expect("kw", "and")
        hi = self.parse_add(scope, agg_ok)
        return (left >= lo) & (left <= hi)

    def _literal_or_expr(self, scope, agg_ok):
        return self.parse_add(scope, agg_ok)

    def parse_add(self, scope, agg_ok):
        left = self.parse_mul(scope, agg_ok)
        while True:
            k, v = self.peek()
            if k == "op" and v in ("+", "-"):
                self.next()
                right = self.parse_mul(scope, agg_ok)
                left = left + right if v == "+" else left - right
            else:
                return left

    def parse_mul(self, scope, agg_ok):
        left = self.parse_unary(scope, agg_ok)
        while True:
            k, v = self.peek()
            if k == "op" and v in ("*", "/", "%"):
                self.next()
                right = self.parse_unary(scope, agg_ok)
                if v == "*":
                    left = left * right
                elif v == "/":
                    left = left / right
                else:
                    left = left % right
            else:
                return left

    def parse_unary(self, scope, agg_ok):
        if self.accept("op", "-"):
            return -self.parse_unary(scope, agg_ok)
        return self.parse_atom(scope, agg_ok)

    def parse_atom(self, scope, agg_ok):
        k, v = self.peek()
        if k == "num":
            self.next()
            return float(v) if ("." in v) else int(v)
        if k == "str":
            self.next()
            return v[1:-1].replace("''", "'")
        if (k, v) == ("kw", "true"):
            self.next()
            return True
        if (k, v) == ("kw", "false"):
            self.next()
            return False
        if (k, v) == ("kw", "null"):
            self.next()
            return None
        if k == "op" and v == "(":
            self.next()
            e = self.parse_expr(scope, agg_ok)
            self.expect("op", ")")
            return e
        if (k, v) == ("kw", "case"):
            return self.parse_case(scope, agg_ok)
        if k == "name":
            name = self.next()[1]
            if self.accept("op", "("):
                fn = name.lower()
                if fn not in _AGGS:
                    raise ValueError(f"unknown SQL function {name!r}")
                if not agg_ok:
                    raise ValueError(f"aggregate {name!r} not allowed here")
                if self.accept("op", "*"):
                    self.expect("op", ")")
                    return _AGGS["count"]()
                arg = self.parse_expr(scope, agg_ok=False)
                self.expect("op", ")")
                return _AGGS[fn](arg)
            if self.accept("op", "."):
                col = self.expect("name")
                return scope.col(col, name)
            return scope.col(name)
        raise ValueError(f"SQL parse error at {v!r}")

    def parse_case(self, scope, agg_ok):
        from pathway_tpu.internals.common import if_else

        self.expect("kw", "case")
        branches = []
        while self.accept("kw", "when"):
            cond = self.parse_expr(scope, agg_ok)
            self.expect("kw", "then")
            val = self.parse_expr(scope, agg_ok)
            branches.append((cond, val))
        default = None
        if self.accept("kw", "else"):
            default = self.parse_expr(scope, agg_ok)
        self.expect("kw", "end")
        out = default
        for cond, val in reversed(branches):
            out = if_else(cond, val, out)
        return out


class _FromScope(_Scope):
    """Scope that materializes joins into one flat result table."""

    def __init__(self):
        super().__init__()
        self.result_table: Table | None = None
        self._col_map: dict[tuple[str, str], str] = {}  # (alias, col) -> flat

    def add_base(self, alias: str, table: Table) -> None:
        self.add(alias, table)
        self.result_table = table
        for c in table.column_names():
            self._col_map[(alias, c)] = c

    def add_join(self, alias: str, table: Table, how: str, parser: _Parser):
        # ON-condition scope: existing aliases resolve through this scope's
        # rename map (collision-renamed columns bind to the right table);
        # the new alias resolves against the joining table directly
        outer = self

        class _OnScope:
            def col(self, name, qualifier=None):
                if qualifier == alias:
                    return table[name]
                if qualifier is not None:
                    return outer.col(name, qualifier)
                in_new = name in table.column_names()
                in_old = name in outer.flat or name in outer.ambiguous
                if in_new and in_old:
                    raise ValueError(f"ambiguous column {name!r}: qualify it")
                if in_new:
                    return table[name]
                return outer.col(name)

        cond = parser.parse_expr(_OnScope(), agg_ok=False)
        conds = [
            _orient_join_condition(c, self.result_table, table)
            for c in _conjuncts(cond)
        ]
        jr = self.result_table.join(table, *conds, how=how)
        # flatten: existing columns keep their flat names; new table's
        # columns get their names, prefixed on collision
        exprs: dict[str, Any] = {}
        for (a, c), flat in self._col_map.items():
            exprs[flat] = self.result_table[flat]
        new_map = dict(self._col_map)
        for c in table.column_names():
            flat = c
            if flat in exprs:
                flat = f"{alias}_{c}"
            exprs[flat] = table[c]
            new_map[(alias, c)] = flat
        flatt = jr.select(**exprs)
        # rebuild resolution over the flat table
        from collections import Counter

        self.result_table = flatt
        self._col_map = new_map
        self.tables = {a: flatt for a in list(self.tables) + [alias]}
        cnt = Counter(c for (_a, c) in new_map)
        self.ambiguous = {c for c, n in cnt.items() if n > 1}
        self.flat = {
            c: flatt[f]
            for (_a, c), f in new_map.items()
            if c not in self.ambiguous
        }

    def rebind(self, new_table: Table) -> None:
        """After filter(): rebind column references to the filtered table."""
        self.result_table = new_table
        self.tables = {a: new_table for a in self.tables}
        self.flat = {
            n: new_table[n]
            for n in self.flat
            if n in new_table.column_names()
        }

    def col(self, name: str, qualifier: str | None = None):
        if qualifier is not None and (qualifier, name) in self._col_map:
            return self.result_table[self._col_map[(qualifier, name)]]
        return super().col(name, qualifier)


def _orient_join_condition(cond, left_table, right_table):
    """SQL places no order on equality operands (ON b.k = a.k is valid);
    Table.join requires <left> == <right>, so flip swapped conjuncts."""
    from pathway_tpu.internals.expression import (
        ColumnBinaryOpExpression,
        ColumnReference,
    )

    if not (
        isinstance(cond, ColumnBinaryOpExpression) and cond._op == "=="
    ):
        return cond

    def side(e):
        for ref in e._dependencies():
            if isinstance(ref, ColumnReference):
                if ref.table is left_table:
                    return "l"
                if ref.table is right_table:
                    return "r"
        return None

    if side(cond._left) == "r" and side(cond._right) == "l":
        return ColumnBinaryOpExpression("==", cond._right, cond._left)
    return cond


def _conjuncts(e):
    """Split a parsed ON condition on top-level AND so composite-key joins
    reach Table.join as separate equality conditions."""
    from pathway_tpu.internals.expression import ColumnBinaryOpExpression

    if isinstance(e, ColumnBinaryOpExpression) and e._op == "&":
        return _conjuncts(e._left) + _conjuncts(e._right)
    return [e]


def _distinct(t: Table) -> Table:
    cols = t.column_names()
    return t.groupby(*[t[c] for c in cols]).reduce(*[t[c] for c in cols])


def _intersect(a: Table, b: Table) -> Table:
    cols = a.column_names()
    da, db = _distinct(a), _distinct(b)
    jr = da.join(
        db, *[da[c] == db[c] for c in cols], how="inner"
    )
    return jr.select(**{c: da[c] for c in cols})


def _except(a: Table, b: Table) -> Table:
    cols = a.column_names()
    da, db = _distinct(a), _distinct(b)
    jr = da.join(db, *[da[c] == db[c] for c in cols], how="left")
    marked = jr.select(
        **{c: da[c] for c in cols}, _hit=db.id.is_not_none()
    )
    kept = marked.filter(~marked._hit)
    return kept.select(*[kept[c] for c in cols])


def sql(query: str, **tables: Table) -> Table:
    """Execute a SQL query over the given tables
    (reference: pw.sql, internals/sql.py:613)."""
    return _Parser(_tokenize(query), tables).parse()
