"""@pw.udf — user-defined functions
(reference: python/pathway/udfs.py + internals/udfs/). Sync UDFs evaluate
batched on the host feed path; async UDFs gather per-row coroutines with
capacity/timeout/retry policies."""

from __future__ import annotations

import asyncio
import functools
import inspect
import typing
from typing import Any, Callable

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod


class CacheStrategy:
    pass


class DiskCache(CacheStrategy):
    def __init__(self, name: str | None = None):
        self.name = name


class InMemoryCache(CacheStrategy):
    pass


class DefaultCache(DiskCache):
    pass


class AsyncRetryStrategy:
    pass


class ExponentialBackoffRetryStrategy(AsyncRetryStrategy):
    def __init__(
        self,
        max_retries: int = 3,
        initial_delay_ms: int = 1000,
        backoff_factor: float = 2.0,
        jitter_ms: int = 300,
    ):
        self.max_retries = max_retries
        self.initial_delay_ms = initial_delay_ms
        self.backoff_factor = backoff_factor
        self.jitter_ms = jitter_ms


class FixedDelayRetryStrategy(ExponentialBackoffRetryStrategy):
    def __init__(self, max_retries: int = 3, delay_ms: int = 1000):
        super().__init__(max_retries, delay_ms, 1.0, 0)


class NoRetryStrategy(AsyncRetryStrategy):
    pass


def async_options(**kwargs):
    def wrapper(fn):
        return fn

    return wrapper


def run_async_blocking(coro_factory: Callable[[], Any]) -> Any:
    """Run a coroutine to completion from sync code, whether or not an
    event loop is already running in this thread (shared by the expression
    evaluator's async apply and AsyncTransformer)."""
    try:
        asyncio.get_running_loop()
    except RuntimeError:
        return asyncio.run(coro_factory())
    import concurrent.futures

    with concurrent.futures.ThreadPoolExecutor(1) as pool:
        return pool.submit(lambda: asyncio.run(coro_factory())).result()


def coerce_async(fn: Callable) -> Callable:
    if asyncio.iscoroutinefunction(fn):
        return fn

    @functools.wraps(fn)
    async def wrapper(*args, **kwargs):
        return fn(*args, **kwargs)

    return wrapper


def with_cache_strategy(fn, cache_strategy):
    memo: dict = {}

    if asyncio.iscoroutinefunction(fn):

        @functools.wraps(fn)
        async def cached_async(*args):
            key = args
            if key in memo:
                return memo[key]
            result = await fn(*args)
            memo[key] = result
            return result

        return cached_async

    @functools.wraps(fn)
    def cached(*args):
        key = args
        if key in memo:
            return memo[key]
        result = fn(*args)
        memo[key] = result
        return result

    return cached


def with_retry_strategy(fn, retry_strategy: AsyncRetryStrategy):
    if isinstance(retry_strategy, NoRetryStrategy) or not isinstance(
        retry_strategy, ExponentialBackoffRetryStrategy
    ):
        return fn

    @functools.wraps(fn)
    async def retried(*args, **kwargs):
        delay = retry_strategy.initial_delay_ms / 1000
        last: Exception | None = None
        for attempt in range(retry_strategy.max_retries + 1):
            try:
                return await fn(*args, **kwargs)
            except Exception as exc:
                last = exc
                if attempt == retry_strategy.max_retries:
                    raise
                await asyncio.sleep(delay)
                delay *= retry_strategy.backoff_factor
        raise last  # pragma: no cover

    return retried


class UDF:
    """Base class for user-defined functions
    (subclass with __wrapped__, or produced by @pw.udf)."""

    def __init__(
        self,
        *,
        return_type: Any = None,
        deterministic: bool = False,
        propagate_none: bool = False,
        executor: Any = None,
        cache_strategy: CacheStrategy | None = None,
        retry_strategy: AsyncRetryStrategy | None = None,
        timeout: float | None = None,
        max_batch_size: int | None = None,
        **kwargs: Any,
    ):
        self._return_type = return_type
        self._deterministic = deterministic
        self._propagate_none = propagate_none
        self._cache_strategy = cache_strategy
        self._retry_strategy = retry_strategy
        self._timeout = timeout
        self._max_batch_size = max_batch_size
        if hasattr(self, "__wrapped__"):
            self._prepare(self.__wrapped__)

    def _prepare(self, fn: Callable) -> None:
        self._fn_raw = fn
        self._is_async = asyncio.iscoroutinefunction(fn)
        fn2 = fn
        if self._cache_strategy is not None:
            fn2 = with_cache_strategy(fn2, self._cache_strategy)
        if self._is_async and self._retry_strategy is not None:
            fn2 = with_retry_strategy(fn2, self._retry_strategy)
        if self._is_async and self._timeout is not None:
            inner = fn2

            @functools.wraps(fn)
            async def timed(*args, **kwargs):
                return await asyncio.wait_for(
                    inner(*args, **kwargs), timeout=self._timeout
                )

            fn2 = timed
        self._fn = fn2
        if self._return_type is None:
            try:
                hints = typing.get_type_hints(fn)
                self._return_type = hints.get("return", Any)
            except Exception:
                self._return_type = Any

    @property
    def func(self) -> Callable:
        return self._fn_raw

    _batched = False

    def __call__(self, *args: Any, **kwargs: Any) -> expr_mod.ColumnExpression:
        if not hasattr(self, "_fn"):
            self._prepare(self.__wrapped__)  # type: ignore[attr-defined]
        if self._batched and self._is_async:
            raise TypeError(
                "batched UDFs must be synchronous (the batch already "
                "amortizes latency); drop async or _batched"
            )
        if self._batched:
            cls: Any = expr_mod.BatchApplyExpression
        elif self._is_async:
            cls = expr_mod.AsyncApplyExpression
        else:
            cls = expr_mod.ApplyExpression
        return cls(
            self._fn,
            self._return_type,
            self._propagate_none,
            self._deterministic,
            args,
            kwargs,
            max_batch_size=self._max_batch_size,
        )


def udf(
    fn: Callable | None = None,
    /,
    *,
    return_type: Any = None,
    deterministic: bool = False,
    propagate_none: bool = False,
    executor: Any = None,
    cache_strategy: CacheStrategy | None = None,
    retry_strategy: AsyncRetryStrategy | None = None,
    timeout: float | None = None,
    max_batch_size: int | None = None,
    **kwargs: Any,
):
    """Decorator turning a function into a column-expression builder."""

    def make(f: Callable) -> UDF:
        u = UDF(
            return_type=return_type,
            deterministic=deterministic,
            propagate_none=propagate_none,
            executor=executor,
            cache_strategy=cache_strategy,
            retry_strategy=retry_strategy,
            timeout=timeout,
            max_batch_size=max_batch_size,
        )
        u._prepare(f)
        functools.update_wrapper(u, f, updated=[])
        return u

    if fn is not None:
        return make(fn)
    return make


# executors façade (reference: internals/udfs/executors.py)
def auto_executor():
    return None


def sync_executor():
    return None


def async_executor(capacity: int | None = None, timeout: float | None = None):
    return None


def fully_async_executor(**kwargs):
    return None
