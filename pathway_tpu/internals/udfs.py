"""@pw.udf — user-defined functions
(reference: python/pathway/udfs.py + internals/udfs/). Sync UDFs evaluate
batched on the host feed path; async UDFs gather per-row coroutines with
capacity/timeout/retry policies."""

from __future__ import annotations

import asyncio
import functools
import inspect
import typing
from typing import Any, Callable

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod


class CacheStrategy:
    pass


class DiskCache(CacheStrategy):
    def __init__(self, name: str | None = None):
        self.name = name


class InMemoryCache(CacheStrategy):
    pass


class DefaultCache(DiskCache):
    pass


class AsyncRetryStrategy:
    pass


class ExponentialBackoffRetryStrategy(AsyncRetryStrategy):
    def __init__(
        self,
        max_retries: int = 3,
        initial_delay_ms: int = 1000,
        backoff_factor: float = 2.0,
        jitter_ms: int = 300,
    ):
        self.max_retries = max_retries
        self.initial_delay_ms = initial_delay_ms
        self.backoff_factor = backoff_factor
        self.jitter_ms = jitter_ms


class FixedDelayRetryStrategy(ExponentialBackoffRetryStrategy):
    def __init__(self, max_retries: int = 3, delay_ms: int = 1000):
        super().__init__(max_retries, delay_ms, 1.0, 0)


class NoRetryStrategy(AsyncRetryStrategy):
    pass


def with_capacity(fn: Callable, capacity: int) -> Callable:
    """Bound concurrent invocations of an async fn with a semaphore
    (reference: executors.py with_capacity). One semaphore per event loop:
    each engine tick runs its own asyncio.run, and a semaphore must not
    carry waiters across loops."""
    import weakref

    sems: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()

    @functools.wraps(fn)
    async def limited(*args, **kwargs):
        loop = asyncio.get_running_loop()
        sem = sems.get(loop)
        if sem is None:
            sem = asyncio.Semaphore(capacity)
            sems[loop] = sem
        async with sem:
            return await fn(*args, **kwargs)

    return limited


def with_timeout(fn: Callable, timeout: float) -> Callable:
    @functools.wraps(fn)
    async def timed(*args, **kwargs):
        return await asyncio.wait_for(fn(*args, **kwargs), timeout=timeout)

    return timed


def async_options(
    capacity: int | None = None,
    timeout: float | None = None,
    retry_strategy: "AsyncRetryStrategy | None" = None,
    cache_strategy: "CacheStrategy | None" = None,
):
    """Decorator applying the async execution options to a coroutine fn
    (reference: udfs.async_options)."""

    def wrapper(fn):
        fn = coerce_async(fn)
        if cache_strategy is not None:
            fn = with_cache_strategy(fn, cache_strategy)
        if retry_strategy is not None:
            fn = with_retry_strategy(fn, retry_strategy)
        if timeout is not None:
            fn = with_timeout(fn, timeout)
        if capacity is not None:
            fn = with_capacity(fn, capacity)
        return fn

    return wrapper


def run_async_blocking(coro_factory: Callable[[], Any]) -> Any:
    """Run a coroutine to completion from sync code, whether or not an
    event loop is already running in this thread (shared by the expression
    evaluator's async apply and AsyncTransformer)."""
    try:
        asyncio.get_running_loop()
    except RuntimeError:
        return asyncio.run(coro_factory())
    import concurrent.futures

    with concurrent.futures.ThreadPoolExecutor(1) as pool:
        return pool.submit(lambda: asyncio.run(coro_factory())).result()


def coerce_async(fn: Callable) -> Callable:
    """Lift a sync fn to a coroutine running in the default thread pool —
    calling it inline would serialize the whole gather behind each
    blocking call (reference: executors.py coerce_async dispatches sync
    fns via run_in_executor)."""
    if asyncio.iscoroutinefunction(fn):
        return fn

    @functools.wraps(fn)
    async def wrapper(*args, **kwargs):
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, functools.partial(fn, *args, **kwargs)
        )

    return wrapper


def with_cache_strategy(fn, cache_strategy):
    memo: dict = {}

    if asyncio.iscoroutinefunction(fn):
        # coalesce CONCURRENT calls for the same key: rows of one batch
        # fire simultaneously, and each key must compute exactly once
        # (reference: async caches share the in-flight future). In-flight
        # state is scoped per event loop (one asyncio.run per tick) via a
        # weak mapping, like with_capacity; only the RESULT memo persists
        # across batches.
        import weakref

        inflight_by_loop: "weakref.WeakKeyDictionary" = (
            weakref.WeakKeyDictionary()
        )

        @functools.wraps(fn)
        async def cached_async(*args):
            key = args
            loop = asyncio.get_running_loop()
            inflight = inflight_by_loop.setdefault(loop, {})
            while True:
                if key in memo:
                    return memo[key]
                fut = inflight.get(key)
                if fut is not None:
                    try:
                        return await fut
                    except asyncio.CancelledError:
                        if fut.cancelled():
                            # the OWNER was cancelled (e.g. its timeout):
                            # retry — this waiter may become the owner and
                            # still produce a per-row result
                            continue
                        raise  # this waiter itself was cancelled
                fut = loop.create_future()
                inflight[key] = fut
                try:
                    result = await fn(*args)
                except asyncio.CancelledError:
                    # do NOT broadcast cancellation as an exception: cancel
                    # the shared future so waiters recompute; the owner's
                    # own cancellation propagates (wait_for turns it into
                    # TimeoutError -> a clean per-row ERROR)
                    inflight.pop(key, None)
                    fut.cancel()
                    raise
                except BaseException as exc:
                    inflight.pop(key, None)
                    fut.set_exception(exc)
                    # consume so an un-awaited future does not warn;
                    # waiters re-raise via the shared future
                    fut.exception()
                    raise
                memo[key] = result
                fut.set_result(result)
                inflight.pop(key, None)
                return result

        return cached_async

    @functools.wraps(fn)
    def cached(*args):
        key = args
        if key in memo:
            return memo[key]
        result = fn(*args)
        memo[key] = result
        return result

    return cached


def with_retry_strategy(fn, retry_strategy: AsyncRetryStrategy):
    if isinstance(retry_strategy, NoRetryStrategy) or not isinstance(
        retry_strategy, ExponentialBackoffRetryStrategy
    ):
        return fn

    @functools.wraps(fn)
    async def retried(*args, **kwargs):
        delay = retry_strategy.initial_delay_ms / 1000
        last: Exception | None = None
        for attempt in range(retry_strategy.max_retries + 1):
            try:
                return await fn(*args, **kwargs)
            except Exception as exc:
                last = exc
                if attempt == retry_strategy.max_retries:
                    raise
                await asyncio.sleep(delay)
                delay *= retry_strategy.backoff_factor
        raise last  # pragma: no cover

    return retried


class UDF:
    """Base class for user-defined functions
    (subclass with __wrapped__, or produced by @pw.udf)."""

    def __init__(
        self,
        *,
        return_type: Any = None,
        deterministic: bool | None = None,
        propagate_none: bool = False,
        executor: Any = None,
        cache_strategy: CacheStrategy | None = None,
        retry_strategy: AsyncRetryStrategy | None = None,
        timeout: float | None = None,
        max_batch_size: int | None = None,
        **kwargs: Any,
    ):
        self._return_type = return_type
        self._deterministic = deterministic
        self._propagate_none = propagate_none
        self._cache_strategy = cache_strategy
        self._retry_strategy = retry_strategy
        self._timeout = timeout
        self._max_batch_size = max_batch_size
        self._executor = executor
        if hasattr(self, "__wrapped__"):
            self._prepare(self.__wrapped__)

    def _prepare(self, fn: Callable) -> None:
        self._fn_raw = fn
        self._is_async = asyncio.iscoroutinefunction(fn)
        fn2 = fn
        ex = getattr(self, "_executor", None)
        if isinstance(ex, AsyncExecutor):
            # async execution requested: lift sync fns and fold the
            # executor's options into the UDF-level ones
            fn2 = coerce_async(fn2)
            self._is_async = True
            if ex.retry_strategy is not None and self._retry_strategy is None:
                self._retry_strategy = ex.retry_strategy
            if ex.timeout is not None and self._timeout is None:
                self._timeout = ex.timeout
        elif isinstance(ex, SyncExecutor) and self._is_async:
            raise TypeError(
                "sync_executor() cannot run a coroutine function"
            )
        if self._cache_strategy is not None:
            fn2 = with_cache_strategy(fn2, self._cache_strategy)
        if self._is_async and self._retry_strategy is not None:
            fn2 = with_retry_strategy(fn2, self._retry_strategy)
        if self._is_async and self._timeout is not None:
            fn2 = with_timeout(fn2, self._timeout)
        if isinstance(ex, AsyncExecutor) and ex.capacity is not None:
            # outermost so the concurrency bound covers retries + timeout
            fn2 = with_capacity(fn2, ex.capacity)
        self._fn = fn2
        if self._return_type is None:
            try:
                hints = typing.get_type_hints(fn)
                self._return_type = hints.get("return", Any)
            except Exception:
                self._return_type = Any

    @property
    def func(self) -> Callable:
        return self._fn_raw

    _batched = False

    def __call__(self, *args: Any, **kwargs: Any) -> expr_mod.ColumnExpression:
        if not hasattr(self, "_fn"):
            self._prepare(self.__wrapped__)  # type: ignore[attr-defined]
        if self._batched and self._is_async:
            raise TypeError(
                "batched UDFs must be synchronous (the batch already "
                "amortizes latency); drop async or _batched"
            )
        if self._batched:
            cls: Any = expr_mod.BatchApplyExpression
        elif self._is_async:
            cls = expr_mod.AsyncApplyExpression
        else:
            cls = expr_mod.ApplyExpression
        expr = cls(
            self._fn,
            self._return_type,
            self._propagate_none,
            self._deterministic,
            args,
            kwargs,
            max_batch_size=self._max_batch_size,
        )
        # provenance for static analysis: which UDF produced this node
        expr._udf_name = getattr(self._fn_raw, "__name__", None)
        return expr

    @property
    def deterministic(self) -> bool | None:
        """Tri-state determinism declaration: True (re-evaluation under
        retraction/replay yields identical values), False (explicitly
        non-deterministic — the Graph Doctor's shard-safety rule flags it
        when it feeds an exchange boundary), or None (unspecified)."""
        return self._deterministic


def udf(
    fn: Callable | None = None,
    /,
    *,
    return_type: Any = None,
    deterministic: bool | None = None,
    propagate_none: bool = False,
    executor: Any = None,
    cache_strategy: CacheStrategy | None = None,
    retry_strategy: AsyncRetryStrategy | None = None,
    timeout: float | None = None,
    max_batch_size: int | None = None,
    **kwargs: Any,
):
    """Decorator turning a function into a column-expression builder."""

    def make(f: Callable) -> UDF:
        u = UDF(
            return_type=return_type,
            deterministic=deterministic,
            propagate_none=propagate_none,
            executor=executor,
            cache_strategy=cache_strategy,
            retry_strategy=retry_strategy,
            timeout=timeout,
            max_batch_size=max_batch_size,
        )
        u._prepare(f)
        functools.update_wrapper(u, f, updated=[])
        return u

    if fn is not None:
        return make(fn)
    return make


# executors (reference: internals/udfs/executors.py:36-225)


class Executor:
    """Execution strategy marker for @pw.udf(executor=...)."""


class AutoExecutor(Executor):
    """Sync for plain functions, async for coroutines (the default)."""


class SyncExecutor(Executor):
    """Force synchronous in-batch evaluation."""


class AsyncExecutor(Executor):
    """Run the UDF asynchronously (a sync fn is lifted to a coroutine)
    with optional concurrency capacity, timeout and retries."""

    def __init__(
        self,
        capacity: int | None = None,
        timeout: float | None = None,
        retry_strategy: AsyncRetryStrategy | None = None,
    ):
        self.capacity = capacity
        self.timeout = timeout
        self.retry_strategy = retry_strategy


class FullyAsyncExecutor(AsyncExecutor):
    """Results may arrive across ticks in the reference; under the
    totally-ordered microbatch engine the batch completes within its tick
    (same stance as AsyncTransformer), so this behaves as AsyncExecutor."""


def auto_executor() -> Executor:
    return AutoExecutor()


def sync_executor() -> Executor:
    return SyncExecutor()


def async_executor(
    capacity: int | None = None,
    timeout: float | None = None,
    retry_strategy: AsyncRetryStrategy | None = None,
) -> Executor:
    return AsyncExecutor(
        capacity=capacity, timeout=timeout, retry_strategy=retry_strategy
    )


def fully_async_executor(
    capacity: int | None = None,
    timeout: float | None = None,
    retry_strategy: AsyncRetryStrategy | None = None,
) -> Executor:
    return FullyAsyncExecutor(
        capacity=capacity, timeout=timeout, retry_strategy=retry_strategy
    )
