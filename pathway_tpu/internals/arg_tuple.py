"""ArgTuple — named multi-value returns
(reference: python/pathway/internals/arg_tuple.py): functions returning
dicts/iterables get a tuple-ish wrapper with attribute, item and unpacking
access; single values unwrap to the bare value."""

from __future__ import annotations

from typing import Any, Callable


class ArgTuple:
    def __init__(self, entries: dict[str, Any]):
        self._entries = dict(entries)

    def __iter__(self):
        return iter(self._entries.values())

    def __len__(self) -> int:
        return len(self._entries)

    def __getitem__(self, key: str) -> Any:
        return self._entries[str(key)]

    def __getattr__(self, name: str) -> Any:
        try:
            return self.__dict__["_entries"][name]
        except KeyError:
            raise AttributeError(name) from None

    def __eq__(self, other: object) -> bool:
        if isinstance(other, ArgTuple):
            return self._entries == other._entries
        return tuple(self) == other

    def __hash__(self) -> int:
        try:
            return hash(tuple(self._entries.values()))
        except TypeError:
            return hash(tuple(self._entries.keys()))

    def __repr__(self) -> str:
        inner = ", ".join(f"{k}={v!r}" for k, v in self._entries.items())
        return f"ArgTuple({inner})"


def _wrap_value(value: Any) -> Any:
    if isinstance(value, dict):
        if len(value) == 1:
            only = next(iter(value.values()))
            wrapped = ArgTuple(value)
            # single-entry dicts keep named access but also compare/unwrap
            # like the bare value
            return wrapped if not _is_plain(only) else _Single(value)
        return ArgTuple(value)
    if isinstance(value, (list, tuple)):
        if len(value) == 1:
            return value[0]
        return ArgTuple({str(i): v for i, v in enumerate(value)})
    return value


def _is_plain(v: Any) -> bool:
    return not isinstance(v, (dict, list, tuple))


class _Single(ArgTuple):
    """One named value: accessible by name AND equal to the bare value."""

    def __hash__(self) -> int:
        (v,) = list(self._entries.values())
        try:
            return hash(v)  # consistent with equality to the bare value
        except TypeError:
            return super().__hash__()

    def __eq__(self, other: object) -> bool:
        (v,) = list(self._entries.values())
        res = v == other
        # == on array-like/expression values returns non-bools; only
        # short-circuit on a genuine boolean result
        if isinstance(res, bool) and res:
            return True
        return super().__eq__(other)


def wrap_arg_tuple(fn: Callable) -> Callable:
    def wrapped(*args: Any, **kwargs: Any) -> Any:
        return _wrap_value(fn(*args, **kwargs))

    return wrapped
