"""YAML app loader (reference: python/pathway/internals/yaml_loader.py,
`pw.load_yaml`): declarative app assembly — `$ref`-style class instantiation
with `!pw.module.Class` tags expressed as `$class` mappings."""

from __future__ import annotations

import importlib
from typing import Any, IO


def _instantiate(obj: Any, definitions: dict[str, Any]) -> Any:
    if isinstance(obj, dict):
        if "$ref" in obj:
            name = obj["$ref"]
            if name not in definitions:
                raise ValueError(f"unresolved $ref: {name}")
            return definitions[name]
        if "$class" in obj:
            path = obj["$class"]
            module_name, _, cls_name = path.rpartition(".")
            cls = getattr(importlib.import_module(module_name), cls_name)
            kwargs = {
                k: _instantiate(v, definitions)
                for k, v in obj.items()
                if k != "$class"
            }
            return cls(**kwargs)
        return {k: _instantiate(v, definitions) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_instantiate(v, definitions) for v in obj]
    return obj


def load_yaml(stream: str | IO) -> Any:
    try:
        import yaml
    except ImportError as exc:  # pragma: no cover
        raise ImportError("pyyaml is required for pw.load_yaml") from exc
    if hasattr(stream, "read"):
        data = yaml.safe_load(stream)
    else:
        import os

        if isinstance(stream, str) and os.path.exists(stream):
            with open(stream) as f:
                data = yaml.safe_load(f)
        else:
            data = yaml.safe_load(stream)
    if not isinstance(data, dict):
        return data
    definitions: dict[str, Any] = {}
    out: dict[str, Any] = {}
    for key, val in data.items():
        inst = _instantiate(val, definitions)
        definitions[key] = inst
        out[key] = inst
    return out
