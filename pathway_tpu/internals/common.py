"""Free-standing expression constructors
(reference: python/pathway/internals/common.py:96-230)."""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr


def apply(fn: Callable, *args: Any, **kwargs: Any) -> expr.ColumnExpression:
    """Apply a python function per row. Result type from fn annotations if
    available."""
    import inspect
    import typing

    try:
        sig = inspect.signature(fn)
    except (ValueError, TypeError):
        sig = None  # builtins without introspectable signatures
    if sig is not None:
        try:
            sig.bind(*args, **kwargs)
        except TypeError as exc:
            raise AssertionError(
                f"pw.apply arguments do not match {fn!r}: {exc}"
            ) from None

    ret = Any
    try:
        hints = typing.get_type_hints(fn)
        ret = hints.get("return", Any)
    except Exception:
        pass
    return expr.ApplyExpression(fn, ret, False, True, args, kwargs)


def apply_with_type(
    fn: Callable, ret_type: Any, *args: Any, **kwargs: Any
) -> expr.ColumnExpression:
    return expr.ApplyExpression(fn, ret_type, False, True, args, kwargs)


def apply_async(fn: Callable, *args: Any, **kwargs: Any) -> expr.ColumnExpression:
    import typing

    ret = Any
    try:
        hints = typing.get_type_hints(fn)
        ret = hints.get("return", Any)
    except Exception:
        pass
    return expr.AsyncApplyExpression(fn, ret, False, True, args, kwargs)


def declare_type(target_type: Any, col: Any) -> expr.ColumnExpression:
    return expr.DeclareTypeExpression(target_type, col)


def cast(target_type: Any, col: Any) -> expr.ColumnExpression:
    return expr.CastExpression(target_type, col)


def coalesce(*args: Any) -> expr.ColumnExpression:
    return expr.CoalesceExpression(*args)


def require(val: Any, *deps: Any) -> expr.ColumnExpression:
    return expr.RequireExpression(val, *deps)


def if_else(if_clause: Any, then_clause: Any, else_clause: Any) -> expr.ColumnExpression:
    return expr.IfElseExpression(if_clause, then_clause, else_clause)


def make_tuple(*args: Any) -> expr.ColumnExpression:
    return expr.MakeTupleExpression(*args)


def unwrap(col: Any) -> expr.ColumnExpression:
    return expr.UnwrapExpression(col)


def fill_error(col: Any, replacement: Any) -> expr.ColumnExpression:
    return expr.FillErrorExpression(col, replacement)


def assert_table_has_schema(table, schema, **kwargs) -> None:
    from pathway_tpu.internals.schema import assert_table_has_schema as _impl

    _impl(table, schema, **kwargs)
