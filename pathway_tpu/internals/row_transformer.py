"""Row transformers — ``@pw.transformer`` classes with demand-driven,
possibly recursive per-row computations (reference:
python/pathway/internals/row_transformer.py:1-294 and the engine's complex
columns, src/engine/dataflow/complex_columns.rs:1-489).

A transformer class declares inner ``ClassArg`` tables whose attributes
are either inputs (``input_attribute``/``input_method``), cached
intermediates (``attribute``), or outputs (``output_attribute`` /
``method``). Compute functions receive a row reference as ``self`` and may
follow pointers into any table of the same transformer via
``self.transformer.<table>[ptr]`` — the demand-driven part.

Engine mapping: the reference compiles these to demand-subscription
dataflow (Computer/ComplexColumn); under the totally-ordered microbatch
engine each transformer output is one operator that keeps the current
state of every argument table, re-derives its rows when any input ticks,
and emits only the changed output rows. Per-tick memoization gives the
same sharing the reference's demand graph provides within one time."""

from __future__ import annotations

import inspect
import types
from typing import Any, Callable

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.engine.nodes import Node, NodeExec
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.api import ERROR, Pointer, ref_scalar
from pathway_tpu.internals.errors import record_error
from pathway_tpu.internals.universe import Universe


# --------------------------------------------------------------------------
# attribute descriptors


class _Attr:
    def __init__(
        self,
        kind: str,
        func: Callable | None = None,
        output_name: str | None = None,
        dtype: Any = None,
    ):
        self.kind = kind  # input | input_method | attribute | output | method
        self.func = func
        self.name: str | None = None
        self._output_name = output_name
        self._dtype = dtype
        if func is not None:
            self.__doc__ = func.__doc__

    def __set_name__(self, owner, name):
        if self.name is None:
            self.name = name

    @property
    def output_name(self) -> str:
        return self._output_name or self.name  # type: ignore[return-value]

    @property
    def is_output(self) -> bool:
        return self.kind in ("output", "method")

    def dtype(self) -> dt.DType:
        if self._dtype is not None:
            return dt.wrap(self._dtype)
        if self.func is not None:
            try:
                sig = inspect.signature(self.func)
                if sig.return_annotation is not inspect.Signature.empty:
                    return dt.wrap(sig.return_annotation)
            except (ValueError, TypeError):
                pass
        return dt.ANY


def input_attribute(dtype: Any = None) -> _Attr:
    """Reads the input column with the attribute's name."""
    return _Attr("input", dtype=dtype)


def input_method(dtype: Any = None) -> _Attr:
    """An input column holding callables (another transformer's method)."""
    return _Attr("input_method", dtype=dtype)


def _deco(kind: str):
    def factory(func: Callable | None = None, /, **params):
        if func is None:
            return lambda f: _Attr(kind, f, **params)
        return _Attr(kind, func, **params)

    return factory


attribute = _deco("attribute")
output_attribute = _deco("output")
method = _deco("method")


# --------------------------------------------------------------------------
# ClassArg


class ClassArg:
    """Base for a transformer's inner table classes (reference:
    row_transformer.py ClassArg)."""

    _attributes: dict[str, _Attr]
    _index: int
    transformer: "Transformer"
    id: Pointer

    def __init_subclass__(cls, /, input: Any = Any, output: Any = Any, **kw):
        super().__init_subclass__(**kw)
        attrs: dict[str, _Attr] = {}
        for klass in reversed(cls.__mro__):
            for name, value in vars(klass).items():
                if isinstance(value, _Attr):
                    attrs[name] = value
        cls._attributes = attrs
        cls.input_schema = input
        out_names = [a.output_name for a in attrs.values() if a.is_output]
        if output is not Any and output is not None:
            declared = set(output.column_names())
            if declared != set(out_names):
                raise RuntimeError(
                    f"output schema validation error: declared columns "
                    f"{sorted(declared)}, transformer produces "
                    f"{sorted(out_names)}"
                )
        cls.output_schema = output

    @staticmethod
    def pointer_from(*args, optional: bool = False) -> Pointer:
        return ref_scalar(*args, optional=optional)


# --------------------------------------------------------------------------
# runtime row references


class _Env:
    """One tick's evaluation context: live state of every argument table +
    per-(table,row,attr) memo so shared sub-computations run once."""

    __slots__ = ("states", "col_idx", "memo", "transformer")

    def __init__(self, transformer: "Transformer", states, col_idx):
        self.transformer = transformer
        self.states = states  # list[dict ptr -> vals tuple]
        self.col_idx = col_idx  # list[dict col name -> position]
        self.memo: dict = {}

    def row_vals(self, ca: type, ptr: int) -> tuple:
        rows = self.states[ca._index]
        vals = rows.get(ptr)
        if vals is None:
            raise KeyError(
                f"row {Pointer(ptr)} not present in transformer table "
                f"{ca.__name__!r}"
            )
        return vals


class RowRef:
    __slots__ = ("_env", "_ca", "_ptr")

    def __init__(self, env: _Env, ca: type, ptr: int):
        object.__setattr__(self, "_env", env)
        object.__setattr__(self, "_ca", ca)
        object.__setattr__(self, "_ptr", ptr)

    @property
    def id(self) -> Pointer:
        return Pointer(self._ptr)

    @property
    def transformer(self) -> "_TransformerRef":
        return _TransformerRef(self._env)

    @staticmethod
    def pointer_from(*args, optional: bool = False) -> Pointer:
        return ref_scalar(*args, optional=optional)

    def __getattr__(self, name: str):
        ca = self._ca
        a = ca._attributes.get(name)
        if a is None:
            static = inspect.getattr_static(ca, name, None)
            if static is None:
                raise AttributeError(name)
            if isinstance(static, staticmethod):
                return static.__func__
            if isinstance(static, (types.FunctionType,)):
                return types.MethodType(static, self)
            if isinstance(static, property):
                return static.fget(self)  # type: ignore[misc]
            return static
        env = self._env
        if a.kind in ("input", "input_method"):
            vals = env.row_vals(ca, self._ptr)
            return vals[env.col_idx[ca._index][name]]
        if a.kind in ("attribute", "output"):
            key = (ca._index, self._ptr, name)
            if key not in env.memo:
                env.memo[key] = a.func(self)
            return env.memo[key]
        # method: bind lazily so other rows can call it with arguments
        return types.MethodType(a.func, self)


class _TransformerRef:
    __slots__ = ("_env",)

    def __init__(self, env: _Env):
        self._env = env

    def __getattr__(self, name: str):
        ca = self._env.transformer.class_args.get(name)
        if ca is None:
            raise AttributeError(name)
        return _TableAccessor(self._env, ca)


class _TableAccessor:
    __slots__ = ("_env", "_ca")

    def __init__(self, env: _Env, ca: type):
        self._env = env
        self._ca = ca

    def __getitem__(self, ptr) -> RowRef:
        return RowRef(self._env, self._ca, int(ptr))


class _BoundMethod:
    """Emitted value of a ``method`` output column: callable against the
    operator's live state, comparable by identity of (table,row,method) so
    re-emission diffs stay quiet. Pickles by (table index, row, name) —
    ``load_state`` rebinds the live exec after a persistence resume."""

    __slots__ = ("exec_ref", "ca_index", "ptr", "attr_name")

    def __init__(self, exec_ref, ca_index, ptr, attr_name):
        self.exec_ref = exec_ref
        self.ca_index = ca_index
        self.ptr = ptr
        self.attr_name = attr_name

    def _ca(self):
        tr = self.exec_ref.node.transformer
        return list(tr.class_args.values())[self.ca_index]

    def __call__(self, *args):
        env = self.exec_ref._make_env()
        ca = self._ca()
        a = ca._attributes[self.attr_name]
        return a.func(RowRef(env, ca, self.ptr), *args)

    def __eq__(self, other):
        return (
            isinstance(other, _BoundMethod)
            and (self.ca_index, self.ptr, self.attr_name)
            == (other.ca_index, other.ptr, other.attr_name)
        )

    def __hash__(self):
        return hash((self.ca_index, self.ptr, self.attr_name))

    def __getstate__(self):
        return (self.ca_index, self.ptr, self.attr_name)

    def __setstate__(self, state):
        self.ca_index, self.ptr, self.attr_name = state
        self.exec_ref = None


# --------------------------------------------------------------------------
# engine operator


class RowTransformerNode(Node):
    def __init__(self, input_nodes, transformer: "Transformer", ca: type):
        out_cols = [
            a.output_name for a in ca._attributes.values() if a.is_output
        ]
        super().__init__(list(input_nodes), out_cols)
        self.transformer = transformer
        self.ca = ca

    def make_exec(self):
        return RowTransformerExec(self)


class RowTransformerExec(NodeExec):
    def __init__(self, node: RowTransformerNode):
        super().__init__(node)
        self.states: list[dict[int, tuple]] = [
            {} for _ in node.inputs
        ]
        self.col_idx = [
            {n: i for i, n in enumerate(inp.column_names)}
            for inp in node.inputs
        ]
        self.emitted: dict[int, tuple] = {}
        self.out_attrs = [
            a for a in node.ca._attributes.values() if a.is_output
        ]

    def state_dict(self):
        # `emitted` must persist too: without it the first tick after a
        # resume would re-emit every row as +1 and double-count downstream
        return {"states": self.states, "emitted": self.emitted}

    def load_state(self, state):
        self.states = state["states"]
        self.emitted = state.get("emitted", {})
        for vals in self.emitted.values():
            for v in vals:
                if isinstance(v, _BoundMethod):
                    v.exec_ref = self

    def _make_env(self) -> _Env:
        return _Env(self.node.transformer, self.states, self.col_idx)

    def process(self, t, inputs):
        changed = False
        for state, batches in zip(self.states, inputs):
            for b in batches:
                for k, d, vals in b.iter_rows():
                    changed = True
                    if d > 0:
                        state[k] = vals
                    else:
                        state.pop(k, None)
        if not changed:
            return []
        # demand-driven recursion can make any row's output depend on any
        # other row, so re-derive the whole table and emit only changes
        env = self._make_env()
        ca = self.node.ca
        own = self.states[ca._index]
        new_vals: dict[int, tuple] = {}
        for ptr in own:
            row = RowRef(env, ca, ptr)
            out = []
            for a in self.out_attrs:
                if a.kind == "method":
                    out.append(_BoundMethod(self, ca._index, ptr, a.name))
                    continue
                try:
                    out.append(getattr(row, a.name))
                except Exception as exc:
                    record_error(exc, str(self.node))
                    out.append(ERROR)
            new_vals[ptr] = tuple(out)
        from pathway_tpu.engine.batch import _values_eq

        out_rows: list[tuple[int, int, tuple]] = []
        for k in set(self.emitted) | set(new_vals):
            old = self.emitted.get(k)
            new = new_vals.get(k)
            if old is not None and new is not None and _values_eq(old, new):
                continue
            if old is not None:
                out_rows.append((k, -1, old))
                del self.emitted[k]
            if new is not None:
                out_rows.append((k, 1, new))
                self.emitted[k] = new
        if not out_rows:
            return []
        return [DiffBatch.from_rows(out_rows, self.node.column_names)]


# --------------------------------------------------------------------------
# the decorator


class _Result:
    def __init__(self, tables: dict[str, Any]):
        self._tables = tables

    def __getattr__(self, name: str):
        try:
            return self._tables[name]
        except KeyError:
            raise AttributeError(name)


class Transformer:
    def __init__(self, name: str, class_args: dict[str, type]):
        self.name = name
        self.class_args = class_args
        for i, ca in enumerate(class_args.values()):
            ca._index = i
            ca.transformer = self

    def __call__(self, *tables, **kwargs) -> _Result:
        from pathway_tpu.internals.table import Table

        if len(tables) > len(self.class_args):
            raise TypeError(
                f"transformer {self.name} takes {len(self.class_args)} "
                f"table(s) but {len(tables)} were given"
            )
        matched = dict(zip(self.class_args.keys(), tables))
        for name in kwargs:
            if name in matched:
                raise TypeError(
                    f"transformer {self.name} got multiple tables for "
                    f"argument {name!r}"
                )
        matched.update(kwargs)
        if set(matched) != set(self.class_args):
            raise TypeError(
                f"transformer {self.name} expects tables for "
                f"{list(self.class_args)}, got {list(matched)}"
            )
        input_nodes = [matched[n]._node for n in self.class_args]
        out_tables: dict[str, Table] = {}
        for name, ca in self.class_args.items():
            node = RowTransformerNode(input_nodes, self, ca)
            dtypes = {
                a.output_name: a.dtype()
                for a in ca._attributes.values()
                if a.is_output
            }
            out_tables[name] = Table._from_node(
                node, dtypes, matched[name]._universe
            )
        return _Result(out_tables)


def transformer(cls: type) -> Transformer:
    """Class decorator (reference: ``@pw.transformer``): turns a class of
    inner ``ClassArg`` tables into a callable transformer."""
    class_args = {
        name: value
        for name, value in vars(cls).items()
        if isinstance(value, type) and issubclass(value, ClassArg)
    }
    if not class_args:
        raise TypeError(
            f"@transformer class {cls.__name__} declares no ClassArg tables"
        )
    return Transformer(cls.__name__, class_args)
