"""Error log exposed as a table (reference: global_error_log,
python/pathway/internals/errors.py).

The log node DRAINS newly-recorded errors every tick (and at the final
tick), so errors produced during the same run appear in the table —
matching the reference, where the error log is itself a streaming table
fed by the engine. Scoped logs (pw.local_error_log) see only entries
tagged with their scope id; the global log sees untagged entries.
"""

from __future__ import annotations

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.engine.nodes import Node, NodeExec
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.api import sequential_key
from pathway_tpu.internals.errors import peek_errors
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe

_COLS = ["message", "operator_id", "trace"]


class ErrorLogNode(Node):
    def __init__(self, scope: int | None):
        super().__init__([], _COLS)
        self.scope = scope

    def make_exec(self):
        return ErrorLogExec(self)


class ErrorLogExec(NodeExec):
    def __init__(self, node: ErrorLogNode):
        super().__init__(node)
        self._pos = 0  # position in the FULL log (all scopes)

    def _drain(self) -> list[DiffBatch]:
        errs = peek_errors()
        rows = []
        for i in range(self._pos, len(errs)):
            e = errs[i]
            if e.get("log_id") != self.node.scope:
                continue
            rows.append(
                (
                    int(sequential_key(i)),
                    1,
                    (e["message"], e["operator_id"], e["trace"]),
                )
            )
        self._pos = len(errs)
        if not rows:
            return []
        return [DiffBatch.from_rows(rows, _COLS)]

    def process(self, t, inputs):
        return self._drain()

    def on_end(self):
        return self._drain()

    def state_dict(self):
        return None  # the log is process-transient, never snapshotted


def error_log_table(scope: int | None = None) -> Table:
    node = ErrorLogNode(scope)
    return Table._from_node(
        node,
        {"message": dt.STR, "operator_id": dt.STR, "trace": dt.STR},
        Universe(),
    )
