"""Error log exposed as a table (reference: global_error_log,
python/pathway/internals/errors.py)."""

from __future__ import annotations

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.engine.nodes import InputNode
from pathway_tpu.engine.runtime import StaticSource
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.api import sequential_key
from pathway_tpu.internals.errors import peek_errors
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe

_COLS = ["message", "operator_id", "trace"]


class _ErrorLogSource(StaticSource):
    def __init__(self):
        super().__init__(_COLS)

    def events(self):
        errs = peek_errors()
        rows = [
            (int(sequential_key(i)), 1, (e["message"], e["operator_id"], e["trace"]))
            for i, e in enumerate(errs)
        ]
        if rows:
            yield 0, DiffBatch.from_rows(rows, _COLS)


def error_log_table() -> Table:
    node = InputNode(_ErrorLogSource(), _COLS)
    return Table._from_node(
        node,
        {"message": dt.STR, "operator_id": dt.STR, "trace": dt.STR},
        Universe(),
    )
