"""Prometheus/OpenMetrics monitoring endpoint.

TPU-native equivalent of the reference's per-process metrics server
(reference: src/engine/http_server.rs:21-90 — OpenMetrics endpoint at port
20000 + process_id with input/output latency gauges). Serves the Runtime's
prober counters (RuntimeStats) in Prometheus text exposition format at
`/metrics` (and `/status` as JSON).
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

BASE_PORT = 20000


def _render_metrics(runtime) -> str:
    import time as _time

    from pathway_tpu.internals.telemetry import process_gauges

    s = runtime.stats
    gauges = process_gauges()
    # frontier lag vs wall clock — the reference's input/output latency
    # gauges (http_server.rs:25-90). Only meaningful when logical times
    # ARE wall-clock ms (streaming mode); static runs with explicit small
    # event times would otherwise report a multi-decade "lag"
    now_ms = _time.time() * 1000.0
    week_ms = 7 * 86400 * 1000.0
    if 0 < s.current_time <= now_ms and now_ms - s.current_time < week_ms:
        lag_ms = now_ms - s.current_time
    else:
        lag_ms = 0.0
    lines = [
        "# TYPE pathway_ticks_total counter",
        f"pathway_ticks_total {s.ticks}",
        "# TYPE pathway_logical_time gauge",
        f"pathway_logical_time {s.current_time}",
        "# TYPE pathway_last_tick_seconds gauge",
        f"pathway_last_tick_seconds {s.last_tick_ns / 1e9}",
        "# TYPE pathway_frontier_lag_ms gauge",
        f"pathway_frontier_lag_ms {lag_ms}",
        "# TYPE pathway_process_cpu_seconds_total counter",
        f"pathway_process_cpu_seconds_total {gauges['process_cpu_seconds_total']}",
        "# TYPE pathway_process_memory_rss_bytes gauge",
        f"pathway_process_memory_rss_bytes {gauges['process_memory_rss_bytes']}",
        "# TYPE pathway_input_rows_total counter",
        "# TYPE pathway_output_rows_total counter",
        "# TYPE pathway_operator_rows_total counter",
        "# TYPE pathway_operator_seconds_total counter",
    ]
    names = {n.id: f"{n.name}_{n.id}" for n in runtime.order}
    for nid, v in sorted(s.rows_in.items()):
        lines.append(f'pathway_input_rows_total{{node="{names.get(nid, nid)}"}} {v}')
    for nid, v in sorted(s.rows_out.items()):
        lines.append(f'pathway_output_rows_total{{node="{names.get(nid, nid)}"}} {v}')
    for nid, v in sorted(s.node_rows.items()):
        lines.append(
            f'pathway_operator_rows_total{{node="{names.get(nid, nid)}"}} {v}'
        )
    for nid, v in sorted(s.node_ns.items()):
        lines.append(
            f'pathway_operator_seconds_total{{node="{names.get(nid, nid)}"}} {v / 1e9}'
        )
    return "\n".join(lines) + "\n"


def start_http_server(runtime, port: int | None = None) -> ThreadingHTTPServer:
    """Start the metrics endpoint in a daemon thread; returns the server."""
    if port is None:
        process_id = int(os.environ.get("PATHWAY_PROCESS_ID", "0") or 0)
        port = BASE_PORT + process_id

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802
            if self.path.rstrip("/") in ("", "/metrics"):
                body = _render_metrics(runtime).encode()
                ctype = "text/plain; version=0.0.4"
            elif self.path.rstrip("/") == "/status":
                body = json.dumps(runtime.stats.snapshot()).encode()
                ctype = "application/json"
            else:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):
            pass

    server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server
