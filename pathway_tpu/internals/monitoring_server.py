"""Prometheus/OpenMetrics monitoring endpoint + debug surfaces.

TPU-native equivalent of the reference's per-process metrics server
(reference: src/engine/http_server.rs:21-90 — OpenMetrics endpoint at port
20000 + process_id with input/output latency gauges), rebuilt on the
Flight Recorder registry (pathway_tpu/observability): ``/metrics`` renders
the process-wide MetricsRegistry (runtime counters are promoted onto it
at scrape time), and the debug endpoints answer the questions the
BENCH_r05 hung-probe investigation couldn't: ``/debug/threads``
(all-thread stack dump), ``/debug/graph`` (per-node rows/ns/backlog as
JSON), ``/debug/profile?seconds=N`` (on-demand jax profiler trace),
``/debug/trace?seconds=N`` (the Trace Weaver span ring as Chrome
trace-event JSON, loadable in Perfetto), ``/debug/signals`` (Fleet Lens
SLO signal rings + burn rates; ``?series=N`` includes trailing points),
``/debug/events`` (the incident journal), and ``/debug/tick`` (Tick
Scope: per-operator tick anatomy, critical path, memory-ledger top
owners, roofline MFU; ``?ticks=N&deep=1&trace=1``). Arming the server also
arms the per-process signal sampler (disable with ``PATHWAY_SIGNALS=0``)
and installs the crash hooks that write the postmortem bundle.

Bind host comes from PATHWAY_MONITORING_HOST (default 127.0.0.1 — set
0.0.0.0 for multi-host scrape); a taken port falls back to an ephemeral
one with a logged warning instead of crashing the run.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from pathway_tpu.observability import (
    REGISTRY,
    ProfilerUnavailable,
    graph_table,
    install_jax_metrics,
    take_profile,
    thread_stack_dump,
)
from pathway_tpu.observability.registry import MetricsRegistry

BASE_PORT = 20000

logger = logging.getLogger("pathway_tpu")

# one server per requested (host, port) per process: a second monitored
# run re-attaches its runtime to the existing server instead of leaking
# a new thread per run and falling back to an ephemeral port — which
# would leave the canonical scrape port serving the finished run's
# frozen stats forever
_servers: dict[tuple[str, int], ThreadingHTTPServer] = {}
_servers_lock = threading.Lock()


def _monitoring_host() -> str:
    return os.environ.get("PATHWAY_MONITORING_HOST", "127.0.0.1")


class _RuntimeBridge:
    """Promotes RuntimeStats raw dicts onto the registry at scrape time
    (pull-based: the tick loop never pays for metric formatting). Node ids
    are process-unique, so per-node series from earlier runtimes stay
    monotone; whole-runtime counters (ticks) roll retired runtimes into a
    base so the process counter never goes backward."""

    def __init__(self, registry: MetricsRegistry):
        self.registry = registry
        self._lock = threading.Lock()
        self._runtime: weakref.ref | None = None
        self._names: dict[int, str] = {}
        self._ticks_base = 0
        self._last_ticks = 0
        g, c = registry.gauge, registry.counter
        self.m_ticks = c("pathway_ticks_total", "engine ticks processed")
        self.m_logical_time = g(
            "pathway_logical_time", "current logical time (ms clock)"
        )
        self.m_last_tick = g(
            "pathway_last_tick_seconds", "duration of the last tick"
        )
        self.m_frontier_lag = g(
            "pathway_frontier_lag_ms",
            "wall clock minus logical frontier (streaming mode only)",
        )
        self.m_cpu = c(
            "pathway_process_cpu_seconds_total", "process CPU time"
        )
        self.m_rss = g(
            "pathway_process_memory_rss_bytes", "resident set size"
        )
        self.m_rows_in = c(
            "pathway_input_rows_total", "rows ingested per input node",
            ("node",),
        )
        self.m_rows_out = c(
            "pathway_output_rows_total", "rows emitted per output node",
            ("node",),
        )
        self.m_node_rows = c(
            "pathway_operator_rows_total", "rows produced per node",
            ("node",),
        )
        self.m_node_seconds = c(
            "pathway_operator_seconds_total",
            "cumulative processing time per node",
            ("node",),
        )
        registry.register_collector(self.collect)

    def attach(self, runtime) -> None:
        with self._lock:
            old = self._runtime() if self._runtime is not None else None
            if old is runtime:
                return
            if old is not None:
                self._ticks_base += old.stats.ticks
            elif self._runtime is not None:
                # previous runtime was GC'd: fold in its last-seen count
                self._ticks_base += self._last_ticks
            self._last_ticks = 0
            self._runtime = weakref.ref(runtime)
            self._names = {
                n.id: f"{n.name}_{n.id}" for n in runtime.order
            }

    def collect(self) -> None:
        import time as _time

        from pathway_tpu.internals.telemetry import process_gauges

        gauges = process_gauges()
        self.m_cpu._unlabeled().set_total(
            gauges["process_cpu_seconds_total"]
        )
        self.m_rss.set(gauges["process_memory_rss_bytes"])
        with self._lock:
            runtime = self._runtime() if self._runtime is not None else None
            names = self._names
            base = self._ticks_base
        if runtime is None:
            self.m_ticks._unlabeled().set_total(base + self._last_ticks)
            return
        s = runtime.stats
        with self._lock:
            self._last_ticks = s.ticks
        self.m_ticks._unlabeled().set_total(base + s.ticks)
        self.m_logical_time.set(s.current_time)
        self.m_last_tick.set(s.last_tick_ns / 1e9)
        # frontier lag vs wall clock — the reference's input/output latency
        # gauges (http_server.rs:25-90). Only meaningful when logical times
        # ARE wall-clock ms (streaming mode); static runs with explicit
        # small event times would otherwise report a multi-decade "lag"
        now_ms = _time.time() * 1000.0
        week_ms = 7 * 86400 * 1000.0
        if 0 < s.current_time <= now_ms and now_ms - s.current_time < week_ms:
            self.m_frontier_lag.set(now_ms - s.current_time)
        else:
            self.m_frontier_lag.set(0.0)
        for metric, data in (
            (self.m_rows_in, s.rows_in),
            (self.m_rows_out, s.rows_out),
            (self.m_node_rows, s.node_rows),
        ):
            for nid, v in data.items():
                metric.labels(names.get(nid, str(nid))).set_total(v)
        for nid, v in s.node_ns.items():
            self.m_node_seconds.labels(
                names.get(nid, str(nid))
            ).set_total(v / 1e9)


_bridge: _RuntimeBridge | None = None
_bridge_lock = threading.Lock()


def _ensure_bridge() -> _RuntimeBridge:
    global _bridge
    with _bridge_lock:
        if _bridge is None:
            _bridge = _RuntimeBridge(REGISTRY)
        return _bridge


def _render_metrics(runtime) -> str:
    """Render the registry with `runtime`'s stats promoted onto it
    (kept as the model for tests and the TUI; the HTTP handler calls the
    same path)."""
    bridge = _ensure_bridge()
    if runtime is not None:
        bridge.attach(runtime)
    install_jax_metrics(REGISTRY)
    return REGISTRY.render()


def start_http_server(
    runtime=None, port: int | None = None, host: str | None = None
) -> ThreadingHTTPServer:
    """Start the metrics/debug endpoint in a daemon thread; returns the
    server (``server.server_address`` carries the actual bound port).
    ``runtime=None`` serves registry metrics and debug surfaces only —
    bench probes use that standalone mode."""
    if port is None:
        process_id = int(os.environ.get("PATHWAY_PROCESS_ID", "0") or 0)
        port = BASE_PORT + process_id
    if host is None:
        host = _monitoring_host()
    bridge = _ensure_bridge()
    if runtime is not None:
        bridge.attach(runtime)
    install_jax_metrics(REGISTRY)
    # Fleet Lens: a monitored process samples its own SLO signals and
    # keeps an incident journal with crash hooks — both opt-out
    # (PATHWAY_SIGNALS=0) and cheap when idle
    from pathway_tpu.observability.journal import install_crash_hooks
    from pathway_tpu.observability.signals import arm_sampler

    arm_sampler()
    install_crash_hooks()
    with _servers_lock:
        # port 0 asks for a FRESH ephemeral server (multi-member fleet
        # drivers start several in one process) — only canonical ports
        # participate in the reuse registry
        existing = _servers.get((host, port)) if port else None
        if existing is not None and existing.socket.fileno() == -1:
            # closed without going through the shutdown wrapper
            del _servers[(host, port)]
            existing = None
    if existing is not None:
        existing._pw_set_runtime(runtime)  # type: ignore[attr-defined]
        if runtime is not None:
            runtime.http_server = existing
        return existing

    # the handler resolves the runtime per request through this weak
    # cell: serving must not pin a finished run's whole graph in memory
    # (the bridge holds runtimes weakly for the same reason), and the
    # next run re-points the cell at its runtime
    cell: dict = {"ref": None}

    def set_runtime(rt) -> None:
        cell["ref"] = weakref.ref(rt) if rt is not None else None

    def current_runtime():
        ref = cell["ref"]
        return ref() if ref is not None else None

    set_runtime(runtime)

    class Handler(BaseHTTPRequestHandler):
        def _reply(
            self, code: int, body: bytes, ctype: str = "text/plain"
        ) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802
            runtime = current_runtime()
            parsed = urlparse(self.path)
            route = parsed.path.rstrip("/")
            try:
                if route in ("", "/metrics"):
                    self._reply(
                        200,
                        _render_metrics(runtime).encode(),
                        "text/plain; version=0.0.4",
                    )
                elif route == "/status":
                    snap = (
                        runtime.stats.snapshot()
                        if runtime is not None
                        else {}
                    )
                    self._reply(
                        200, json.dumps(snap).encode(), "application/json"
                    )
                elif route == "/debug/threads":
                    self._reply(200, thread_stack_dump().encode())
                elif route == "/debug/graph":
                    self._reply(
                        200,
                        json.dumps(graph_table(runtime)).encode(),
                        "application/json",
                    )
                elif route == "/debug/profile":
                    self._profile(parse_qs(parsed.query))
                elif route == "/debug/trace":
                    self._trace(parse_qs(parsed.query))
                elif route == "/debug/signals":
                    self._signals(parse_qs(parsed.query))
                elif route == "/debug/events":
                    self._events(parse_qs(parsed.query))
                elif route == "/debug/tick":
                    self._tick(runtime, parse_qs(parsed.query))
                elif route == "/debug/autoscale":
                    # Flux Pilot: the armed controller's live status
                    # (ranks, cooldown, last decision, actuation-cost
                    # EWMA) — 404s when no controller is armed so
                    # probes can distinguish "absent" from "idle"
                    from pathway_tpu.autoscale import get_controller

                    ctrl = get_controller()
                    if ctrl is None:
                        self._reply(404, b"no autoscale controller armed")
                    else:
                        self._reply(
                            200,
                            json.dumps(ctrl.status()).encode(),
                            "application/json",
                        )
                elif route in (
                    "/fleet/metrics",
                    "/fleet/events",
                    "/fleet/trace",
                ):
                    self._fleet(route, parse_qs(parsed.query))
                else:
                    self._reply(404, b"not found")
            except BrokenPipeError:
                pass
            except Exception as exc:  # a broken page must not kill serving
                try:
                    self._reply(
                        500, f"internal error: {exc}".encode()
                    )
                except Exception:
                    pass

        def _trace(self, query: dict) -> None:
            """Trace Weaver export: the span ring as Chrome trace-event
            JSON — save the body to a file and load it in Perfetto
            (ui.perfetto.dev) or chrome://tracing. ``seconds=N`` keeps
            only spans that ended within the trailing window."""
            from pathway_tpu.observability.tracing import get_tracer

            raw = query.get("seconds", ["0"])[0]
            try:
                seconds = float(raw)
            except ValueError:
                self._reply(400, b"seconds must be a number")
                return
            if seconds < 0:
                self._reply(400, b"seconds must be non-negative")
                return
            doc = get_tracer().chrome_trace(
                seconds=seconds if seconds > 0 else None
            )
            self._reply(
                200, json.dumps(doc).encode(), "application/json"
            )

        def _tick(self, runtime, query: dict) -> None:
            """Tick Scope (observability/tickscope.py): last-tick
            anatomy (per-operator wall/rows, compiled-vs-interpreted,
            critical path), the memory ledger's top owners, roofline
            MFU per kernel family, and per-channel wire bytes.
            ``ticks=N`` adds a trailing-N operator rollup; ``deep=1``
            includes monolith-pickle sizes (costs a pickle per
            monolithic exec); ``trace=1`` returns the ring as Chrome
            trace-event JSON instead (one Perfetto track per exec)."""
            from pathway_tpu.observability import tickscope

            scope = getattr(runtime, "_tickscope", None)
            if scope is None:
                scope = tickscope.recorder()
            try:
                ticks = int(query.get("ticks", ["1"])[0])
            except ValueError:
                self._reply(400, b"ticks must be an integer")
                return
            deep = query.get("deep", ["0"])[0] not in ("0", "")
            if query.get("trace", ["0"])[0] not in ("0", ""):
                doc = (
                    scope.chrome_trace(n_ticks=ticks if ticks > 0 else None)
                    if scope is not None
                    else {"traceEvents": []}
                )
                self._reply(
                    200, json.dumps(doc).encode(), "application/json"
                )
                return
            if scope is None:
                doc = {
                    "enabled": tickscope.enabled_from_env(),
                    "ticks_recorded": 0,
                    "memory": tickscope.memory_snapshot(deep=deep),
                    "roofline": tickscope.roofline().snapshot(),
                    "wire": tickscope.wire_snapshot(),
                }
            else:
                doc = scope.snapshot(ticks=max(ticks, 1), deep=deep)
            self._reply(200, json.dumps(doc).encode(), "application/json")

        def _signals(self, query: dict) -> None:
            """Fleet Lens SLO signal rings (observability/signals.py):
            the feed the autoscaler consumes. ``series=N`` includes the
            trailing N ring points per signal."""
            from pathway_tpu.observability.signals import get_sampler

            sampler = get_sampler()
            if sampler is None:
                self._reply(
                    200,
                    json.dumps(
                        {"enabled": False, "signals": {}, "slo": {}}
                    ).encode(),
                    "application/json",
                )
                return
            raw = query.get("series", ["0"])[0]
            try:
                series_points = int(raw)
            except ValueError:
                self._reply(400, b"series must be an integer")
                return
            snap = sampler.snapshot(series_points=series_points)
            snap["enabled"] = True
            self._reply(200, json.dumps(snap).encode(), "application/json")

        def _events(self, query: dict) -> None:
            """Incident journal (observability/journal.py). ``kind=a,b``
            filters; ``n=N`` caps at the trailing N events."""
            from pathway_tpu.observability.journal import journal

            j = journal()
            kinds_raw = query.get("kind", [""])[0]
            kinds = (
                [k for k in kinds_raw.split(",") if k] or None
            )
            events = j.events(kinds=kinds)
            raw = query.get("n", ["0"])[0]
            try:
                n = int(raw)
            except ValueError:
                self._reply(400, b"n must be an integer")
                return
            if n > 0:
                events = events[-n:]
            self._reply(
                200,
                json.dumps(
                    {"member": j.member, "events": events}
                ).encode(),
                "application/json",
            )

        def _fleet(self, route: str, query: dict) -> None:
            """Fleet Lens federation over PATHWAY_FLEET_MEMBERS (the
            group supervisor stamps the rank -> monitoring-port map into
            every rank's env): one member-labeled exposition, one merged
            incident timeline, one stitched cross-member trace."""
            from pathway_tpu.observability.fleet import (
                federate_events,
                federate_metrics,
                members_from_env,
                stitch_traces,
            )
            from pathway_tpu.observability.journal import journal

            members = members_from_env()
            me = journal().member
            # this process serves its own view inline — a member entry
            # naming OUR port would double-count us in the merge
            port = self.server.server_address[1]

            def _is_self(u: str) -> bool:
                p = urlparse(u)
                return p.port == port and p.hostname in (
                    "127.0.0.1", "localhost", host,
                )

            members = [(n, u) for n, u in members if not _is_self(u)]
            if route == "/fleet/metrics":
                # fetch errors are already encoded in the body as
                # pathway_fleet_member_up{member=...} 0
                text, _errors = federate_metrics(
                    members, local=(me, _render_metrics(current_runtime()))
                )
                self._reply(
                    200, text.encode(), "text/plain; version=0.0.4"
                )
            elif route == "/fleet/events":
                merged = federate_events(
                    members, local=journal().events()
                )
                self._reply(
                    200, json.dumps(merged).encode(), "application/json"
                )
            else:
                from pathway_tpu.observability.tracing import get_tracer

                trace_id = query.get("trace_id", [""])[0] or None
                doc = stitch_traces(
                    members,
                    trace_id=trace_id,
                    local=(me, get_tracer().chrome_trace()),
                )
                self._reply(
                    200, json.dumps(doc).encode(), "application/json"
                )

        def _profile(self, query: dict) -> None:
            try:
                seconds = float(query.get("seconds", ["1.0"])[0])
            except ValueError:
                self._reply(400, b"seconds must be a number")
                return
            try:
                trace_dir = take_profile(seconds)
            except ProfilerUnavailable as exc:
                self._reply(501, str(exc).encode())
                return
            except ValueError as exc:
                self._reply(400, str(exc).encode())
                return
            except RuntimeError as exc:
                self._reply(409, str(exc).encode())
                return
            self._reply(
                200,
                json.dumps(
                    {"trace_dir": trace_dir, "seconds": seconds}
                ).encode(),
                "application/json",
            )

        def log_message(self, *args):
            pass

    try:
        server = ThreadingHTTPServer((host, port), Handler)
    except OSError as exc:
        # the requested port is taken (common when several runs share a
        # box): fall back to an ephemeral port instead of crashing the run
        server = ThreadingHTTPServer((host, 0), Handler)
        logger.warning(
            "monitoring port %s:%d unavailable (%s); serving metrics on "
            "ephemeral port %d instead",
            host, port, exc, server.server_address[1],
        )
    server._pw_set_runtime = set_runtime  # type: ignore[attr-defined]
    real_shutdown = server.shutdown
    # canonical asks key by the REQUESTED port (the next run asking for
    # that port reuses this server even when a foreign process forced
    # the ephemeral fallback); a requested port of 0 keys by the BOUND
    # port instead, so it stays visible to the doctor's armed check but
    # can never be handed to a second port-0 caller
    reg_key = (host, port or server.server_address[1])

    def shutdown_and_deregister() -> None:
        with _servers_lock:
            if _servers.get(reg_key) is server:
                del _servers[reg_key]
        real_shutdown()
        # shutdown() only stops serve_forever; the listening socket
        # would stay bound and its backlog would swallow scrapes of the
        # canonical port without ever replying
        server.server_close()

    server.shutdown = shutdown_and_deregister  # type: ignore[method-assign]
    with _servers_lock:
        _servers[reg_key] = server
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    if runtime is not None:
        runtime.http_server = server
    return server
