"""Runtime configuration from env vars
(reference: python/pathway/internals/config.py:58-97 +
src/engine/dataflow/config.rs:88-121)."""

from __future__ import annotations

import os
from dataclasses import dataclass, field


def _env_bool(name: str, default: bool = False) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.lower() in ("1", "true", "yes", "on")


@dataclass
class PathwayConfig:
    ignore_asserts: bool = field(
        default_factory=lambda: _env_bool("PATHWAY_IGNORE_ASSERTS")
    )
    runtime_typechecking: bool = field(
        default_factory=lambda: _env_bool("PATHWAY_RUNTIME_TYPECHECKING")
    )
    terminate_on_error: bool = field(
        default_factory=lambda: _env_bool("PATHWAY_TERMINATE_ON_ERROR", True)
    )
    monitoring_server: str | None = field(
        default_factory=lambda: os.environ.get("PATHWAY_MONITORING_SERVER")
    )
    replay_storage: str | None = field(
        default_factory=lambda: os.environ.get("PATHWAY_REPLAY_STORAGE")
    )
    snapshot_access: str | None = field(
        default_factory=lambda: os.environ.get("PATHWAY_SNAPSHOT_ACCESS")
    )
    persistence_mode: str | None = field(
        default_factory=lambda: os.environ.get("PATHWAY_PERSISTENCE_MODE")
    )
    license_key: str | None = field(
        default_factory=lambda: os.environ.get("PATHWAY_LICENSE_KEY")
    )
    process_id: int = field(
        default_factory=lambda: int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
    )
    processes: int = field(
        default_factory=lambda: int(os.environ.get("PATHWAY_PROCESSES", "1"))
    )
    threads: int = field(
        default_factory=lambda: int(os.environ.get("PATHWAY_THREADS", "1"))
    )
    first_port: int = field(
        default_factory=lambda: int(os.environ.get("PATHWAY_FIRST_PORT", "10000"))
    )


pathway_config = PathwayConfig()


def get_pathway_config() -> PathwayConfig:
    return pathway_config


def serving_bulk_chunk() -> int:
    """Max bulk-session rows drained per tick while an interactive
    (Surge Gate) session is hot — bounds how long a serving tick can
    stall behind ingest/backfill. Re-read per run like engine_threads."""
    raw = os.environ.get("PATHWAY_SERVING_BULK_CHUNK", "")
    try:
        return max(1, int(raw)) if raw else 128
    except ValueError:
        return 128


def engine_threads() -> int:
    """Worker-thread count at RUN start. The reference re-reads the env
    per run (Config::from_env, src/engine/dataflow/config.rs:88), unlike
    the import-time PathwayConfig snapshot; the env wins when set."""
    raw = os.environ.get("PATHWAY_THREADS", "")
    try:
        return max(1, int(raw)) if raw else max(1, pathway_config.threads)
    except ValueError:
        return max(1, pathway_config.threads)
