from pathway_tpu.internals import dtype
from pathway_tpu.internals.api import (
    PathwayType,
    PersistenceMode,
    Pointer,
    PyObjectWrapper,
    wrap_py_object,
)
from pathway_tpu.internals.common import (
    apply,
    apply_async,
    apply_with_type,
    assert_table_has_schema,
    cast,
    coalesce,
    declare_type,
    fill_error,
    if_else,
    make_tuple,
    require,
    unwrap,
)
from pathway_tpu.internals.datetime_types import DateTimeNaive, DateTimeUtc, Duration
from pathway_tpu.internals.errors import global_error_log, local_error_log
from pathway_tpu.internals.expression import ColumnExpression, ColumnReference
from pathway_tpu.internals.groupbys import GroupedJoinResult, GroupedTable
from pathway_tpu.internals.joins import JoinMode, JoinResult
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.run import MonitoringLevel, run, run_all
from pathway_tpu.internals.schema import (
    Schema,
    SchemaProperties,
    assert_table_has_schema,  # noqa: F811
    column_definition,
    schema_builder,
    schema_from_csv,
    schema_from_dict,
    schema_from_types,
)
from pathway_tpu.internals.table import (
    Joinable,
    Table,
    TableLike,
    groupby,
    join,
    join_inner,
    join_left,
    join_outer,
    join_right,
)
from pathway_tpu.internals.thisclass import left, right, this
from pathway_tpu.internals.udfs import UDF, udf

__version__ = "0.1.0"
