"""DocumentStore — VectorStoreServer generalized over any retriever factory
(reference: xpacks/llm/document_store.py:32)."""

from __future__ import annotations

from typing import Any, Callable, Iterable, Sequence

import pathway_tpu as pw
from pathway_tpu.internals.common import apply_with_type
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.thisclass import right, this
from pathway_tpu.stdlib.indexing.colnames import _SCORE
from pathway_tpu.stdlib.indexing.retrievers import AbstractRetrieverFactory
from pathway_tpu.xpacks.llm.vector_store import VectorStoreServer, _coerce_doc_tuple


class DocumentStore(VectorStoreServer):
    """Indexing pipeline + queries over an arbitrary retriever factory."""

    def __init__(
        self,
        docs: Table | Iterable[Table],
        retriever_factory: AbstractRetrieverFactory,
        parser: Callable | None = None,
        splitter: Callable | None = None,
        doc_post_processors: Sequence[Callable] | None = None,
    ):
        self.retriever_factory = retriever_factory
        if isinstance(docs, Table):
            docs = [docs]
        # VectorStoreServer.__init__ builds the graph; embedder lives inside
        # the retriever factory for DocumentStore
        self.docs = list(docs)
        self.parser = parser
        self.splitter = splitter
        self.doc_post_processors = list(doc_post_processors or [])
        self.embedding_dimension = None
        self._index_params = {}
        self._graph = self._build_graph()

    def _build_graph(self) -> dict:
        import pathway_tpu.xpacks.llm.vector_store as vs

        # reuse the parse/post-proc/split pipeline, then index raw text via
        # the retriever factory (which applies its own embedder if any)
        graph = {}
        self_embedder_saved = getattr(self, "embedder", None)

        docs_tables = self._clean_tables(self.docs)
        docs = docs_tables[0]
        if len(docs_tables) > 1:
            docs = docs.concat_reindex(*docs_tables[1:])

        parser = self.parser
        if parser is None:
            from pathway_tpu.xpacks.llm.parsers import Utf8Parser

            parser = Utf8Parser()

        def parse_doc(data: Any, metadata: Any) -> list:
            raw = parser.func(data) if hasattr(parser, "func") else parser(data)
            base_meta = (
                dict(metadata.value or {})
                if isinstance(metadata, Json)
                else dict(metadata or {})
            )
            return [
                Json({"text": t, "metadata": {**base_meta, **m}})
                for t, m in (_coerce_doc_tuple(e) for e in raw)
            ]

        parsed = docs.select(
            docs_list=apply_with_type(parse_doc, list, docs.data, docs._metadata)
        ).flatten(this.docs_list)
        parsed = parsed.select(data_json=this.docs_list)

        for processor in self.doc_post_processors:

            def post_proc(data_json: Json, _proc=processor) -> Json:
                d = data_json.value
                text, meta = _proc(d["text"], d["metadata"])
                return Json({"text": text, "metadata": meta})

            parsed = parsed.select(
                data_json=apply_with_type(post_proc, Json, this.data_json)
            )

        splitter = self.splitter
        if splitter is None:
            from pathway_tpu.xpacks.llm.splitters import NullSplitter

            splitter = NullSplitter()

        def split_doc(data_json: Json) -> list:
            d = data_json.value
            fn = splitter.func if hasattr(splitter, "func") else splitter
            return [
                Json({"text": t, "metadata": {**d["metadata"], **m}})
                for t, m in (_coerce_doc_tuple(e) for e in fn(d["text"]))
            ]

        chunked = parsed.select(
            chunks=apply_with_type(split_doc, list, this.data_json)
        ).flatten(this.chunks)
        chunked_docs = chunked.select(
            text=apply_with_type(lambda j: j.value["text"], str, this.chunks),
            metadata=apply_with_type(
                lambda j: Json(j.value["metadata"]), Json, this.chunks
            ),
        )
        chunked_docs = chunked_docs.filter(chunked_docs.text.str.len() > 0)

        index = self.retriever_factory.build_index(
            chunked_docs.text,
            chunked_docs,
            metadata_column=chunked_docs.metadata,
        )
        return {
            "docs": docs,
            "chunked_docs": chunked_docs,
            "embedded": chunked_docs,
            "index": index,
        }

    def retrieve_query(self, retrieval_queries: Table) -> Table:
        queries = self.merge_filters(retrieval_queries)
        jr = self.index.query_as_of_now(
            queries.query,
            number_of_matches=queries.k,
            metadata_filter=queries.metadata_filter,
        )
        raw = jr.select(
            texts=right["text"],
            metas=right["metadata"],
            scores=right[_SCORE],
        )

        def fmt(texts, metas, scores) -> Json:
            out = []
            if texts is not None:
                for t, m, s in zip(texts, metas, scores):
                    out.append(
                        {
                            "text": t,
                            "metadata": m.value if isinstance(m, Json) else m,
                            "dist": -float(s),
                            "score": float(s),
                        }
                    )
            return Json(out)

        return raw.select(
            result=apply_with_type(fmt, Json, raw.texts, raw.metas, raw.scores)
        )

    def parsed_documents_query(self, parse_docs_queries: Table) -> Table:
        parsed = self._graph["chunked_docs"]
        import pathway_tpu.reducers as reducers

        collected = parsed.reduce(
            texts=reducers.tuple(parsed.text),
            metas=reducers.tuple(parsed.metadata),
        )
        from pathway_tpu.internals.common import if_else

        joined = parse_docs_queries.join_left(
            collected.with_columns(_one=1),
            if_else(parse_docs_queries.id == parse_docs_queries.id, 1, 1)
            == right["_one"],
            id=parse_docs_queries.id,
        )

        def fmt(texts, metas) -> Json:
            out = []
            for t, m in zip(texts or (), metas or ()):
                out.append(
                    {"text": t, "metadata": m.value if isinstance(m, Json) else m}
                )
            return Json(out)

        return joined.select(
            result=apply_with_type(fmt, Json, right["texts"], right["metas"])
        )
