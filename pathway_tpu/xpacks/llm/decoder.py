"""Tiny bundled decoder for the generation serving plane.

The RAG loop the xpack serves (retrieve -> generate) needs a decoder
the repo can run WITHOUT downloading weights: a small pre-LN
transformer with deterministic random-init parameters (seeded, so the
writer, every replica, and a restarted process all build bit-identical
weights — the ``text_vector`` trick applied to a language model) and a
byte-level tokenizer (no vocab file).  The module is layout-compatible
with real checkpoints: ``init_params`` builds the same pytree a weight
loader would fill in, so swapping in trained weights is a loader, not a
rewrite.

The decode step is ONE jitted function per (batch-bucket, kernel):
embed -> N pre-LN transformer blocks whose attention reads the paged KV
pools through :mod:`pathway_tpu.ops.paged_attention` -> final norm ->
tied-embedding logits.  It also WRITES the current token's K/V into the
pools (functional ``.at[].set`` — the pools are donated so XLA updates
in place), which makes prefill just "decode the prompt token by token
and ignore the logits": one code path, so a kill/restart that restores
the pools mid-sequence provably continues the exact computation.

Sampling is host-side numpy (batch sizes are small at decode): greedy
at ``temperature == 0``, else top-k softmax sampling with a
per-(seed, step) PRNG so a restored run re-draws identical tokens.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from pathway_tpu.ops.paged_attention import (
    lane_pad,
    paged_attention,
    paged_attention_ref,
)

BOS = 256
EOS = 257
PAD = 258


@dataclass(frozen=True)
class DecoderConfig:
    """Static decoder shape — hashable, so it rides jit as a static arg.

    ``head_dim_padded`` (the KV-pool lane width) pads ``head_dim`` up to
    the TPU 128-lane boundary per the paged-attention layout rules; the
    padded tail is zero in q/k/v so the math is unchanged."""

    vocab_size: int = 259  # 256 bytes + BOS/EOS/PAD
    dim: int = 128
    n_layers: int = 2
    n_heads: int = 4
    head_dim: int = 32
    ffn_dim: int = 256
    max_len: int = 512
    page_size: int = 16

    @property
    def head_dim_padded(self) -> int:
        return lane_pad(self.head_dim)

    @property
    def max_pages(self) -> int:
        return -(-self.max_len // self.page_size)


def init_params(cfg: DecoderConfig, seed: int = 0) -> dict:
    """Deterministic random-init parameter pytree (numpy, f32): the
    same (cfg, seed) always builds bit-identical weights on every
    process — generation replicas need no weight distribution."""
    rng = np.random.default_rng(seed)

    def mat(*shape: int) -> np.ndarray:
        scale = 1.0 / np.sqrt(shape[0])
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    h = cfg.n_heads * cfg.head_dim
    params: dict = {
        "embed": mat(cfg.vocab_size, cfg.dim),
        "pos": (rng.standard_normal((cfg.max_len, cfg.dim)) * 0.02).astype(
            np.float32
        ),
        "lnf_scale": np.ones(cfg.dim, np.float32),
        "lnf_bias": np.zeros(cfg.dim, np.float32),
        "layers": [],
    }
    for _ in range(cfg.n_layers):
        params["layers"].append(
            {
                "ln1_scale": np.ones(cfg.dim, np.float32),
                "ln1_bias": np.zeros(cfg.dim, np.float32),
                "wq": mat(cfg.dim, h),
                "wk": mat(cfg.dim, h),
                "wv": mat(cfg.dim, h),
                "wo": mat(h, cfg.dim),
                "ln2_scale": np.ones(cfg.dim, np.float32),
                "ln2_bias": np.zeros(cfg.dim, np.float32),
                "w1": mat(cfg.dim, cfg.ffn_dim),
                "b1": np.zeros(cfg.ffn_dim, np.float32),
                "w2": mat(cfg.ffn_dim, cfg.dim),
                "b2": np.zeros(cfg.dim, np.float32),
            }
        )
    return params


def empty_pools(
    cfg: DecoderConfig, n_pages: int
) -> tuple[jax.Array, jax.Array]:
    """Zeroed K/V page pools ``[n_layers, n_pages, H, P, Dp]``.  Page 0
    is the sacrificial null page: padded batch slots carry an all-zero
    page table, so their (masked-out) writes land there and never
    clobber a live sequence."""
    shape = (
        cfg.n_layers,
        n_pages,
        cfg.n_heads,
        cfg.page_size,
        cfg.head_dim_padded,
    )
    return jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32)


def _ln(x: jax.Array, scale: jax.Array, bias: jax.Array) -> jax.Array:
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + 1e-6) * scale + bias


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "kernel", "interpret"),
    donate_argnums=(3, 4),
)
def decode_step(
    params: dict,
    tokens: jax.Array,  # [B] int32 token being fed at `positions`
    positions: jax.Array,  # [B] int32 (0-based; 0 for padded slots)
    k_pool: jax.Array,  # [L, n_pages, H, P, Dp] (donated)
    v_pool: jax.Array,  # (donated)
    page_tables: jax.Array,  # [B, max_pages] int32 (all-zero for pads)
    seq_lens: jax.Array,  # [B] int32 valid tokens INCLUDING this one;
    #                         0 marks a padded batch slot
    *,
    cfg: DecoderConfig,
    kernel: str = "ref",
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One decode step over the paged KV cache: write this token's K/V,
    attend over each sequence's cached prefix (ragged), and return
    ``(logits [B, vocab], k_pool, v_pool)``."""
    b = tokens.shape[0]
    hd, dp, p = cfg.head_dim, cfg.head_dim_padded, cfg.page_size
    scale = 1.0 / float(np.sqrt(hd))
    page_ids = jnp.take_along_axis(
        page_tables, (positions // p)[:, None], axis=1
    )[:, 0]  # [B] physical page of the current position
    slots = positions % p
    x = params["embed"][tokens] + params["pos"][positions]
    for li, layer in enumerate(params["layers"]):
        hnorm = _ln(x, layer["ln1_scale"], layer["ln1_bias"])

        def heads(y: jax.Array) -> jax.Array:
            y = y.reshape(b, cfg.n_heads, hd)
            return jnp.pad(y, ((0, 0), (0, 0), (0, dp - hd)))

        q = heads(hnorm @ layer["wq"]) * scale
        k = heads(hnorm @ layer["wk"])
        v = heads(hnorm @ layer["wv"])
        # write this token's K/V into its page slot (advanced indexing
        # over [pages, :, slots] yields [B, H, Dp] — matching k/v)
        k_pool = k_pool.at[li, page_ids, :, slots, :].set(k)
        v_pool = v_pool.at[li, page_ids, :, slots, :].set(v)
        attend = (
            functools.partial(paged_attention, interpret=interpret)
            if kernel == "pallas"
            else paged_attention_ref
        )
        att = attend(
            q, k_pool[li], v_pool[li], page_tables, seq_lens,
            sm_scale=1.0,  # q is pre-scaled
        )
        att = att[:, :, :hd].reshape(b, cfg.n_heads * hd)
        x = x + att @ layer["wo"]
        hnorm = _ln(x, layer["ln2_scale"], layer["ln2_bias"])
        x = x + (
            jax.nn.gelu(hnorm @ layer["w1"] + layer["b1"]) @ layer["w2"]
            + layer["b2"]
        )
    x = _ln(x, params["lnf_scale"], params["lnf_bias"])
    logits = x @ params["embed"].T
    return logits, k_pool, v_pool


def sample_token(
    logits: np.ndarray,  # [vocab] f32 host logits of ONE sequence
    *,
    temperature: float = 0.0,
    top_k: int = 40,
    seed: int = 0,
    step: int = 0,
) -> int:
    """Greedy at temperature 0, else top-k softmax sampling with a
    per-(seed, step) PRNG — a restored run re-draws the same tokens."""
    if temperature <= 0.0:
        return int(np.argmax(logits))
    top_k = max(int(top_k), 1)
    idx = np.argpartition(logits, -top_k)[-top_k:]
    z = logits[idx].astype(np.float64) / float(temperature)
    z -= z.max()
    probs = np.exp(z)
    probs /= probs.sum()
    # mask to a non-negative 63-bit stream id: client-supplied seeds
    # may be negative, and default_rng rejects negative ints
    stream = ((int(seed) << 20) ^ int(step)) & 0x7FFFFFFFFFFFFFFF
    rng = np.random.default_rng(stream)
    return int(rng.choice(idx, p=probs))


# --- byte tokenizer ---------------------------------------------------------


def encode_text(text: str) -> list[int]:
    """BOS + UTF-8 bytes (truncation is the caller's policy)."""
    return [BOS] + list(str(text).encode("utf-8", errors="replace"))


def decode_tokens(tokens: list[int]) -> str:
    return bytes(t for t in tokens if 0 <= t < 256).decode(
        "utf-8", errors="replace"
    )
