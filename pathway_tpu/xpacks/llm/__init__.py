"""pathway_tpu.xpacks.llm — the RAG product layer
(reference: python/pathway/xpacks/llm/, ~8.3k LoC)."""

from pathway_tpu.xpacks.llm import (
    embedders,
    llms,
    parsers,
    prompts,
    rerankers,
    splitters,
)
from pathway_tpu.xpacks.llm.document_store import DocumentStore
from pathway_tpu.xpacks.llm.question_answering import (
    AdaptiveRAGQuestionAnswerer,
    BaseRAGQuestionAnswerer,
    RAGClient,
    answer_with_geometric_rag_strategy,
)
from pathway_tpu.xpacks.llm.servers import (
    BaseRestServer,
    DocumentStoreServer,
    QARestServer,
    QASummaryRestServer,
)
from pathway_tpu.xpacks.llm.vector_store import (
    VectorStoreClient,
    VectorStoreServer,
)

__all__ = [
    "embedders",
    "llms",
    "parsers",
    "prompts",
    "rerankers",
    "splitters",
    "DocumentStore",
    "VectorStoreServer",
    "VectorStoreClient",
    "BaseRAGQuestionAnswerer",
    "AdaptiveRAGQuestionAnswerer",
    "RAGClient",
    "answer_with_geometric_rag_strategy",
    "BaseRestServer",
    "DocumentStoreServer",
    "QARestServer",
    "QASummaryRestServer",
]
