"""REST servers for document stores and QA apps
(reference: xpacks/llm/servers.py:16-193 — BaseRestServer,
DocumentStoreServer:92, QARestServer:140, QASummaryRestServer:193)."""

from __future__ import annotations

import threading
from typing import Any

import pathway_tpu as pw
from pathway_tpu.io.http import PathwayWebserver, rest_connector


class BaseRestServer:
    def __init__(self, host: str, port: int, qos: Any = None, **rest_kwargs):
        self.host = host
        self.port = port
        self.qos = qos  # serving.QoSConfig applied to every route
        self.webserver = PathwayWebserver(host=host, port=port)

    def serve(
        self,
        route: str,
        schema: Any,
        handler,
        documentation: Any = None,
        **kwargs,
    ) -> None:
        queries, writer = rest_connector(
            webserver=self.webserver,
            route=route,
            schema=schema,
            methods=("POST",),
            delete_completed_queries=True,
            documentation=documentation,
            qos=kwargs.pop("qos", self.qos),
        )
        result = handler(queries)
        writer(result.select(query_id=result.id, result=result.result))

    def drain(self, grace_s: float | None = None) -> bool:
        """Graceful overload exit: stop admitting (503 + Retry-After),
        flush in-flight micro-batches, wait for every admitted request's
        response, then shut the webserver down."""
        return self.webserver.drain(grace_s)

    def run(
        self,
        threaded: bool = False,
        with_cache: bool = True,
        cache_backend: Any = None,
        terminate_on_error: bool = True,
        **kwargs,
    ):
        def run_inner():
            pw.run(terminate_on_error=terminate_on_error)

        if threaded:
            t = threading.Thread(target=run_inner, daemon=True)
            t.start()
            return t
        run_inner()


class DocumentStoreServer(BaseRestServer):
    """(reference: servers.py:92)"""

    def __init__(self, host: str, port: int, document_store, **rest_kwargs):
        super().__init__(host, port, **rest_kwargs)
        self.document_store = document_store
        self.serve(
            "/v1/retrieve",
            document_store.RetrieveQuerySchema,
            document_store.retrieve_query,
        )
        self.serve(
            "/v1/statistics",
            document_store.StatisticsQuerySchema,
            document_store.statistics_query,
        )
        self.serve(
            "/v1/inputs",
            document_store.InputsQuerySchema,
            document_store.inputs_query,
        )


class QARestServer(BaseRestServer):
    """(reference: servers.py:140)"""

    def __init__(self, host: str, port: int, rag_question_answerer, **rest_kwargs):
        super().__init__(host, port, **rest_kwargs)
        self.rag = rag_question_answerer
        self.serve(
            "/v1/retrieve",
            self.rag.RetrieveQuerySchema,
            self.rag.retrieve,
        )
        self.serve(
            "/v1/statistics",
            self.rag.StatisticsQuerySchema,
            self.rag.statistics,
        )
        self.serve(
            "/v1/pw_list_documents",
            self.rag.InputsQuerySchema,
            self.rag.list_documents,
        )
        self.serve(
            "/v1/pw_ai_answer",
            self.rag.AnswerQuerySchema,
            self.rag.answer_query,
        )
        self.serve(
            "/v2/answer",
            self.rag.AnswerQuerySchema,
            self.rag.answer_query,
        )


class QASummaryRestServer(QARestServer):
    """(reference: servers.py:193)"""

    def __init__(self, host: str, port: int, rag_question_answerer, **rest_kwargs):
        super().__init__(host, port, rag_question_answerer, **rest_kwargs)
        self.serve(
            "/v1/pw_ai_summary",
            self.rag.SummarizeQuerySchema,
            self.rag.summarize_query,
        )
        self.serve(
            "/v2/summarize",
            self.rag.SummarizeQuerySchema,
            self.rag.summarize_query,
        )
