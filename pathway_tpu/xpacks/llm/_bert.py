"""Faithful BERT/MiniLM encoder in flax + pretrained-weight loading.

The reference's `SentenceTransformerEmbedder` runs torch
sentence-transformers checkpoints (reference:
python/pathway/xpacks/llm/embedders.py:270). This module is the TPU-native
counterpart: an exact post-LN BERT in flax (matching the HF `BertModel`
computation step for step — erf GELU, 1e-12 LayerNorm eps, additive
attention-mask bias, mean-pool + L2 norm per the sentence-transformers
convention) plus a safetensors→flax weight mapper, so MiniLM-class
checkpoints load directly from a local directory / HF cache with no torch
at inference time. Correctness is proven by tests/test_bert_parity.py:
a torch `BertModel` and this module produce matching pooled embeddings for
the same random checkpoint.
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn


class BertLayer(nn.Module):
    dim: int
    heads: int
    intermediate: int
    eps: float = 1e-12
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, bias):
        # bias: [B, 1, 1, L] additive attention mask (0 or large negative)
        b, l, d = x.shape
        hd = self.dim // self.heads

        def heads_split(y):
            return y.reshape(b, l, self.heads, hd).transpose(0, 2, 1, 3)

        q = heads_split(nn.Dense(self.dim, dtype=self.dtype, name="query")(x))
        k = heads_split(nn.Dense(self.dim, dtype=self.dtype, name="key")(x))
        v = heads_split(nn.Dense(self.dim, dtype=self.dtype, name="value")(x))
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(
            jnp.asarray(hd, self.dtype)
        )
        probs = jax.nn.softmax(scores + bias, axis=-1)
        ctx = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, l, d)
        attn_out = nn.Dense(self.dim, dtype=self.dtype, name="attn_out")(ctx)
        x = nn.LayerNorm(
            epsilon=self.eps, dtype=self.dtype, name="attn_ln"
        )(x + attn_out)
        h = nn.Dense(self.intermediate, dtype=self.dtype, name="ffn_in")(x)
        h = nn.gelu(h, approximate=False)  # BERT uses exact (erf) GELU
        h = nn.Dense(self.dim, dtype=self.dtype, name="ffn_out")(h)
        x = nn.LayerNorm(
            epsilon=self.eps, dtype=self.dtype, name="ffn_ln"
        )(x + h)
        return x


class BertEncoder(nn.Module):
    """HF `BertModel`-equivalent trunk with sentence-transformers pooling:
    masked mean over token states, then L2 normalization."""

    vocab_size: int
    dim: int
    depth: int
    heads: int
    intermediate: int
    max_len: int = 512
    type_vocab_size: int = 2
    eps: float = 1e-12
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, ids, mask):
        x = nn.Embed(
            self.vocab_size, self.dim, dtype=self.dtype,
            name="word_embeddings",
        )(ids)
        x = x + nn.Embed(
            self.max_len, self.dim, dtype=self.dtype,
            name="position_embeddings",
        )(jnp.arange(ids.shape[1])[None, :])
        x = x + nn.Embed(
            self.type_vocab_size, self.dim, dtype=self.dtype,
            name="token_type_embeddings",
        )(jnp.zeros_like(ids))
        x = nn.LayerNorm(epsilon=self.eps, dtype=self.dtype, name="emb_ln")(x)
        bias = (1.0 - mask[:, None, None, :]).astype(self.dtype) * jnp.asarray(
            -1e9, self.dtype
        )
        for i in range(self.depth):
            x = BertLayer(
                dim=self.dim,
                heads=self.heads,
                intermediate=self.intermediate,
                eps=self.eps,
                dtype=self.dtype,
                name=f"layer_{i}",
            )(x, bias)
        denom = jnp.maximum(mask.sum(axis=1, keepdims=True), 1e-9)
        pooled = (x * mask[:, :, None]).sum(axis=1) / denom
        pooled = pooled.astype(jnp.float32)
        return pooled / (
            jnp.linalg.norm(pooled, axis=-1, keepdims=True) + 1e-12
        )


# --- checkpoint loading -----------------------------------------------------


def _find_model_dir(name_or_path: str) -> str | None:
    """Resolve a model id to a local directory: a plain path, or the HF
    cache layout (~/.cache/huggingface/hub/models--org--name/snapshots/*)."""
    if os.path.isdir(name_or_path):
        return name_or_path
    cache = os.environ.get(
        "HF_HOME", os.path.expanduser("~/.cache/huggingface")
    )
    slug = "models--" + name_or_path.replace("/", "--")
    root = os.path.join(cache, "hub", slug)
    snaps = os.path.join(root, "snapshots")
    if os.path.isdir(snaps):
        # prefer the snapshot the refs/main file points at (the current
        # one); commit-hash names carry no order, so fall back to mtime
        candidates: list[str] = []
        ref_file = os.path.join(root, "refs", "main")
        if os.path.isfile(ref_file):
            with open(ref_file) as f:
                candidates.append(f.read().strip())
        candidates += sorted(
            os.listdir(snaps),
            key=lambda s: os.path.getmtime(os.path.join(snaps, s)),
            reverse=True,
        )
        for snap in candidates:
            d = os.path.join(snaps, snap)
            if os.path.exists(os.path.join(d, "model.safetensors")):
                return d
    return None


def _hf_key(tensors: dict, *names: str) -> np.ndarray:
    """Fetch an HF tensor tolerating the optional 'bert.' prefix."""
    for n in names:
        for cand in (n, "bert." + n):
            if cand in tensors:
                return np.asarray(tensors[cand])
    raise KeyError(names[0])


def load_bert_checkpoint(
    model_dir: str, dtype: Any = jnp.float32
) -> tuple[BertEncoder, dict]:
    """Read config.json + model.safetensors from `model_dir` and return the
    flax module + parameter pytree (HF torch [out,in] Linear weights are
    transposed into flax [in,out] kernels)."""
    from safetensors.numpy import load_file

    with open(os.path.join(model_dir, "config.json")) as f:
        cfg = json.load(f)
    tensors = load_file(os.path.join(model_dir, "model.safetensors"))

    model = BertEncoder(
        vocab_size=cfg["vocab_size"],
        dim=cfg["hidden_size"],
        depth=cfg["num_hidden_layers"],
        heads=cfg["num_attention_heads"],
        intermediate=cfg["intermediate_size"],
        max_len=cfg.get("max_position_embeddings", 512),
        type_vocab_size=cfg.get("type_vocab_size", 2),
        eps=cfg.get("layer_norm_eps", 1e-12),
        dtype=dtype,
    )

    def dense(prefix: str) -> dict:
        return {
            "kernel": _hf_key(tensors, prefix + ".weight").T,
            "bias": _hf_key(tensors, prefix + ".bias"),
        }

    def ln(prefix: str) -> dict:
        return {
            "scale": _hf_key(tensors, prefix + ".weight"),
            "bias": _hf_key(tensors, prefix + ".bias"),
        }

    params: dict[str, Any] = {
        "word_embeddings": {
            "embedding": _hf_key(tensors, "embeddings.word_embeddings.weight")
        },
        "position_embeddings": {
            "embedding": _hf_key(
                tensors, "embeddings.position_embeddings.weight"
            )
        },
        "token_type_embeddings": {
            "embedding": _hf_key(
                tensors, "embeddings.token_type_embeddings.weight"
            )
        },
        "emb_ln": ln("embeddings.LayerNorm"),
    }
    for i in range(cfg["num_hidden_layers"]):
        p = f"encoder.layer.{i}"
        params[f"layer_{i}"] = {
            "query": dense(p + ".attention.self.query"),
            "key": dense(p + ".attention.self.key"),
            "value": dense(p + ".attention.self.value"),
            "attn_out": dense(p + ".attention.output.dense"),
            "attn_ln": ln(p + ".attention.output.LayerNorm"),
            "ffn_in": dense(p + ".intermediate.dense"),
            "ffn_out": dense(p + ".output.dense"),
            "ffn_ln": ln(p + ".output.LayerNorm"),
        }

    # validate against the module's own expected tree (catches mapping bugs)
    ref = jax.eval_shape(
        lambda: model.init(
            jax.random.PRNGKey(0),
            jnp.zeros((1, 8), jnp.int32),
            jnp.ones((1, 8), jnp.float32),
        )
    )["params"]
    flat_ref = jax.tree_util.tree_leaves_with_path(ref)
    built = {"params": params}
    for path, leaf in flat_ref:
        node: Any = built["params"]
        for key in path:
            node = node[key.key]
        if tuple(node.shape) != tuple(leaf.shape):
            raise ValueError(
                f"shape mismatch at {path}: checkpoint "
                f"{tuple(node.shape)} vs model {tuple(leaf.shape)}"
            )
    return model, jax.tree.map(jnp.asarray, built)
