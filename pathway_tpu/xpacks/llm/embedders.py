"""Embedders (reference: xpacks/llm/embedders.py — BaseEmbedder:64,
OpenAIEmbedder:85, LiteLLMEmbedder:180, SentenceTransformerEmbedder:270,
GeminiEmbedder:330).

The local embedder is TPU-native: a flax encoder jitted per pad-bucket
(`pathway_tpu/xpacks/llm/_encoder.py`), fed whole ticks at once through the
engine's batched-UDF path — this is the BASELINE.md "embed docs/sec/chip"
configuration. API embedders (OpenAI/LiteLLM/Gemini) keep the reference
surface and degrade with a clear error when the client lib / network is
unavailable."""

from __future__ import annotations

import asyncio
from typing import Any, Sequence

import numpy as np

from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals.udfs import UDF


class BaseEmbedder(UDF):
    """UDF str -> np.ndarray; also callable on expressions."""

    def get_embedding_dimension(self, **kwargs) -> int:
        out = self.func("pathway", **kwargs)  # type: ignore[misc]
        if asyncio.iscoroutine(out):
            out = asyncio.run(out)
        return len(out)

    def __call__(self, input: Any, **kwargs: Any) -> expr_mod.ColumnExpression:
        return super().__call__(input, **kwargs)


class SentenceTransformerEmbedder(BaseEmbedder):
    """Local embedder on TPU
    (reference name: xpacks/llm/embedders.py:270 — there torch
    sentence-transformers; here the flax encoder; pass a model name of a
    locally-cached HF tokenizer to reuse its vocab, otherwise a hashing
    tokenizer is used)."""

    def __init__(
        self,
        model: str = "pathway-tpu/minilm-384",
        call_kwargs: dict = {},
        device: str = "tpu",
        *,
        dim: int = 384,
        depth: int = 6,
        heads: int = 6,
        max_len: int = 512,
        mesh: Any = None,
        batch_size: int = 1024,
        **init_kwargs,
    ):
        import os

        from pathway_tpu.xpacks.llm._bert import _find_model_dir
        from pathway_tpu.xpacks.llm._encoder import EncoderRuntime
        from pathway_tpu.xpacks.llm._tokenizer import (
            HashingTokenizer,
            HFTokenizerAdapter,
            WordPieceTokenizer,
        )

        # resolve a pretrained checkpoint: local dir or HF cache; the
        # random-init flax trunk + hashing tokenizer remain the offline
        # fallback (reference loads sentence-transformers checkpoints,
        # embedders.py:270)
        model_dir = _find_model_dir(model)
        model_path = None
        if model_dir is not None and os.path.exists(
            os.path.join(model_dir, "model.safetensors")
        ):
            model_path = model_dir
        # tokenizer priority: exact HF implementation when importable →
        # our WordPiece (BertTokenizer-parity, dependency-free) → hashing
        self.tokenizer: Any = None
        for candidate in ([model_dir] if model_dir else []) + [model]:
            try:
                self.tokenizer = HFTokenizerAdapter(candidate)
                break
            except Exception:
                pass
        vocab_txt = (
            os.path.join(model_dir, "vocab.txt") if model_dir else None
        )
        if (
            self.tokenizer is None
            and vocab_txt
            and os.path.exists(vocab_txt)
        ):
            lowercase = True
            tok_cfg = os.path.join(model_dir, "tokenizer_config.json")
            if os.path.exists(tok_cfg):
                import json

                with open(tok_cfg) as f:
                    lowercase = bool(
                        json.load(f).get("do_lower_case", True)
                    )
            self.tokenizer = WordPieceTokenizer(
                vocab_txt, lowercase=lowercase
            )
        if self.tokenizer is None:
            self.tokenizer = HashingTokenizer()
        vocab_size = self.tokenizer.vocab_size
        if model_path is not None and isinstance(
            self.tokenizer, HashingTokenizer
        ):
            # hash-bucket ids are unrelated to the checkpoint's vocabulary
            # — pretrained weights would emit noise; use the random trunk
            import logging

            logging.getLogger("pathway_tpu").warning(
                "checkpoint %s has weights but no usable tokenizer "
                "(vocab.txt missing); falling back to the random-init "
                "encoder",
                model,
            )
            model_path = None
        self.runtime = EncoderRuntime(
            vocab_size=vocab_size,
            dim=dim,
            depth=depth,
            heads=heads,
            max_len=max_len,
            mesh=mesh,
            model_path=model_path,
        )
        self.model = model
        self.kwargs = call_kwargs
        # Flight Recorder: embed batch latency + the BASELINE.md
        # docs/sec/chip figure, measured where the work happens instead of
        # reconstructed by bench.py from the outside
        from pathway_tpu.observability import REGISTRY

        m_batch_seconds = REGISTRY.histogram(
            "pathway_embed_batch_seconds",
            "embedder batch latency (tokenize + device forward)",
            labelnames=("model",),
        ).labels(model)
        m_docs = REGISTRY.counter(
            "pathway_embed_docs_total",
            "documents embedded",
            labelnames=("model",),
        ).labels(model)
        m_rate = REGISTRY.gauge(
            "pathway_embed_docs_per_sec_per_chip",
            "throughput of the most recent embed batch, per local device",
            labelnames=("model",),
        ).labels(model)
        chips: list[int] = []  # resolved after the first forward
        from pathway_tpu.observability.tracing import get_tracer
        from pathway_tpu.serving.metrics import occupancy_histogram
        from pathway_tpu.xpacks.llm._encoder import _bucket_batch

        m_occupancy = occupancy_histogram()
        _tracer = get_tracer()

        def embed_batch(texts: Sequence[str]) -> list[np.ndarray]:
            import time as _time

            # Trace Weaver: one child span per device batch (nested under
            # the operator span of the tick that carried these rows)
            with _tracer.span(
                "embed.batch", model=model, docs=len(texts)
            ) as sp:
                t0 = _time.perf_counter()
                ids, mask = self.tokenizer.encode_batch(
                    # runtime.max_len is clamped to the checkpoint's
                    # position table; exceeding it would silently clamp
                    # position ids
                    [str(t) for t in texts], self.runtime.max_len
                )
                out = self.runtime.forward_ids(ids, mask)
                dt = _time.perf_counter() - t0
            m_batch_seconds.observe(dt, exemplar=sp.trace_id)
            m_docs.inc(len(texts))
            # Surge Gate ladder visibility: how well realized batches
            # fill the encoder's pad bucket (the shape XLA compiled for)
            pad_bucket = _bucket_batch(len(texts))
            m_occupancy.labels("embed", str(pad_bucket)).observe(
                min(1.0, len(texts) / pad_bucket)
            )
            if not chips:
                # forward_ids just used the backend, so counting devices
                # cannot trigger a fresh (possibly hanging) backend init
                try:
                    import jax

                    chips.append(max(1, jax.local_device_count()))
                except Exception:
                    chips.append(1)
            if dt > 0:
                m_rate.set(len(texts) / dt / chips[0])
            return [out[i] for i in range(len(texts))]

        self._embed_batch = embed_batch
        super().__init__(
            return_type=np.ndarray, max_batch_size=batch_size, deterministic=True
        )
        self._prepare(self._single)
        self._batched = True
        # batched path: fn receives a list of texts
        self._fn = embed_batch

    def _single(self, text: str) -> np.ndarray:
        return self._embed_batch([text])[0]

    @property
    def func(self):
        return self._single

    def get_embedding_dimension(self, **kwargs) -> int:
        return self.runtime.dim


class _ApiEmbedder(BaseEmbedder):
    """Shared plumbing for API-backed embedders."""

    def __init__(self, capacity=None, retry_strategy=None, cache_strategy=None, **kwargs):
        self._api_kwargs = kwargs
        super().__init__(
            return_type=np.ndarray,
            cache_strategy=cache_strategy,
            retry_strategy=retry_strategy,
        )
        self._prepare(self._embed)

    async def _embed(self, input: str, **kwargs) -> np.ndarray:
        raise NotImplementedError


class OpenAIEmbedder(_ApiEmbedder):
    """(reference: embedders.py:85) — requires the `openai` package +
    network access."""

    def __init__(self, model: str = "text-embedding-3-small", **kwargs):
        self.model = model
        super().__init__(**kwargs)

    async def _embed(self, input: str, **kwargs) -> np.ndarray:
        try:
            import openai  # type: ignore[import-not-found]
        except ImportError as exc:
            raise ImportError(
                "OpenAIEmbedder requires the `openai` package; use "
                "SentenceTransformerEmbedder for on-TPU embedding"
            ) from exc
        client = openai.AsyncOpenAI(**self._api_kwargs)
        ret = await client.embeddings.create(
            input=[input or "."], model=kwargs.get("model", self.model)
        )
        return np.array(ret.data[0].embedding)


class LiteLLMEmbedder(_ApiEmbedder):
    """(reference: embedders.py:180)"""

    def __init__(self, model: str = "", **kwargs):
        self.model = model
        super().__init__(**kwargs)

    async def _embed(self, input: str, **kwargs) -> np.ndarray:
        try:
            import litellm  # type: ignore[import-not-found]
        except ImportError as exc:
            raise ImportError("LiteLLMEmbedder requires `litellm`") from exc
        ret = await litellm.aembedding(
            input=[input or "."], model=kwargs.get("model", self.model)
        )
        return np.array(ret.data[0]["embedding"])


class GeminiEmbedder(_ApiEmbedder):
    """(reference: embedders.py:330)"""

    def __init__(self, model: str = "models/embedding-001", **kwargs):
        self.model = model
        super().__init__(**kwargs)

    async def _embed(self, input: str, **kwargs) -> np.ndarray:
        try:
            import google.generativeai as genai  # type: ignore[import-not-found]
        except ImportError as exc:
            raise ImportError("GeminiEmbedder requires `google-generativeai`") from exc
        ret = genai.embed_content(
            model=kwargs.get("model", self.model), content=input or "."
        )
        return np.array(ret["embedding"])


class OpenAIEmbedderWithDimensions(OpenAIEmbedder):
    pass
