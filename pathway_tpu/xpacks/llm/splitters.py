"""Text splitters (reference: xpacks/llm/splitters.py —
TokenCountSplitter:99, RecursiveSplitter, NullSplitter).

Splitters are UDFs str -> list[tuple[str, dict]] (chunk, metadata)."""

from __future__ import annotations

import re
from typing import Any

from pathway_tpu.internals.json import Json
from pathway_tpu.internals.udfs import UDF


class BaseSplitter(UDF):
    def __init__(self, **kwargs):
        super().__init__(return_type=list, **kwargs)
        self._prepare(self.split)

    def split(self, text: str, **kwargs) -> list[tuple[str, dict]]:
        raise NotImplementedError

    @property
    def func(self):
        return self.split


class NullSplitter(BaseSplitter):
    """No-op splitter (reference: splitters.py NullSplitter)."""

    def split(self, text: str, **kwargs) -> list[tuple[str, dict]]:
        return [(text, {})]


class TokenCountSplitter(BaseSplitter):
    """Split into chunks of [min_tokens, max_tokens] tokens, preferring
    sentence/punctuation boundaries (reference: splitters.py:99)."""

    def __init__(
        self,
        min_tokens: int = 50,
        max_tokens: int = 500,
        encoding_name: str = "cl100k_base",
        **kwargs,
    ):
        self.min_tokens = min_tokens
        self.max_tokens = max_tokens
        self.encoding_name = encoding_name
        super().__init__(**kwargs)

    def _tokens(self, text: str) -> list[str]:
        return re.findall(r"\S+|\n", text)

    def split(self, text: str, **kwargs) -> list[tuple[str, dict]]:
        if not text:
            return []
        tokens = self._tokens(str(text))
        chunks: list[tuple[str, dict]] = []
        start = 0
        n = len(tokens)
        while start < n:
            end = min(start + self.max_tokens, n)
            # prefer to end at sentence punctuation past min_tokens
            best = end
            if end < n:
                for j in range(end - 1, start + self.min_tokens - 1, -1):
                    if re.search(r"[.!?]$", tokens[j]):
                        best = j + 1
                        break
            chunk = " ".join(t for t in tokens[start:best] if t != "\n")
            if chunk.strip():
                chunks.append((chunk, {}))
            start = best
        return chunks


class RecursiveSplitter(BaseSplitter):
    """Recursively split on separators until chunks fit
    (reference: splitters.py RecursiveSplitter — langchain-style)."""

    def __init__(
        self,
        chunk_size: int = 500,
        chunk_overlap: int = 0,
        separators: list[str] | None = None,
        encoding_name: str = "cl100k_base",
        model_name: str | None = None,
        **kwargs,
    ):
        self.chunk_size = chunk_size
        self.chunk_overlap = chunk_overlap
        self.separators = separators or ["\n\n", "\n", ". ", " "]
        super().__init__(**kwargs)

    def _size(self, text: str) -> int:
        return len(text.split())

    def _split_rec(self, text: str, seps: list[str]) -> list[str]:
        if self._size(text) <= self.chunk_size or not seps:
            return [text]
        sep, rest = seps[0], seps[1:]
        parts = text.split(sep)
        out: list[str] = []
        cur = ""
        for part in parts:
            candidate = (cur + sep + part) if cur else part
            if self._size(candidate) <= self.chunk_size:
                cur = candidate
            else:
                if cur:
                    out.append(cur)
                if self._size(part) > self.chunk_size:
                    out.extend(self._split_rec(part, rest))
                    cur = ""
                else:
                    cur = part
        if cur:
            out.append(cur)
        return out

    def split(self, text: str, **kwargs) -> list[tuple[str, dict]]:
        if not text:
            return []
        return [
            (c, {}) for c in self._split_rec(str(text), self.separators) if c.strip()
        ]
