"""Chat/LLM wrappers (reference: xpacks/llm/llms.py — BaseChat:40,
OpenAIChat:97, LiteLLMChat:320, HFPipelineChat:445, CohereChat:547).

API chats keep the reference surface (gated on their client libs);
`HFPipelineChat` runs a locally-cached transformers pipeline. `EchoChat` is
the deterministic offline model used in tests."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals.udfs import UDF


def prompt_chat_single_qa(question: str) -> tuple:
    return ({"role": "system", "content": question},)


_UDF_SETTING_NAMES = (
    "return_type",
    "deterministic",
    "propagate_none",
    "executor",
    "cache_strategy",
    "retry_strategy",
    "timeout",
    "max_batch_size",
)

# the OpenAI chat-completion parameter surface (reference consults
# litellm.get_supported_openai_params; that lib is absent here, so the
# public parameter list is tabled)
_OPENAI_CALL_ARGS = {
    "temperature", "top_p", "max_tokens", "max_completion_tokens", "n",
    "stop", "presence_penalty", "frequency_penalty", "logit_bias",
    "logprobs", "top_logprobs", "seed", "response_format", "stream",
    "stream_options", "tools", "tool_choice", "user", "parallel_tool_calls",
}

# provider prefix -> args NOT accepted (litellm-style routing)
_PROVIDER_UNSUPPORTED = {
    "cohere": {"stream_options", "response_format", "logit_bias"},
}


class BaseChat(UDF):
    def __init__(self, **kwargs):
        settings = {
            k: v for k, v in kwargs.items() if k in _UDF_SETTING_NAMES
        }
        # remaining kwargs are API parameters, exposed as `.kwargs`
        # (reference: BaseChat keeps non-None model kwargs)
        self.kwargs = {
            k: v
            for k, v in kwargs.items()
            if k not in _UDF_SETTING_NAMES and v is not None
        }
        super().__init__(return_type=str, **settings)
        if not hasattr(self, "__wrapped__"):
            # subclasses may define __wrapped__ directly (the reference's
            # BaseChat contract, used by test mocks); _accept is the
            # default body
            self._prepare(self._accept)

    @property
    def executor(self):
        if self._executor is not None:
            return self._executor
        from pathway_tpu.internals.udfs import AutoExecutor

        return AutoExecutor()

    @property
    def cache_strategy(self):
        return self._cache_strategy

    def _accepts_call_arg(self, arg_name: str) -> bool:
        """Whether this model accepts `arg_name` as a per-call parameter
        (reference: BaseChat._accepts_call_arg via litellm)."""
        return False

    def _accept(self, messages, **kwargs) -> str:
        raise NotImplementedError

    def __call__(self, messages: Any, **kwargs) -> expr_mod.ColumnExpression:
        return super().__call__(messages, **kwargs)


def _messages_to_prompt(messages: Any) -> str:
    from pathway_tpu.internals.json import Json

    if isinstance(messages, Json):
        messages = messages.value
    if isinstance(messages, str):
        return messages
    parts = []
    for m in messages:
        if isinstance(m, Json):
            m = m.value
        if isinstance(m, dict):
            content = m.get("content", "")
            if isinstance(content, Json):
                content = content.value
            parts.append(str(content))
        else:
            parts.append(str(m))
    return "\n".join(parts)


class EchoChat(BaseChat):
    """Deterministic offline 'LLM': echoes the tail of the prompt. Useful for
    tests and wiring checks (the reference tests use similar fakes,
    python/pathway/xpacks/llm/tests/mocks.py)."""

    def __init__(self, prefix: str = "", **kwargs):
        self.prefix = prefix
        super().__init__(**kwargs)

    def _accept(self, messages, **kwargs) -> str:
        return self.prefix + _messages_to_prompt(messages)


class OpenAIChat(BaseChat):
    """(reference: llms.py:97)"""

    def _accepts_call_arg(self, arg_name: str) -> bool:
        if self.model is None:
            return False
        return arg_name in _OPENAI_CALL_ARGS

    def __init__(self, model: str | None = "gpt-3.5-turbo", **kwargs):
        self.model = model
        self._api_kwargs = {
            k: v
            for k, v in kwargs.items()
            if k in ("api_key", "base_url", "organization")
        }
        super().__init__(**kwargs)

    async def _accept(self, messages, **kwargs) -> str:
        try:
            import openai  # type: ignore[import-not-found]
        except ImportError as exc:
            raise ImportError(
                "OpenAIChat requires the `openai` package"
            ) from exc
        client = openai.AsyncOpenAI(**self._api_kwargs)
        msgs = (
            [{"role": "user", "content": messages}]
            if isinstance(messages, str)
            else list(messages)
        )
        params = {
            k: v
            for k, v in {**self.kwargs, **kwargs}.items()
            if self._accepts_call_arg(k) and v is not None
        }
        ret = await client.chat.completions.create(
            messages=msgs, model=kwargs.get("model", self.model), **params
        )
        return ret.choices[0].message.content


class LiteLLMChat(BaseChat):
    def _accepts_call_arg(self, arg_name: str) -> bool:
        if self.model is None:
            return False
        provider = self.model.split("/", 1)[0] if "/" in self.model else None
        unsupported = _PROVIDER_UNSUPPORTED.get(provider, set())
        return arg_name in _OPENAI_CALL_ARGS and arg_name not in unsupported

    """(reference: llms.py:320)"""

    def __init__(self, model: str | None = None, **kwargs):
        self.model = model
        super().__init__()

    async def _accept(self, messages, **kwargs) -> str:
        try:
            import litellm  # type: ignore[import-not-found]
        except ImportError as exc:
            raise ImportError("LiteLLMChat requires `litellm`") from exc
        msgs = (
            [{"role": "user", "content": messages}]
            if isinstance(messages, str)
            else list(messages)
        )
        params = {
            k: v
            for k, v in {**self.kwargs, **kwargs}.items()
            if self._accepts_call_arg(k) and v is not None
        }
        ret = await litellm.acompletion(
            model=kwargs.get("model", self.model), messages=msgs, **params
        )
        return ret["choices"][0]["message"]["content"]


class HFPipelineChat(BaseChat):
    """Local transformers pipeline (reference: llms.py:445). Works offline
    when the model is in the local HF cache."""

    def __init__(
        self,
        model: str | None = None,
        call_kwargs: dict = {},
        device: str = "cpu",
        **pipeline_kwargs,
    ):
        self.model = model
        self.call_kwargs = call_kwargs
        self._pipeline = None
        self._pipeline_kwargs = pipeline_kwargs
        super().__init__()

    def _get_pipeline(self):
        if self._pipeline is None:
            from transformers import pipeline

            self._pipeline = pipeline(
                "text-generation", model=self.model, **self._pipeline_kwargs
            )
        return self._pipeline

    def _accept(self, messages, **kwargs) -> str:
        pipe = self._get_pipeline()
        prompt = _messages_to_prompt(messages)
        out = pipe(prompt, **self.call_kwargs)
        text = out[0]["generated_text"]
        if isinstance(text, list):
            text = text[-1].get("content", "")
        return str(text)

    def crop_to_max_length(self, input_string: str, max_prompt_length: int = 500) -> str:
        words = str(input_string).split()
        return " ".join(words[:max_prompt_length])


class CohereChat(BaseChat):
    """(reference: llms.py:547)"""

    def __init__(self, model: str | None = "command", **kwargs):
        self.model = model
        super().__init__()

    async def _accept(self, messages, **kwargs) -> str:
        try:
            import cohere  # type: ignore[import-not-found]
        except ImportError as exc:
            raise ImportError("CohereChat requires `cohere`") from exc
        client = cohere.AsyncClient()
        ret = await client.chat(
            message=_messages_to_prompt(messages),
            model=kwargs.get("model", self.model),
        )
        return ret.text
