"""Chat/LLM wrappers (reference: xpacks/llm/llms.py — BaseChat:40,
OpenAIChat:97, LiteLLMChat:320, HFPipelineChat:445, CohereChat:547).

API chats keep the reference surface (gated on their client libs);
`HFPipelineChat` runs a locally-cached transformers pipeline. `EchoChat` is
the deterministic offline model used in tests."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals.udfs import UDF


def prompt_chat_single_qa(question: str) -> tuple:
    return ({"role": "system", "content": question},)


class BaseChat(UDF):
    def __init__(self, **kwargs):
        super().__init__(return_type=str, **kwargs)
        self._prepare(self._accept)

    def _accept(self, messages, **kwargs) -> str:
        raise NotImplementedError

    def __call__(self, messages: Any, **kwargs) -> expr_mod.ColumnExpression:
        return super().__call__(messages, **kwargs)


def _messages_to_prompt(messages: Any) -> str:
    if isinstance(messages, str):
        return messages
    parts = []
    for m in messages:
        if isinstance(m, dict):
            parts.append(str(m.get("content", "")))
        else:
            parts.append(str(m))
    return "\n".join(parts)


class EchoChat(BaseChat):
    """Deterministic offline 'LLM': echoes the tail of the prompt. Useful for
    tests and wiring checks (the reference tests use similar fakes,
    python/pathway/xpacks/llm/tests/mocks.py)."""

    def __init__(self, prefix: str = "", **kwargs):
        self.prefix = prefix
        super().__init__(**kwargs)

    def _accept(self, messages, **kwargs) -> str:
        return self.prefix + _messages_to_prompt(messages)


class OpenAIChat(BaseChat):
    """(reference: llms.py:97)"""

    def __init__(self, model: str | None = "gpt-3.5-turbo", **kwargs):
        self.model = model
        self._api_kwargs = {
            k: v
            for k, v in kwargs.items()
            if k in ("api_key", "base_url", "organization")
        }
        super().__init__(
            cache_strategy=kwargs.get("cache_strategy"),
            retry_strategy=kwargs.get("retry_strategy"),
        )

    async def _accept(self, messages, **kwargs) -> str:
        try:
            import openai  # type: ignore[import-not-found]
        except ImportError as exc:
            raise ImportError(
                "OpenAIChat requires the `openai` package"
            ) from exc
        client = openai.AsyncOpenAI(**self._api_kwargs)
        msgs = (
            [{"role": "user", "content": messages}]
            if isinstance(messages, str)
            else list(messages)
        )
        ret = await client.chat.completions.create(
            messages=msgs, model=kwargs.get("model", self.model)
        )
        return ret.choices[0].message.content


class LiteLLMChat(BaseChat):
    """(reference: llms.py:320)"""

    def __init__(self, model: str | None = None, **kwargs):
        self.model = model
        super().__init__()

    async def _accept(self, messages, **kwargs) -> str:
        try:
            import litellm  # type: ignore[import-not-found]
        except ImportError as exc:
            raise ImportError("LiteLLMChat requires `litellm`") from exc
        msgs = (
            [{"role": "user", "content": messages}]
            if isinstance(messages, str)
            else list(messages)
        )
        ret = await litellm.acompletion(
            model=kwargs.get("model", self.model), messages=msgs
        )
        return ret["choices"][0]["message"]["content"]


class HFPipelineChat(BaseChat):
    """Local transformers pipeline (reference: llms.py:445). Works offline
    when the model is in the local HF cache."""

    def __init__(
        self,
        model: str | None = None,
        call_kwargs: dict = {},
        device: str = "cpu",
        **pipeline_kwargs,
    ):
        self.model = model
        self.call_kwargs = call_kwargs
        self._pipeline = None
        self._pipeline_kwargs = pipeline_kwargs
        super().__init__()

    def _get_pipeline(self):
        if self._pipeline is None:
            from transformers import pipeline

            self._pipeline = pipeline(
                "text-generation", model=self.model, **self._pipeline_kwargs
            )
        return self._pipeline

    def _accept(self, messages, **kwargs) -> str:
        pipe = self._get_pipeline()
        prompt = _messages_to_prompt(messages)
        out = pipe(prompt, **self.call_kwargs)
        text = out[0]["generated_text"]
        if isinstance(text, list):
            text = text[-1].get("content", "")
        return str(text)

    def crop_to_max_length(self, input_string: str, max_prompt_length: int = 500) -> str:
        words = str(input_string).split()
        return " ".join(words[:max_prompt_length])


class CohereChat(BaseChat):
    """(reference: llms.py:547)"""

    def __init__(self, model: str | None = "command", **kwargs):
        self.model = model
        super().__init__()

    async def _accept(self, messages, **kwargs) -> str:
        try:
            import cohere  # type: ignore[import-not-found]
        except ImportError as exc:
            raise ImportError("CohereChat requires `cohere`") from exc
        client = cohere.AsyncClient()
        ret = await client.chat(
            message=_messages_to_prompt(messages),
            model=kwargs.get("model", self.model),
        )
        return ret.text
