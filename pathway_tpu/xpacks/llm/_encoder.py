"""Flax sentence-encoder running on TPU — the local-embedder engine behind
SentenceTransformerEmbedder / CrossEncoderReranker
(reference: xpacks/llm/embedders.py:270, rerankers.py:159 — there, torch
sentence-transformers on CPU/GPU; here a bf16 flax transformer jitted per
pad-bucket, batch-sharded over the mesh 'data' axis for multi-chip DP).
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn


class TransformerEncoder(nn.Module):
    vocab_size: int = 30522
    dim: int = 384
    depth: int = 6
    heads: int = 6
    mlp_ratio: int = 4
    max_len: int = 512
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, ids, mask):
        x = nn.Embed(self.vocab_size, self.dim, dtype=self.dtype)(ids)
        pos = nn.Embed(self.max_len, self.dim, dtype=self.dtype)(
            jnp.arange(ids.shape[1])[None, :]
        )
        x = x + pos
        attn_mask = mask[:, None, None, :] * mask[:, None, :, None]
        for _ in range(self.depth):
            h = nn.LayerNorm(dtype=self.dtype)(x)
            h = nn.MultiHeadDotProductAttention(
                num_heads=self.heads,
                dtype=self.dtype,
                deterministic=True,
            )(h, h, mask=attn_mask.astype(bool))
            x = x + h
            h = nn.LayerNorm(dtype=self.dtype)(x)
            h = nn.Dense(self.dim * self.mlp_ratio, dtype=self.dtype)(h)
            h = nn.gelu(h)
            h = nn.Dense(self.dim, dtype=self.dtype)(h)
            x = x + h
        x = nn.LayerNorm(dtype=self.dtype)(x)
        # masked mean pool + L2 normalize (sentence-transformers convention)
        denom = jnp.maximum(mask.sum(axis=1, keepdims=True), 1.0)
        pooled = (x * mask[:, :, None]).sum(axis=1) / denom
        pooled = pooled.astype(jnp.float32)
        return pooled / (jnp.linalg.norm(pooled, axis=-1, keepdims=True) + 1e-12)


class CrossEncoderHead(nn.Module):
    """Encoder + scalar relevance head (query/doc pair scoring)."""

    encoder: TransformerEncoder

    @nn.compact
    def __call__(self, ids, mask):
        emb = self.encoder(ids, mask)
        return nn.Dense(1, dtype=jnp.float32)(emb)[:, 0]


def _bucket_batch(n: int) -> int:
    b = 8
    while b < n:
        b *= 2
    return b


class EncoderRuntime:
    """Owns params + jitted forwards; pads batches to power-of-two buckets so
    each (batch, seq) bucket compiles once. Optional mesh → batch-dim DP
    sharding (multi-chip embedding throughput)."""

    def __init__(
        self,
        vocab_size: int = 30522,
        dim: int = 384,
        depth: int = 6,
        heads: int = 6,
        max_len: int = 512,
        seed: int = 0,
        mesh: Any = None,
        axis: str = "data",
        cross_encoder: bool = False,
        model_path: str | None = None,
        param_dtype: Any = None,
    ):
        self.max_len = max_len
        self.pretrained = False
        if model_path is not None and not cross_encoder:
            # pretrained BERT/MiniLM checkpoint: exact post-LN architecture
            # + safetensors weights (_bert.py); replaces the random-init
            # trunk entirely
            from pathway_tpu.xpacks.llm._bert import load_bert_checkpoint

            self.model, self.params = load_bert_checkpoint(
                model_path,
                dtype=param_dtype if param_dtype is not None else jnp.float32,
            )
            self.dim = self.model.dim
            self.max_len = min(max_len, self.model.max_len)
            self.pretrained = True
        else:
            enc = TransformerEncoder(
                vocab_size=vocab_size,
                dim=dim,
                depth=depth,
                heads=heads,
                max_len=max_len,
            )
            self.model = CrossEncoderHead(enc) if cross_encoder else enc
            self.dim = dim
            rng = jax.random.PRNGKey(seed)
            ids0 = jnp.zeros((1, 16), jnp.int32)
            mask0 = jnp.ones((1, 16), jnp.float32)
            self.params = self.model.init(rng, ids0, mask0)
        self.mesh = mesh
        self.axis = axis
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            # replicate params; shard activations on batch
            self.params = jax.device_put(
                self.params, NamedSharding(mesh, P())
            )
            self._in_shard = NamedSharding(mesh, P(axis, None))
        else:
            self._in_shard = None

        @jax.jit
        def fwd(params, ids, mask):
            return self.model.apply(params, ids, mask)

        self._fwd = fwd

    def forward_ids(self, ids: np.ndarray, mask: np.ndarray) -> np.ndarray:
        n = ids.shape[0]
        bucket = _bucket_batch(n)
        if self.mesh is not None:
            n_dev = self.mesh.shape[self.axis]
            bucket = max(bucket, n_dev)
            bucket = ((bucket + n_dev - 1) // n_dev) * n_dev
        if bucket != n:
            ids = np.pad(ids, ((0, bucket - n), (0, 0)))
            mask = np.pad(mask, ((0, bucket - n), (0, 0)))
        ids_j = jnp.asarray(ids)
        mask_j = jnp.asarray(mask)
        if self._in_shard is not None:
            ids_j = jax.device_put(ids_j, self._in_shard)
            mask_j = jax.device_put(mask_j, self._in_shard)
        out = self._fwd(self.params, ids_j, mask_j)
        return np.asarray(out)[:n]
